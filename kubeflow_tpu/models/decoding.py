"""Autoregressive decoding with a KV cache — the inference half of the
notebook model stack.

The reference ships no model code (SURVEY.md §2); training throughput is
covered by the benches, and this adds the generation path a notebook user
expects from the same checkpoint:

- prefill: one forward over the whole prompt fills every layer's KV cache
  (``TransformerConfig(decode=True)``; grouped KV stays grouped — GQA
  divides cache memory by H/KV);
- decode: ``lax.while_loop`` over single-token steps, cache threaded as a
  jit-carried pytree — one compiled program, no per-step retrace;
- sampling: greedy (temperature 0), temperature, and top-k, all shape-static;
- early exit: generation stops when every row has emitted ``eos_id`` (the
  emitted suffix stays padded with eos).

Decode attention: with attention_impl='flash' the single-token step runs the
flash-decode Pallas kernel (``ops/flash_decode.py``) — KV-cache traffic
scales with the live context via scalar-prefetch block skipping, not
max_seq_len. Other impls use the cache-masked einsum path, where XLA fuses
mask+softmax+pv into the (full-cache) read.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM


def decode_config(cfg: TransformerConfig) -> TransformerConfig:
    """The decoding twin of a training config (same params, cache on).

    'flash' survives into decode — single-token steps then use the
    flash-decode kernel (``ops/flash_decode.py``), whose KV traffic scales
    with the live context instead of max_seq_len. Every other impl falls
    back to the cache-masked einsum path ('xla'): at S=1 there is nothing
    for the *training* kernels to tile."""
    impl = "flash" if cfg.attention_impl == "flash" else "xla"
    return dataclasses.replace(
        cfg, decode=True, remat=False, attention_impl=impl, mesh=None
    )


def _sample(logits, temperature, top_k, rng):
    """logits [B, V] f32 → token ids [B].

    With top-k, sampling happens INSIDE the candidate set: categorical over
    the k kept logits + index gather. Distribution-identical to masking the
    vocab to -inf and sampling [B, V] (renormalization over the same k
    values), but the RNG draws B*k gumbels instead of B*V — measured 0.26
    ms/step of threefry at V=32k, the single largest non-matmul cost of the
    decode loop."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k is not None:
        vals, idx = jax.lax.top_k(logits, top_k)        # [B, k] each
        choice = jax.random.categorical(rng, vals / temperature, axis=-1)
        return jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(
            jnp.int32
        )
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(
        jnp.int32
    )


@partial(jax.jit, static_argnames=("model",))
def prefill(model: TransformerLM, params, prompt: jnp.ndarray):
    """Fill the KV cache from a prompt [B, P]; returns (cache, last_logits).

    The serving split: prefill once (with attention_impl='flash' this runs
    the training flash kernel — linear memory in P, no [S, S] score
    materialization), then drive ``decode_steps``/``generate`` from the
    returned cache. ``generate`` composes these two for the simple case.
    """
    B, P = prompt.shape
    logits, state = model.apply(
        {"params": params}, prompt, positions=jnp.arange(P),
        mutable=["cache"],
    )
    return state["cache"], logits[:, -1].astype(jnp.float32)


@partial(
    jax.jit,
    static_argnames=("model", "n", "temperature", "top_k"),
    donate_argnums=(2,),  # the cache updates in place: at 16k context it is
    # ~6.5 GB — holding input AND output copies would double that per call
)
def decode_steps(
    model: TransformerLM,
    params,
    cache,
    first_token: jnp.ndarray,
    start_pos: int | jnp.ndarray,
    *,
    n: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: Any = None,
):
    """Run exactly ``n`` single-token decode steps from ``start_pos``.

    ``first_token`` [B] is the token at position ``start_pos`` (e.g. sampled
    from prefill's last_logits). Returns (tokens [B, n], cache) — one
    compiled ``fori_loop`` program, no per-step retrace and no early-exit
    data-dependence, which also makes it the honest steady-state decode
    benchmark body (benchmarks/decode_bench.py --long): prefill time never
    amortizes into the per-step rate.
    """
    cfg = model.cfg
    B = first_token.shape[0]
    if rng is None:
        rng = jax.random.PRNGKey(0)
    tokens0 = jnp.zeros((B, n), jnp.int32)
    start = jnp.asarray(start_pos, jnp.int32)

    def body(i, carry):
        tokens, cache, cur, rng = carry
        pos = start + i
        logits, new_state = model.apply(
            {"params": params, "cache": cache}, cur[:, None],
            positions=pos[None], mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(
            logits[:, -1].astype(jnp.float32), temperature, top_k, sub
        )
        tokens = lax.dynamic_update_slice(tokens, nxt[:, None], (0, i))
        return tokens, new_state["cache"], nxt, rng

    tokens, cache, _, _ = lax.fori_loop(
        0, n, body, (tokens0, cache, first_token.astype(jnp.int32), rng)
    )
    return tokens, cache


@partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k", "eos_id"),
)
def generate(
    model: TransformerLM,
    params,
    prompt: jnp.ndarray,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    eos_id: int | None = None,
    rng: Any = None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt`` [B, P].

    ``model`` must be built with ``decode_config(cfg)``; params are the
    training params unchanged. Returns [B, P + max_new_tokens] tokens.
    """
    cfg = model.cfg
    B, P = prompt.shape
    if P + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt {P} + new {max_new_tokens} exceeds the cache "
            f"(max_seq_len={cfg.max_seq_len})"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # prefill: full prompt in one pass, cache initialized + filled
    cache0, last_logits = prefill(model, params, prompt)
    rng, prefill_rng = jax.random.split(rng)  # keys are single-use
    next_tok = _sample(last_logits, temperature, top_k, prefill_rng)

    # pad with eos (not 0 — a real token id) so rows that finish early
    # carry an eos suffix, per the module contract
    pad_id = eos_id if eos_id is not None else 0
    tokens0 = jnp.concatenate(
        [prompt, jnp.full((B, max_new_tokens), pad_id, prompt.dtype)], axis=1
    )
    tokens0 = lax.dynamic_update_slice(tokens0, next_tok[:, None], (0, P))
    done0 = (
        next_tok == eos_id if eos_id is not None
        else jnp.zeros((B,), jnp.bool_)
    )

    def cond(carry):
        step, _, _, done, _ = carry
        return jnp.logical_and(step < max_new_tokens - 1, ~jnp.all(done))

    def body(carry):
        step, tokens, cache, done, rng = carry
        pos = P + step
        cur = lax.dynamic_slice(tokens, (0, pos), (B, 1))
        logits, new_state = model.apply(
            {"params": params, "cache": cache}, cur,
            positions=pos[None], mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(
            logits[:, -1].astype(jnp.float32), temperature, top_k, sub
        )
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        tokens = lax.dynamic_update_slice(tokens, nxt[:, None], (0, pos + 1))
        return step + 1, tokens, new_state["cache"], done, rng

    if max_new_tokens > 1:
        _, tokens, _, _, _ = lax.while_loop(
            cond,
            body,
            (jnp.asarray(0, jnp.int32), tokens0, cache0, done0, rng),
        )
    else:
        tokens = tokens0
    return tokens
