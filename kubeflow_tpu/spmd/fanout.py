"""Controller-side SPMD fan-out surface + the per-seed gang-identity audit.

The notebook controller renders one StatefulSet per slice with
``replicas == num_hosts`` and stamps each pod template with the derived-mesh
annotation below; admission (``webhooks/tpu_env.py``) then gives each pod its
worker identity. This module owns the pieces both sides and the soaks share:

- :data:`SPMD_MESH_ANNOTATION` — the canonical derived-mesh JSON on every
  slice pod, rendered from the *bound placement* when one exists (the
  placement is the authority once bound; its cuboid may be a rotation of the
  requested topology) and from the requested topology otherwise;
- :func:`audit_spmd` — the per-seed soak invariant: every multi-host gang's
  pods carry a consistent worker-id assignment (``TPU_WORKER_ID`` == pod
  ordinal, global process ids gap-free when the gang is fully Running, one
  coordinator, one mesh), and the headless rendezvous Service exists with
  ``publishNotReadyAddresses`` wherever a gang has pods up. Runs against the
  fake cluster's store alone, so it holds in the chaos soak (no scheduler —
  env checks still bind) and the sessions soak (placements present —
  placement agreement also binds).
"""
from __future__ import annotations

import json

from kubeflow_tpu.spmd import bootstrap as spmd_bootstrap
from kubeflow_tpu.spmd import mesh as spmd_mesh

# Canonical derived-mesh JSON (sort_keys) on every slice pod template.
# Owned here; the controller stamps it, the JWA detail view and the soak
# audit re-derive and compare (TPU004: the key lives in exactly one place).
SPMD_MESH_ANNOTATION = "tpu.kubeflow.org/spmd-mesh"

__all__ = ["SPMD_MESH_ANNOTATION", "mesh_annotation_value", "audit_spmd"]


def mesh_annotation_value(
    topo, num_slices: int = 1, placement_slice: dict | None = None
) -> str:
    """The annotation payload for one slice's pod template.

    Prefers the bound placement's cuboid (what the gang actually sits on);
    falls back to the requested topology for unscheduled/adopted gangs.
    """
    if placement_slice is not None:
        try:
            dm = spmd_mesh.from_placement_slice(placement_slice, num_slices)
            return json.dumps(dm.to_dict(), sort_keys=True)
        except ValueError:
            pass  # malformed slice: fall back to the spec'd topology
    dm = spmd_mesh.from_topology(topo, num_slices)
    return json.dumps(dm.to_dict(), sort_keys=True)


def _pod_env(pod: dict) -> dict[str, str]:
    """First workload container's env as a dict (sidecars excluded)."""
    for c in pod.get("spec", {}).get("containers", []):
        if c.get("name") in ("istio-proxy",):
            continue
        return {
            e["name"]: e.get("value", "")
            for e in c.get("env", [])
            if "name" in e
        }
    return {}


def _ordinal(pod_name: str) -> int | None:
    base, _, tail = pod_name.rpartition("-")
    return int(tail) if base and tail.isdigit() else None


def audit_spmd(cluster, *, where: str = "") -> list[str]:
    """Per-seed invariant: gang worker identity is consistent and gap-free.

    For every TPU notebook that fans out (multi-host or multislice):

    1. every existing slice pod's injected env parses (``read_env``) and its
       ``TPU_WORKER_ID`` equals its StatefulSet ordinal — a restarted pod
       re-admitted under the same name MUST come back as the same worker;
    2. slice/process arithmetic matches the CR: ``JAX_NUM_PROCESSES`` =
       hosts x slices, ``JAX_PROCESS_ID`` = slice_id x hosts + ordinal;
    3. all pods of the gang agree on one coordinator address;
    4. when the gang is fully Running, global process ids are exactly
       ``0..hosts*slices-1`` — no gaps, no collisions (churn mid-kill leaves
       gaps legitimately; a *complete* Running gang may not);
    5. a bound gang's replica count and mesh annotation derive from its
       placement cuboid (hosts agreement — the placement is the authority);
    6. any gang with pods up has its headless rendezvous Service, with
       ``publishNotReadyAddresses`` (worker 0 must resolve before Ready).

    Pure store read; deterministic; returns violations (empty = clean).
    """
    from kubeflow_tpu import scheduler as sched
    from kubeflow_tpu.api import types as api
    from kubeflow_tpu.runtime import objects as ko
    from kubeflow_tpu.tpu import topology as tputopo

    out: list[str] = []
    for nb in cluster.list("Notebook"):
        try:
            topo = api.notebook_topology(nb)
        except ValueError:
            continue  # invalid spec is admission's problem, not fan-out's
        if topo is None:
            continue
        num_slices = api.notebook_num_slices(nb)
        if not topo.is_multi_host and num_slices <= 1:
            continue  # single-host single-slice: localhost identity, no gang
        name, ns = ko.name(nb), ko.namespace(nb)
        key = f"{ns}/{name}"
        placement = sched.placement_of(nb)
        p_slices = (placement or {}).get("slices") or []
        hosts = topo.num_hosts
        total = hosts * num_slices

        contexts: list[spmd_bootstrap.SpmdContext] = []
        pods_seen = 0
        running = 0
        replicas_up = 0
        for j in range(num_slices):
            sts_name = name if num_slices == 1 else f"{name}-s{j}"
            sts = cluster.try_get("StatefulSet", sts_name, ns)
            if sts is None:
                continue
            replicas = (sts.get("spec") or {}).get("replicas", 0)
            replicas_up += replicas
            if replicas and j < len(p_slices):
                try:
                    dm = spmd_mesh.from_placement_slice(p_slices[j], num_slices)
                except ValueError:
                    dm = None
                if dm is not None and replicas != dm.num_hosts:
                    out.append(
                        f"{where}: {key}/s{j}: {replicas} replicas but the "
                        f"bound placement cuboid {dm.topology} has "
                        f"{dm.num_hosts} hosts"
                    )
            template_anns = (
                (sts.get("spec") or {})
                .get("template", {})
                .get("metadata", {})
                .get("annotations", {})
            )
            mesh_ann = template_anns.get(SPMD_MESH_ANNOTATION)
            if replicas and not mesh_ann:
                out.append(
                    f"{where}: {key}/s{j}: slice pod template lacks the "
                    f"derived-mesh annotation {SPMD_MESH_ANNOTATION}"
                )
            elif mesh_ann:
                try:
                    got = json.loads(mesh_ann)
                except ValueError:
                    got = None
                if not isinstance(got, dict) or (
                    got.get("numHosts"),
                    got.get("numSlices"),
                    got.get("chipsPerHost"),
                ) != (hosts, num_slices, topo.chips_per_host):
                    out.append(
                        f"{where}: {key}/s{j}: derived-mesh annotation "
                        f"disagrees with the gang's shape "
                        f"({hosts} hosts x {num_slices} slices)"
                    )

            for pod in sorted(
                cluster.list(
                    "Pod", ns, selector={"matchLabels": {"statefulset": sts_name}}
                ),
                key=ko.name,
            ):
                pods_seen += 1
                pod_name = ko.name(pod)
                if pod.get("status", {}).get("phase") == "Running":
                    running += 1
                ordinal = _ordinal(pod_name)
                if ordinal is None:
                    out.append(
                        f"{where}: {key}: pod {pod_name} has no ordinal"
                    )
                    continue
                env = _pod_env(pod)
                try:
                    ctx = spmd_bootstrap.read_env(env)
                except spmd_bootstrap.SpmdEnvError as e:
                    out.append(
                        f"{where}: {key}: pod {pod_name} env violates the "
                        f"SPMD contract: {e}"
                    )
                    continue
                if ctx is None:
                    out.append(
                        f"{where}: {key}: pod {pod_name} of a multi-host "
                        f"gang has no injected TPU_WORKER_ID"
                    )
                    continue
                contexts.append(ctx)
                if ctx.worker_id != ordinal:
                    out.append(
                        f"{where}: {key}: pod {pod_name} ordinal {ordinal} "
                        f"but TPU_WORKER_ID={ctx.worker_id}"
                    )
                if num_slices > 1 and ctx.slice_id != j:
                    out.append(
                        f"{where}: {key}: pod {pod_name} in slice {j} but "
                        f"MEGASCALE_SLICE_ID={ctx.slice_id}"
                    )
                if ctx.num_processes != total:
                    out.append(
                        f"{where}: {key}: pod {pod_name} has "
                        f"JAX_NUM_PROCESSES={ctx.num_processes}, gang has "
                        f"{total} hosts"
                    )
                expected_pid = j * hosts + ordinal
                if ctx.process_id != expected_pid:
                    out.append(
                        f"{where}: {key}: pod {pod_name} has "
                        f"JAX_PROCESS_ID={ctx.process_id}, expected "
                        f"{expected_pid}"
                    )

        if contexts:
            for v in spmd_bootstrap.validate_gang(contexts):
                # gaps are legitimate mid-churn (a killed pod IS a gap);
                # they only indict a gang whose every pod is up and Running
                if v.startswith("worker-id assignment has gaps") and not (
                    pods_seen == total == running
                ):
                    continue
                out.append(f"{where}: {key}: {v}")
        if pods_seen == total == running and len(contexts) == total:
            pids = sorted(c.process_id for c in contexts)
            if pids != list(range(total)):
                out.append(
                    f"{where}: {key}: Running gang's process ids {pids} are "
                    f"not gap-free 0..{total - 1}"
                )

        if replicas_up or pods_seen:
            svc = cluster.try_get(
                "Service", tputopo.headless_service_name(name), ns
            )
            if svc is None:
                out.append(
                    f"{where}: {key}: multi-host gang has pods but no "
                    f"headless rendezvous Service"
                )
            else:
                spec = svc.get("spec") or {}
                if spec.get("clusterIP") != "None" or not spec.get(
                    "publishNotReadyAddresses"
                ):
                    out.append(
                        f"{where}: {key}: rendezvous Service is not headless "
                        f"+ publishNotReadyAddresses (coordinator DNS must "
                        f"resolve before readiness)"
                    )
    return out
