"""In-pod SPMD bootstrap: injected env → typed context → derived mesh.

Extends ``parallel/bootstrap.py`` (which tolerantly parses the env and joins
``jax.distributed``) with the strict, typed half the runtime contract needs:

- ``read_env(env)`` takes the environment as an *injected mapping* — unit
  tests exercise every malformed-env path without a TPU or a subprocess, and
  **resume-after-suspend is literally a re-read**: the pod a resumed gang
  gets was re-admitted against the re-bound placement, so calling
  ``read_env`` again yields the new worker identity (same rule, possibly a
  different pool's cuboid). Nothing is cached at module level.
- malformed env raises :class:`SpmdEnvError` (a ValueError) naming the exact
  variable, instead of an ``int()`` traceback five frames into user code;
- the context carries the :class:`~kubeflow_tpu.spmd.mesh.DerivedMesh` every
  host derives identically from (accelerator, topology, numSlices) alone —
  no cross-host negotiation, so a restarted worker re-derives the same mesh
  its peers already hold;
- ``validate_gang`` checks a set of contexts for the gang-level invariants
  (gap-free ids, no collisions, one coordinator) — the same predicate the
  soak audit applies to live pods (``spmd/fanout.py``).
"""
from __future__ import annotations

import dataclasses
from typing import Mapping

from kubeflow_tpu.spmd import mesh as spmd_mesh

__all__ = [
    "SpmdEnvError",
    "SpmdContext",
    "read_env",
    "validate_gang",
    "local_mesh",
]


class SpmdEnvError(ValueError):
    """The injected worker-identity env violates the admission contract.

    Raised (not returned) so a mis-injected pod fails loudly at bootstrap
    with the variable named, rather than joining the gang under a wrong
    identity and corrupting the collective.
    """


@dataclasses.dataclass(frozen=True)
class SpmdContext:
    """One host's validated SPMD identity, as admission injected it."""

    worker_id: int                    # ordinal within THIS slice
    hostnames: tuple[str, ...]        # this slice's stable DNS names
    num_processes: int                # GLOBAL (hosts x slices)
    process_id: int                   # GLOBAL (slice_id * hosts + worker_id)
    coordinator: str | None           # host:port of slice 0's host 0
    slice_id: int
    num_slices: int
    topology: str | None              # e.g. "2x2x2"
    accelerator_type: str | None      # e.g. "v4-16" (slice name)
    mesh: spmd_mesh.DerivedMesh | None   # None when topology env is absent

    @property
    def is_multi_host(self) -> bool:
        return self.num_processes > 1


def _int_env(env: Mapping[str, str], key: str, default: int | None = None) -> int:
    raw = env.get(key)
    if raw is None:
        if default is None:
            raise SpmdEnvError(f"{key} is required but missing")
        return default
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise SpmdEnvError(f"{key}={raw!r} is not an integer") from None
    return value


def _accelerator_name(slice_name: str) -> str:
    # TPU_ACCELERATOR_TYPE carries the marketing slice name ("v4-16"); the
    # generation short name is everything before the core/chip count
    return slice_name.rsplit("-", 1)[0] if "-" in slice_name else slice_name


def read_env(env: Mapping[str, str] | None = None) -> SpmdContext | None:
    """Parse + validate the injected env; None when not on a TPU slice.

    ``env`` defaults to ``os.environ`` in the pod; tests (and the resume
    path, which re-reads after the re-bound placement re-admitted the pod)
    pass an explicit mapping.
    """
    if env is None:
        import os

        env = os.environ
    if "TPU_WORKER_ID" not in env:
        return None  # not a slice pod; nothing to bootstrap

    worker_id = _int_env(env, "TPU_WORKER_ID")
    if worker_id < 0:
        raise SpmdEnvError(f"TPU_WORKER_ID={worker_id} is negative")
    hostnames = tuple(
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    )
    if hostnames and worker_id >= len(hostnames):
        raise SpmdEnvError(
            f"TPU_WORKER_ID={worker_id} out of range for "
            f"{len(hostnames)} TPU_WORKER_HOSTNAMES"
        )
    num_slices = _int_env(env, "MEGASCALE_NUM_SLICES", 1)
    slice_id = _int_env(env, "MEGASCALE_SLICE_ID", 0)
    if num_slices < 1 or not (0 <= slice_id < num_slices):
        raise SpmdEnvError(
            f"MEGASCALE_SLICE_ID={slice_id} not in [0, "
            f"{num_slices}=MEGASCALE_NUM_SLICES)"
        )

    topology = env.get("TPU_TOPOLOGY")
    accel_type = env.get("TPU_ACCELERATOR_TYPE")
    mesh = None
    if topology and accel_type:
        try:
            mesh = spmd_mesh.derive(
                _accelerator_name(accel_type), topology, num_slices
            )
        except ValueError as e:
            raise SpmdEnvError(
                f"TPU_ACCELERATOR_TYPE={accel_type!r} / "
                f"TPU_TOPOLOGY={topology!r}: {e}"
            ) from None

    default_procs = mesh.num_processes if mesh else max(1, len(hostnames))
    num_processes = _int_env(env, "JAX_NUM_PROCESSES", default_procs)
    process_id = _int_env(
        env, "JAX_PROCESS_ID",
        (mesh.num_hosts if mesh else len(hostnames) or 1) * slice_id
        + worker_id,
    )

    if mesh is not None:
        if hostnames and len(hostnames) != mesh.num_hosts:
            raise SpmdEnvError(
                f"{len(hostnames)} TPU_WORKER_HOSTNAMES for a "
                f"{mesh.num_hosts}-host {mesh.topology} slice"
            )
        if worker_id >= mesh.num_hosts:
            raise SpmdEnvError(
                f"TPU_WORKER_ID={worker_id} out of range for a "
                f"{mesh.num_hosts}-host {mesh.topology} slice"
            )
        if num_processes != mesh.num_processes:
            raise SpmdEnvError(
                f"JAX_NUM_PROCESSES={num_processes} but the "
                f"{mesh.topology} x{num_slices} placement has "
                f"{mesh.num_processes} hosts"
            )
        expected_pid = slice_id * mesh.num_hosts + worker_id
        if process_id != expected_pid:
            raise SpmdEnvError(
                f"JAX_PROCESS_ID={process_id} inconsistent with "
                f"slice {slice_id} worker {worker_id} "
                f"(expected {expected_pid})"
            )
    if not (0 <= process_id < num_processes):
        raise SpmdEnvError(
            f"JAX_PROCESS_ID={process_id} not in [0, {num_processes})"
        )

    coordinator = env.get("JAX_COORDINATOR_ADDRESS")
    if num_processes > 1 and not coordinator:
        raise SpmdEnvError(
            "multi-host slice without JAX_COORDINATOR_ADDRESS — the gang "
            "cannot rendezvous"
        )
    return SpmdContext(
        worker_id=worker_id,
        hostnames=hostnames,
        num_processes=num_processes,
        process_id=process_id,
        coordinator=coordinator,
        slice_id=slice_id,
        num_slices=num_slices,
        topology=topology,
        accelerator_type=accel_type,
        mesh=mesh,
    )


def validate_gang(contexts: list[SpmdContext]) -> list[str]:
    """Gang-level invariants over one slice-or-job's worth of contexts.

    The collision/gap predicate shared by the restart test (a restarted pod
    must come back as the SAME worker, never a duplicate of a peer) and the
    soak audit's per-pod env checks. Returns violations, empty when clean.
    """
    out: list[str] = []
    if not contexts:
        return out
    by_pid: dict[int, int] = {}
    for ctx in contexts:
        by_pid[ctx.process_id] = by_pid.get(ctx.process_id, 0) + 1
    dupes = sorted(pid for pid, n in by_pid.items() if n > 1)
    if dupes:
        out.append(f"worker-id collision: process ids {dupes} held twice")
    want = contexts[0].num_processes
    if any(c.num_processes != want for c in contexts):
        out.append(
            "hosts disagree on JAX_NUM_PROCESSES: "
            f"{sorted({c.num_processes for c in contexts})}"
        )
    elif len(contexts) == want:
        missing = sorted(set(range(want)) - set(by_pid))
        if missing:
            out.append(f"worker-id assignment has gaps: missing {missing}")
    coords = sorted({c.coordinator for c in contexts if c.coordinator})
    if len(coords) > 1:
        out.append(f"hosts disagree on the coordinator: {coords}")
    return out


def local_mesh(ctx: SpmdContext, devices=None):
    """The jax Mesh this host should build — identical on every host.

    Call after ``parallel.bootstrap.auto_initialize()`` (so ``jax.devices()``
    spans the whole gang); tests pass forced-CPU devices directly.
    """
    if ctx.mesh is None:
        raise SpmdEnvError(
            "cannot build a mesh without TPU_TOPOLOGY/TPU_ACCELERATOR_TYPE"
        )
    return spmd_mesh.build_mesh(ctx.mesh, devices)
