"""Placement cuboid → JAX mesh axes: the deterministic derivation rule.

The scheduler binds a gang to a cuboid of the pool's torus (``fleet.place_gang``
writes the slice's chip ``shape`` into the placement annotation); this module
turns that shape into the mesh every host of the gang builds identically:

    dcn    = numSlices          (cross-slice data parallelism over DCN)
    data   = num_hosts          (the host grid: shape[i] // host_block[i] —
                                 batch parallelism over per-host ICI blocks)
    model  = chips_per_host     (the intra-host sub-torus — the tightest ICI
                                 neighborhood, so model/tensor collectives
                                 never leave a host's block)

"model" here maps onto ``parallel/mesh.py``'s ``tensor`` axis (that module's
vocabulary); :meth:`DerivedMesh.to_plan` does the translation, so everything
downstream (param sharding rules, batch specs, the placement-aware device
ordering in ``create_mesh``) is reused, not reimplemented.

The rule is a *default*, not a straitjacket — a notebook can always build its
own plan — but it is the one every pod of a gang derives from nothing but its
injected env, so all hosts agree without coordination. Determinism is the
contract: same (accelerator, topology, numSlices) → same mesh, on every host,
every restart, every resume.
"""
from __future__ import annotations

import dataclasses
import math

from kubeflow_tpu.tpu.topology import SliceTopology, parse_topology

__all__ = [
    "DerivedMesh",
    "derive",
    "from_topology",
    "from_placement_slice",
    "build_mesh",
    "per_host_batch",
]


@dataclasses.dataclass(frozen=True)
class DerivedMesh:
    """The mesh every host of a gang derives from its placement, identically.

    Frozen and fully determined by (accelerator, topology, num_slices) — the
    three values admission injects — so it can be recomputed anywhere (pod,
    controller, JWA detail view, soak audit) and compared for agreement.
    """

    accelerator: str              # short name, e.g. "v4"
    topology: str                 # e.g. "4x4x4" (the slice's chip cuboid)
    shape: tuple[int, ...]        # parsed topology dims
    host_grid: tuple[int, ...]    # per-dim host counts (shape / host_block)
    num_slices: int
    num_hosts: int                # per slice
    chips_per_host: int

    @property
    def num_chips(self) -> int:
        return math.prod(self.shape)

    @property
    def num_processes(self) -> int:
        """Global jax.distributed process count (hosts x slices)."""
        return self.num_hosts * self.num_slices

    @property
    def num_devices(self) -> int:
        """Global chip count the mesh spans."""
        return self.num_chips * self.num_slices

    def axes(self) -> dict[str, int]:
        """The derived logical axes, issue vocabulary (data/model + dcn)."""
        return {
            "dcn": self.num_slices,
            "data": self.num_hosts,
            "model": self.chips_per_host,
        }

    def to_plan(self):
        """Translate into ``parallel/mesh.py`` vocabulary (model → tensor)."""
        from kubeflow_tpu.parallel import mesh as meshlib

        return meshlib.MeshPlan(
            dcn=self.num_slices, data=self.num_hosts,
            tensor=self.chips_per_host,
        )

    def to_data_plan(self):
        """The pure-data-parallel projection of the derivation.

        Batch-parallel workloads (the ResNet cell, MFU_BENCH) have no model
        axis to feed, so the intra-host block folds into ``fsdp`` instead:
        the batch then shards over every chip (``batch_spec`` covers
        dcn x data x fsdp) while params ZeRO-shard over the tightest ICI
        neighborhood. Same device order, same host-major layout — only the
        axis naming changes, so per-host batches stay contiguous per host.
        """
        from kubeflow_tpu.parallel import mesh as meshlib

        return meshlib.MeshPlan(
            dcn=self.num_slices, data=self.num_hosts,
            fsdp=self.chips_per_host,
        )

    def to_dict(self) -> dict:
        """Canonical JSON-able form — the pod annotation / JWA detail payload.

        Key order is fixed by json.dumps(sort_keys=True) at the call sites;
        equality of two dicts is the audit's mesh-agreement check.
        """
        return {
            "accelerator": self.accelerator,
            "topology": self.topology,
            "numSlices": self.num_slices,
            "numHosts": self.num_hosts,
            "chipsPerHost": self.chips_per_host,
            "axes": self.axes(),
        }


def from_topology(topo: SliceTopology, num_slices: int = 1) -> DerivedMesh:
    """Derive from a validated SliceTopology (controller/JWA side)."""
    if num_slices < 1:
        raise ValueError(f"numSlices must be >= 1; got {num_slices}")
    block = topo.accelerator.host_block
    # sub-host single-host offerings (v5e 1x1/2x2) don't tile the block;
    # their host grid is the identity
    host_grid = tuple(
        max(1, d // b) for d, b in zip(topo.shape, block)
    )
    if math.prod(host_grid) != topo.num_hosts:
        host_grid = (1,) * len(topo.shape)
    return DerivedMesh(
        accelerator=topo.accelerator.name,
        topology=topo.topology_str,
        shape=topo.shape,
        host_grid=host_grid,
        num_slices=num_slices,
        num_hosts=topo.num_hosts,
        chips_per_host=topo.chips_per_host,
    )


def derive(accelerator: str, topology: str, num_slices: int = 1) -> DerivedMesh:
    """Derive from raw CR/env strings; validation via ``parse_topology``
    (raises ValueError with the admission-grade message on bad input)."""
    return from_topology(parse_topology(accelerator, topology), num_slices)


def from_placement_slice(placement_slice: dict, num_slices: int = 1) -> DerivedMesh:
    """Derive from one bound placement slice (``fleet.place_gang`` wire form).

    The slice dict carries the *chip* cuboid the scheduler committed
    (``shape``) plus the accelerator — exactly the inputs the rule needs, so
    the controller renders fan-out for what was actually bound, not what was
    requested (they agree by construction, but the placement is the
    authority once bound).
    """
    accel = placement_slice.get("accelerator")
    shape = placement_slice.get("shape") or []
    if not accel or not shape:
        raise ValueError(
            "placement slice lacks accelerator/shape; cannot derive mesh"
        )
    return derive(str(accel), "x".join(str(int(d)) for d in shape), num_slices)


def build_mesh(dm: DerivedMesh, devices=None, *, data_parallel: bool = False):
    """Build the jax Mesh for this derivation (workload side; lazy jax).

    Orders devices by the slice's physical torus via ``create_mesh``'s
    placement-aware path so the ``model`` axis rides the intra-host block.
    Device count must equal ``dm.num_devices`` — on a real slice that is
    ``jax.devices()`` after ``jax.distributed.initialize``; tests pass a
    forced-CPU device list. ``data_parallel=True`` builds the
    :meth:`DerivedMesh.to_data_plan` projection instead (batch-parallel
    workloads with no model axis).
    """
    from kubeflow_tpu.parallel import mesh as meshlib

    plan = dm.to_data_plan() if data_parallel else dm.to_plan()
    physical = dm.shape if dm.num_slices == 1 else None
    return meshlib.create_mesh(plan, devices, physical_topology=physical)


def per_host_batch(dm: DerivedMesh, global_batch: int) -> int:
    """Topology-aware per-host batch: the global batch splits over the
    data-parallel axes (dcn x data = every host), never over model.

    Divisibility is an error, not a silent round — a batch that doesn't
    split evenly would give hosts different shapes and break SPMD.
    """
    hosts = dm.num_processes
    if global_batch < 1 or global_batch % hosts:
        raise ValueError(
            f"global batch {global_batch} does not divide over "
            f"{hosts} hosts ({dm.num_hosts} hosts x {dm.num_slices} slices)"
        )
    return global_batch // hosts
