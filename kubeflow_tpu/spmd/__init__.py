"""Multi-host SPMD notebook runtime — the workload half of the L0/L3 contract.

The scheduler half of the platform binds a gang to a torus cuboid
(``scheduler/``); this package is the other half: the notebook that lands on
that cuboid learns its own topology and turns it into a JAX mesh with zero
user configuration.

    placement cuboid ──(controller fan-out + admission env)──► pod env
    pod env ──(spmd.bootstrap.read_env)──► SpmdContext
    SpmdContext.mesh ──(spmd.mesh.build_mesh)──► jax.sharding.Mesh

- ``spmd.mesh``      deterministic cuboid-shape → mesh-axes derivation
- ``spmd.bootstrap`` in-pod env parsing with typed errors; resume re-read
- ``spmd.fanout``    controller-side derived-mesh annotation + the per-seed
                     soak audit (gap-free worker ids, coordinator agreement,
                     headless-Service rendezvous)

Everything here is deterministic and unit-testable without TPUs: mesh
derivation is pure math on validated topologies, bootstrap takes the env as
an injected mapping, and the audit reads the fake cluster's store.
"""
from kubeflow_tpu.spmd.bootstrap import SpmdContext, SpmdEnvError, read_env
from kubeflow_tpu.spmd.mesh import DerivedMesh, derive, from_placement_slice

__all__ = [
    "DerivedMesh",
    "SpmdContext",
    "SpmdEnvError",
    "derive",
    "from_placement_slice",
    "read_env",
]
