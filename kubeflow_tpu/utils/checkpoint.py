"""Sharded checkpoint/resume for notebook training state.

The reference's only persistence notion is stop/restart with durable volumes
(SURVEY.md §5 "Checkpoint / resume"); training state checkpointing does not
exist there. This module adds it TPU-natively on orbax:

- saves arrive sharded: each host writes its own param shards (no gather
  through one host's RAM — mandatory at pod-slice scale);
- restore takes the target mesh/shardings, so a notebook culled on a 4x4x4
  slice resumes onto the re-formed mesh (same topology guaranteed by the
  reconciler) or even a *different* plan (orbax reshards);
- the culling convention: workspace PVC (or GCS path) + ``latest_step`` make
  stop → cull → restart lossless for long-running cells.
"""
from __future__ import annotations

import logging
from typing import Any

import jax

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin policy layer over orbax's CheckpointManager."""

    def __init__(self, directory: str, *, max_to_keep: int = 3, save_interval_steps: int = 1) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async sharded save; returns True if a save was started."""
        saved = self.manager.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        return bool(saved)

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        """Restore into the sharding/structure of ``state_like`` (an abstract
        or concrete state pytree on the *current* mesh)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            state_like,
        )
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        """Retained steps, ascending (fallback order for torn-step recovery)."""
        return sorted(self.manager.all_steps())

    def wait_until_finished(self) -> None:
        """Block until every async save is durable on disk/GCS. ``save()``
        returning only means the save was *started*: orbax writes shards in
        the background, and a gang torn down before they land leaves a torn
        step behind (recoverable, but the work since the previous step is
        gone). The suspend barrier (``sessions/``) calls this before
        reporting snapshot-committed — an ack must never point at bytes
        that are still in flight."""
        self.manager.wait_until_finished()

    def wait(self) -> None:
        """Alias kept for existing callers (cull paths)."""
        self.wait_until_finished()

    def close(self) -> None:
        # draining first makes close() safe to call on the teardown path:
        # closing with an async save in flight would abandon it
        self.manager.wait_until_finished()
        self.manager.close()


def snapshot_for_suspend(manager: CheckpointManager, step: int, state: Any) -> int | None:
    """The suspend barrier's save: force a checkpoint and BLOCK until it is
    durable, then report the step that may be acked as snapshot-committed.

    The in-pod session agent calls this when the platform requests a
    suspend (``sessions/controller.py``); only after it returns may the
    agent answer the snapshot RPC — the control plane's commit record must
    never be written for an async save that a pod teardown could still
    tear. Returns the committed step (None if nothing was saved)."""
    manager.save(step, state, force=True)
    manager.wait_until_finished()
    return manager.latest_step()


def snapshot_for_precopy(manager: CheckpointManager) -> int | None:
    """The suspend PRE-COPY pass's read: the newest step that is ALREADY
    durable, without forcing a save and without blocking the kernel.

    The sessions controller streams a best-effort chunk pass while the
    session is still running (docs/sessions.md "snapshot fast path"); the
    session extension serves that first snapshot request from here — the
    user's cells keep executing, nothing stops the world. Drift between
    this step and the final forced ``snapshot_for_suspend`` is exactly the
    residual delta the barrier's save then writes. Returns None when no
    step has landed yet (the pre-copy is skipped, never waited on)."""
    return manager.latest_step()


def resume_or_init(directory: str, init_fn, *args, **kwargs):
    """The notebook-friendly entrypoint: restore the latest checkpoint if one
    exists, else build fresh state. Combined with the platform's stop/restart
    (same topology re-formed by the reconciler), this makes culling lossless:

        state = resume_or_init("/home/jovyan/ckpt", bundle.init, rng, batch)

    A corrupt or partial step is treated as absent, not fatal: a notebook
    culled (or its host drained) mid-save leaves a torn latest step behind,
    and the very next cell execution calls this — raising here would brick
    resume exactly when it matters. Fall back step-by-step to the newest
    restorable checkpoint, or fresh init when none survives.
    """
    state = init_fn(*args, **kwargs)
    mgr = CheckpointManager(directory)
    try:
        for step in reversed(mgr.all_steps()):
            try:
                return mgr.restore(state, step)
            except Exception as exc:
                log.warning(
                    "checkpoint step %d under %s is torn/corrupt (%s); "
                    "falling back to the previous step",
                    step, directory, exc,
                )
    finally:
        mgr.close()
    return state
