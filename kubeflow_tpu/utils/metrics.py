"""Prometheus-style metrics with text exposition.

The platform's observability contract mirrors the reference's
(``notebook-controller/pkg/metrics/metrics.go:13-99``) and extends it with
controller-runtime's standard families (reconcile duration/outcome, workqueue
queue-wait, apiserver request latency — docs/observability.md): counters,
gauges, and cumulative-bucket histograms exposed in Prometheus text format at
``/metrics`` by the web layer. Implemented standalone (no prometheus_client
in the image) — exposition format is stable and tiny.

Label discipline: a family's label names are fixed — at registration when
``labelnames`` is passed, else frozen by the first observation. A later call
with a different label set raises ``ValueError`` naming both sets (the
silent-drop/KeyError failure mode this replaces corrupted series invisibly).
"""
from __future__ import annotations

import bisect
import threading
from typing import Mapping, Sequence

# prometheus DefBuckets: tuned for request/reconcile latencies in seconds
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def escape_label_value(v: str) -> str:
    """Exposition-format label escaping: backslash, quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def escape_help(v: str) -> str:
    """HELP text escaping: backslash and newline (quotes are legal there)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Sample value formatting: integers render exactly (counters must not
    round through %g's 6 significant digits), floats keep full precision."""
    if v == int(v) and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(
        self,
        name: str,
        help_: str,
        kind: str,
        labelnames: Sequence[str] | None = None,
        buckets: Sequence[float] | None = None,
    ) -> None:
        self.name = name
        self.help = help_
        self.kind = kind
        # counters/gauges: key -> float; histograms: key -> [bucket counts...,
        # +Inf count, sum] (one list per label set, len(buckets) + 2)
        self._values: dict[tuple, object] = {}
        # None = not yet frozen; () = frozen unlabeled
        self._label_names: tuple[str, ...] | None = (
            tuple(labelnames) if labelnames is not None else None
        )
        if kind == "histogram":
            bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
            if not bs:
                raise ValueError(f"histogram {name!r} needs at least one bucket")
            self.buckets: tuple[float, ...] = bs
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, str]) -> tuple:
        names = tuple(sorted(labels))
        if self._label_names is None:
            # first observation freezes the schema (registration may have
            # already fixed it via labelnames)
            self._label_names = names
        elif names != tuple(sorted(self._label_names)):
            raise ValueError(
                f"metric {self.name!r} has labels "
                f"{sorted(self._label_names)}, got {sorted(names)} — a "
                f"family's label names are fixed at registration/first use"
            )
        return tuple(labels[n] for n in self._label_names)

    # ------------------------------------------------------ counters/gauges

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if self.kind == "histogram":
            raise TypeError(f"{self.name}: use observe() on histograms")
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        if self.kind == "histogram":
            raise TypeError(f"{self.name}: use observe() on histograms")
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            k = self._key(labels)
            if self.kind == "histogram":
                cells = self._values.get(k)
                # observation count (cells hold per-bucket counts + sum)
                return float(builtins_sum(cells[:-1])) if cells else 0.0
            return self._values.get(k, 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def remove(self, **labels: str) -> None:
        """Drop ONE label set's series (a pool/family that left the fleet
        must stop exposing its last value — a stale gauge reads as live
        state). Scoped removal, unlike clear(): under sharding several
        collectors share one family and may only retire their own series."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    # ----------------------------------------------------------- histograms

    def observe(self, value: float, **labels: str) -> None:
        if self.kind != "histogram":
            raise TypeError(f"{self.name}: observe() is histogram-only")
        with self._lock:
            k = self._key(labels)
            cells = self._values.get(k)
            if cells is None:
                cells = [0] * (len(self.buckets) + 1) + [0.0]
                self._values[k] = cells
            i = bisect.bisect_left(self.buckets, value)
            cells[i] += 1  # non-cumulative per-bucket; cumulated at expose
            cells[-1] += value

    def sum(self, **labels: str) -> float:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return float(cells[-1]) if cells else 0.0

    def count(self, **labels: str) -> int:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return int(builtins_sum(cells[:-1])) if cells else 0

    def quantile(self, q: float, **labels: str) -> float:
        """Prometheus histogram_quantile: linear interpolation inside the
        bucket the q-th observation falls in (the +Inf bucket clamps to the
        largest finite bound — same convention)."""
        with self._lock:
            cells = self._values.get(self._key(labels))
            if not cells:
                return 0.0
            counts = cells[:-1]
            total = builtins_sum(counts)
            if total == 0:
                return 0.0
            rank = q * total
            seen = 0.0
            for i, c in enumerate(counts):
                seen += c
                if seen >= rank:
                    if i >= len(self.buckets):  # +Inf bucket
                        return self.buckets[-1]
                    lo = self.buckets[i - 1] if i else 0.0
                    hi = self.buckets[i]
                    if c == 0:
                        return hi
                    return lo + (hi - lo) * (rank - (seen - c)) / c
            return self.buckets[-1]

    # ------------------------------------------------------------ exposition

    def samples(self) -> list[dict]:
        """Public sample view: [{"labels": {...}, "value": v}, ...] (for
        histograms, value is the observation count and "sum" rides along)."""
        with self._lock:
            names = self._label_names or ()
            out = []
            for k, v in sorted(self._values.items()):
                labels = dict(zip(names, k))
                if self.kind == "histogram":
                    out.append({
                        "labels": labels,
                        "value": builtins_sum(v[:-1]),
                        "sum": v[-1],
                    })
                else:
                    out.append({"labels": labels, "value": v})
            return out

    def _labelstr(self, key: tuple, extra: str = "") -> str:
        names = self._label_names or ()
        parts = [
            f'{n}="{escape_label_value(v)}"' for n, v in zip(names, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            labeled = bool(self._label_names)
            if not self._values:
                # an empty UNLABELED counter/gauge still exposes its zero (a
                # scraper can distinguish "0" from "missing"); a labeled or
                # histogram family with no series exposes none — the old
                # bogus unlabeled `name 0` sample was invalid exposition
                if not labeled and self.kind != "histogram":
                    lines.append(f"{self.name} 0")
                return "\n".join(lines)
            for key, val in sorted(self._values.items()):
                if self.kind == "histogram":
                    cum = 0
                    for i, bound in enumerate(self.buckets):
                        cum += val[i]
                        le = 'le="' + format_value(bound) + '"'
                        lines.append(
                            f"{self.name}_bucket{self._labelstr(key, le)} {cum}"
                        )
                    cum += val[len(self.buckets)]
                    inf = 'le="+Inf"'
                    lines.append(
                        f"{self.name}_bucket{self._labelstr(key, inf)} {cum}"
                    )
                    lines.append(
                        f"{self.name}_sum{self._labelstr(key)} "
                        f"{format_value(val[-1])}"
                    )
                    lines.append(
                        f"{self.name}_count{self._labelstr(key)} {cum}"
                    )
                else:
                    lines.append(
                        f"{self.name}{self._labelstr(key)} "
                        f"{format_value(val)}"
                    )
        return "\n".join(lines)


builtins_sum = sum  # _Metric defines .sum(); keep the builtin reachable


class _Bound:
    """A metric with preset labels (the ``shard`` label under control-plane
    sharding): every observation merges the bound labels in, so N shards
    sharing one registry write disjoint series instead of colliding on one
    unlabeled sample (gauges would last-writer-win, counters double-count).
    Call sites keep the unlabeled API — ``metrics.queue_retries.inc()``
    works identically whether the family is shard-labeled or not."""

    __slots__ = ("_metric", "_labels")

    def __init__(self, metric: _Metric, labels: Mapping[str, str]) -> None:
        self._metric = metric
        self._labels = dict(labels)

    def _merge(self, labels: Mapping[str, str]) -> dict:
        merged = dict(self._labels)
        merged.update(labels)
        return merged

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        self._metric.inc(amount, **self._merge(labels))

    def set(self, value: float, **labels: str) -> None:
        self._metric.set(value, **self._merge(labels))

    def get(self, **labels: str) -> float:
        return self._metric.get(**self._merge(labels))

    def observe(self, value: float, **labels: str) -> None:
        self._metric.observe(value, **self._merge(labels))

    def sum(self, **labels: str) -> float:
        return self._metric.sum(**self._merge(labels))

    def count(self, **labels: str) -> int:
        return self._metric.count(**self._merge(labels))

    def quantile(self, q: float, **labels: str) -> float:
        return self._metric.quantile(q, **self._merge(labels))

    def remove(self, **labels: str) -> None:
        self._metric.remove(**self._merge(labels))

    @property
    def name(self) -> str:
        return self._metric.name

    @property
    def kind(self) -> str:
        return self._metric.kind

    def samples(self) -> list[dict]:
        return self._metric.samples()


class _ShardScope:
    """Registration helper for collectors that grow a ``shard`` label when
    sharded (ControlPlaneMetrics, SchedulerMetrics). With ``shard=None`` it
    is a transparent pass-through — the single-shard exposition is byte-
    identical to the pre-sharding one. With a shard id, every family is
    registered with ``shard`` appended to its label names and every returned
    handle is bound to that shard's value. Mixing sharded and unsharded
    instances on one registry raises (the family's label schema is frozen),
    which is the configuration error it looks like."""

    def __init__(self, registry: "Registry", shard: str | None) -> None:
        self.registry = registry
        self.shard = shard

    def _wrap(self, metric: _Metric):
        if self.shard is None:
            return metric
        return _Bound(metric, {"shard": self.shard})

    def _names(self, labelnames: Sequence[str] | None) -> Sequence[str] | None:
        if self.shard is None:
            return labelnames
        return tuple(labelnames or ()) + ("shard",)

    def counter(self, name, help_, labelnames=None):
        return self._wrap(self.registry.counter(name, help_, self._names(labelnames)))

    def gauge(self, name, help_, labelnames=None):
        return self._wrap(self.registry.gauge(name, help_, self._names(labelnames)))

    def histogram(self, name, help_, labelnames=None, buckets=None):
        return self._wrap(
            self.registry.histogram(name, help_, self._names(labelnames), buckets)
        )


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []
        self._pre_expose: list = []

    def pre_expose(self, fn) -> None:
        """Register a live-scrape hook run before each exposition (the
        reference's custom-collector idiom, metrics.go:82-99)."""
        self._pre_expose.append(fn)

    def counter(
        self, name: str, help_: str, labelnames: Sequence[str] | None = None
    ) -> _Metric:
        return self._add(_Metric(name, help_, "counter", labelnames))

    def gauge(
        self, name: str, help_: str, labelnames: Sequence[str] | None = None
    ) -> _Metric:
        return self._add(_Metric(name, help_, "gauge", labelnames))

    def histogram(
        self,
        name: str,
        help_: str,
        labelnames: Sequence[str] | None = None,
        buckets: Sequence[float] | None = None,
    ) -> _Metric:
        return self._add(_Metric(name, help_, "histogram", labelnames, buckets))

    def _add(self, m: _Metric) -> _Metric:
        # same-name registration returns the existing family (two Apps
        # sharing one registry must not emit duplicate metric families —
        # strict Prometheus scrapers reject that exposition)
        for existing in self._metrics:
            if existing.name == m.name:
                if existing.kind != m.kind:
                    raise ValueError(
                        f"metric {m.name!r} already registered as "
                        f"{existing.kind}, not {m.kind}"
                    )
                if m._label_names is not None:
                    if existing._label_names is None:
                        # schema not yet frozen: the declaring registration
                        # fixes it
                        existing._label_names = m._label_names
                    elif tuple(existing._label_names) != tuple(
                        m._label_names
                    ):
                        # a sharded and an unsharded collector (or any two
                        # conflicting schemas) on one registry is a wiring
                        # error — fail HERE, at registration, not at some
                        # arbitrary later observation (the delayed error
                        # let a soak run a crash-every-cycle scheduler
                        # while looking green)
                        raise ValueError(
                            f"metric {m.name!r} already registered with "
                            f"labels {sorted(existing._label_names)}, got "
                            f"{sorted(m._label_names)} — one registry, one "
                            f"schema per family"
                        )
                return existing
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        for fn in self._pre_expose:
            fn()
        return "\n".join(m.expose() for m in self._metrics) + "\n"


class NotebookMetrics:
    """Reference collector parity (metrics.go:13-64): running gauge scraped
    live from StatefulSets, create/fail/cull counters."""

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.running = self.registry.gauge(
            "notebook_running", "Current running notebooks in the cluster",
            labelnames=("namespace",),
        )
        self.tpu_chips_in_use = self.registry.gauge(
            "notebook_tpu_chips_in_use", "TPU chips held by running notebooks",
            labelnames=("namespace",),
        )
        self.created = self.registry.counter(
            "notebook_create_total", "Total notebooks created",
            labelnames=("namespace",),
        )
        self.create_failed = self.registry.counter(
            "notebook_create_failed_total", "Total notebook create failures",
            labelnames=("namespace",),
        )
        self.culled = self.registry.counter(
            "notebook_cull_total", "Total notebooks culled",
            labelnames=("namespace",),
        )

    def observe_notebooks(self, cluster) -> None:
        by_ns: dict[str, int] = {}
        chips: dict[str, int] = {}
        for sts in cluster.list("StatefulSet"):
            ns = sts["metadata"].get("namespace", "")
            ready = sts.get("status", {}).get("readyReplicas", 0)
            if ready:
                by_ns[ns] = by_ns.get(ns, 0) + 1
                tmpl = sts["spec"]["template"]["spec"]
                for c in tmpl.get("containers", []):
                    n = int(
                        c.get("resources", {})
                        .get("limits", {})
                        .get("google.com/tpu", 0)
                    )
                    chips[ns] = chips.get(ns, 0) + n * ready
        self.running.clear()
        self.tpu_chips_in_use.clear()
        for ns, n in by_ns.items():
            self.running.set(n, namespace=ns)
        for ns, n in chips.items():
            self.tpu_chips_in_use.set(n, namespace=ns)

    def notebook_created(self, namespace: str) -> None:
        self.created.inc(namespace=namespace)

    def notebook_culled(self, namespace: str) -> None:
        self.culled.inc(namespace=namespace)


class WebAppMetrics:
    """Read-path observability for the web apps (docs/observability.md):
    per-route request latency, HTTP-revalidation and gzip counters from the
    shared App plumbing, and the ReadCache's health — hit/fallback ratio,
    live object counts, positive-confirmation age (staleness), and re-list
    churn. One instance rides each app's registry (``App.web_metrics``); a
    shared registry (standalone, controller+webapp colocations) dedups the
    families, so two apps never emit duplicates."""

    # in-proc serve path: 304s are ~100µs, cached 200s low ms, fallback
    # full lists can reach hundreds of ms at fleet scale
    REQUEST_BUCKETS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5,
    )

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.request_seconds = self.registry.histogram(
            "webapp_request_seconds",
            "Web-app request latency by route pattern and response status",
            labelnames=("route", "status"),
            buckets=self.REQUEST_BUCKETS,
        )
        self.not_modified = self.registry.counter(
            "webapp_responses_not_modified_total",
            "Responses served as 304 via If-None-Match (no serialization)",
            labelnames=("route",),
        )
        self.gzipped = self.registry.counter(
            "webapp_responses_gzipped_total",
            "Responses compressed for an Accept-Encoding: gzip client",
        )
        self.cache_reads = self.registry.counter(
            "webapp_cache_reads_total",
            "ReadCache reads by kind and source (cache|fallback)",
            labelnames=("kind", "source"),
        )
        self.cache_objects = self.registry.gauge(
            "webapp_cache_objects",
            "Objects currently held in the ReadCache, per kind",
            labelnames=("kind",),
        )
        self.cache_staleness = self.registry.gauge(
            "webapp_cache_staleness_seconds",
            "Age of the last positive freshness confirmation (watch prime, "
            "rv poll, or re-list), per kind — refreshed at confirm cadence",
            labelnames=("kind",),
        )
        self.cache_relists = self.registry.counter(
            "webapp_cache_relists_total",
            "Full re-lists the ReadCache ran (cold start, rv divergence, "
            "or staleness recovery), per kind",
            labelnames=("kind",),
        )
        self.cache_watch_events = self.registry.counter(
            "webapp_cache_watch_events_total",
            "Watch events ingested into the ReadCache, per kind",
            labelnames=("kind",),
        )

    def observe_request(self, route: str, status: int, seconds: float) -> None:
        self.request_seconds.observe(
            max(0.0, seconds), route=route, status=str(status)
        )


class ControlPlaneMetrics:
    """controller-runtime's standard families for the reconcile hot path
    (docs/observability.md): reconcile duration + outcome per kind
    (``manager.py``), workqueue queue-wait and retry churn, and per-verb
    apiserver request latency (``kubeclient.py``). One instance is shared by
    the manager and the API client so a single /metrics scrape answers
    "where did the reconcile's time go".

    ``shard`` (control-plane sharding, runtime/sharding.py): N shard
    managers share one registry — each instance passes its shard id so the
    families carry a ``shard`` label and per-shard series never collide or
    double-count. ``shard=None`` (the unsharded default) registers the
    exact pre-sharding schema."""

    # reconcile/queue-wait spans ms..minutes; apiserver requests ms..seconds
    RECONCILE_BUCKETS = (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
    )

    def __init__(
        self, registry: Registry | None = None, *, shard: str | None = None
    ) -> None:
        self.registry = registry or Registry()
        scoped = _ShardScope(self.registry, shard)
        self.reconcile_duration = scoped.histogram(
            "controller_reconcile_duration_seconds",
            "Time spent in reconcile(), per primary kind",
            labelnames=("kind",),
            buckets=self.RECONCILE_BUCKETS,
        )
        self.reconcile_total = scoped.counter(
            "controller_reconcile_total",
            "Reconcile outcomes per kind (success|error|requeue)",
            labelnames=("kind", "outcome"),
        )
        self.queue_wait = scoped.histogram(
            "workqueue_queue_wait_seconds",
            "Time a key waited in the workqueue before a worker picked it up",
            buckets=self.RECONCILE_BUCKETS,
        )
        self.queue_retries = scoped.counter(
            "workqueue_retries_total",
            "Keys re-enqueued through per-key error backoff",
        )
        self.api_latency = scoped.histogram(
            "apiserver_request_duration_seconds",
            "Kubernetes API request latency, per verb",
            labelnames=("verb",),
        )
        self.api_retries = scoped.counter(
            "apiserver_request_retries_total",
            "Transient-error retries inside one logical API request, per verb",
            labelnames=("verb",),
        )

    def observe_reconcile(self, kind: str, seconds: float, outcome: str) -> None:
        self.reconcile_duration.observe(seconds, kind=kind)
        self.reconcile_total.inc(kind=kind, outcome=outcome)

    def observe_queue_wait(self, seconds: float) -> None:
        self.queue_wait.observe(seconds)


class SessionMetrics:
    """Session-lifecycle observability (docs/sessions.md): suspend-barrier
    latency (request→commit), time-to-resume (resume start→restore
    complete), and the failure/force counters an operator tunes the force
    deadline against. Shares a registry with the other collectors so one
    /metrics scrape carries the whole story; ``SESSIONS_BENCH`` reads its
    p50/p99 straight off these histograms.
    """

    # suspend: dominated by the snapshot write (seconds); resume: dominated
    # by the queue wait + gang start (seconds to hours)
    SUSPEND_BUCKETS = (0.5, 1.0, 5.0, 15.0, 60.0, 120.0, 300.0, 900.0)
    RESUME_BUCKETS = (1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0, 14400.0)
    # residual bytes span "nothing changed" (first bucket) to a full
    # re-copy of a large session
    RESIDUAL_BUCKETS = (
        1024.0, 65536.0, 1048576.0, 16777216.0, 268435456.0, 1073741824.0,
    )

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.suspends = self.registry.counter(
            "session_suspend_total",
            "Sessions suspended with a committed snapshot, per reason",
            labelnames=("reason",),
        )
        self.resumes = self.registry.counter(
            "session_resume_total",
            "Sessions resumed (from a snapshot or cold)",
            labelnames=("from_snapshot",),
        )
        self.snapshot_failures = self.registry.counter(
            "session_snapshot_failed_total",
            "Snapshot attempts that failed (store write or verify)",
        )
        self.force_suspends = self.registry.counter(
            "session_force_suspend_total",
            "Suspends that hit the force deadline without a snapshot",
        )
        self.suspended = self.registry.gauge(
            "session_suspended",
            "Sessions currently suspended (snapshot held, no pods)",
        )
        self.suspend_latency = self.registry.histogram(
            "session_suspend_seconds",
            "Suspend-request→snapshot-commit latency (the barrier's hold time)",
            buckets=self.SUSPEND_BUCKETS,
        )
        self.time_to_resume = self.registry.histogram(
            "session_resume_seconds",
            "Resume-start→restore-complete latency (includes any queue wait)",
            buckets=self.RESUME_BUCKETS,
        )
        # snapshot fast path (docs/sessions.md): logical vs physical bytes
        # is the dedup story — physical ≪ logical means warm suspends are
        # writing only dirty chunks, the whole point of the chunk store
        self.snapshot_logical_bytes = self.registry.counter(
            "session_snapshot_logical_bytes_total",
            "Payload bytes committed through snapshot saves",
        )
        self.snapshot_physical_bytes = self.registry.counter(
            "session_snapshot_physical_bytes_total",
            "Chunk bytes physically written (after dedup; incl. pre-copy)",
        )
        self.dedup_ratio = self.registry.gauge(
            "session_snapshot_dedup_ratio",
            "Cumulative logical/physical byte ratio (1.0 = no dedup)",
        )
        self.chunk_pool_queue_depth = self.registry.gauge(
            "session_chunk_pool_queue_depth",
            "Chunk I/O operations queued on the store's worker pool",
        )
        self.precopy_residual_bytes = self.registry.histogram(
            "session_precopy_residual_bytes",
            "Bytes written INSIDE the suspend barrier after a pre-copy "
            "pass (the stop-the-world residual)",
            buckets=self.RESIDUAL_BUCKETS,
        )

    def observe_suspend(self, seconds: float, reason: str) -> None:
        self.suspends.inc(reason=reason)
        self.suspend_latency.observe(max(0.0, seconds))

    def observe_resume(self, seconds: float, *, from_snapshot: bool) -> None:
        self.resumes.inc(from_snapshot="true" if from_snapshot else "false")
        self.time_to_resume.observe(max(0.0, seconds))

    def _update_dedup(self) -> None:
        physical = self.snapshot_physical_bytes.get()
        if physical > 0:
            self.dedup_ratio.set(
                self.snapshot_logical_bytes.get() / physical
            )

    def observe_precopy(self, logical: int, written: int) -> None:
        """One pre-copy pass: counts toward physical bytes (the chunks are
        durable) but NOT logical (nothing committed yet)."""
        if written:
            self.snapshot_physical_bytes.inc(written)
        self._update_dedup()

    def observe_save(self, logical: int, written: int) -> None:
        """One committed save: the payload's logical size and the residual
        chunk bytes the barrier actually wrote."""
        self.snapshot_logical_bytes.inc(logical)
        if written:
            self.snapshot_physical_bytes.inc(written)
        self._update_dedup()


class SchedulerMetrics:
    """Fleet-scheduler observability (docs/scheduler.md): queue pressure,
    time-to-bind, fleet utilization, and preemption churn — the four numbers
    an operator needs to answer "why is my notebook still pending".

    Shares a registry with :class:`NotebookMetrics` so one /metrics endpoint
    carries both. Time-to-bind is a histogram (`_bucket`/`_sum`/`_count`):
    `rate(sum)/rate(count)` gives the mean and `histogram_quantile` the
    tail — the old sum-only counter made both impossible. The max gauge
    stays: a single pathological wait must survive bucket averaging.

    ``shard`` (control-plane sharding, runtime/sharding.py): each scheduler
    shard is an independent scheduler over its own accelerator families —
    N of them share one registry, so every family carries a ``shard`` label
    when sharded (unlabeled gauges would last-writer-win across shards and
    read as one fleet). ``shard=None`` keeps the pre-sharding schema.
    """

    # queue waits span seconds (idle fleet) to hours (saturated fleet)
    BIND_BUCKETS = (1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0, 14400.0)
    CYCLE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)
    # phases are sub-cycle: an incremental steady-state phase is sub-ms,
    # a cold full rebuild can take the whole cycle budget
    PHASE_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
    # handoff hold: snapshot-commit bound (sub-second warm, the force
    # deadline worst-case)
    HANDOFF_BUCKETS = (0.5, 1.0, 5.0, 15.0, 60.0, 120.0, 300.0, 900.0)

    def __init__(
        self, registry: Registry | None = None, *, shard: str | None = None
    ) -> None:
        self.registry = registry or Registry()
        scoped = _ShardScope(self.registry, shard)
        self.queue_depth = scoped.gauge(
            "scheduler_queue_depth", "Gangs waiting for TPU capacity"
        )
        self.family_queue_depth = scoped.gauge(
            "scheduler_family_queue_depth",
            "Gangs waiting for TPU capacity, per accelerator family",
            labelnames=("family",),
        )
        self.unschedulable = scoped.gauge(
            "scheduler_unschedulable",
            "Gangs no node pool could ever hold (bad topology for this fleet)",
        )
        # --- placement explainability (scheduler/explain.py) -------------
        # fragmentation index: largest free cuboid / free chips per pool —
        # 1.0 is one contiguous hole, →0 is shattered capacity. The defrag
        # trigger the live-migration roadmap item consumes.
        self.pool_fragmentation = scoped.gauge(
            "scheduler_pool_fragmentation_index",
            "Largest free cuboid over free chips per pool (1.0 = one "
            "contiguous hole; lower = fragmented)",
            labelnames=("pool",),
        )
        self.pool_largest_free = scoped.gauge(
            "scheduler_pool_largest_free_cuboid_chips",
            "Chips in the largest contiguous free cuboid per pool",
            labelnames=("pool",),
        )
        self.would_fit_after_defrag = scoped.gauge(
            "scheduler_would_fit_after_defrag",
            "Waiting gangs whose only blocker is fragmentation: enough "
            "free chips exist, no contiguous slice does",
        )
        self.unschedulable_reasons = scoped.counter(
            "scheduler_unschedulable_total",
            "Gang transitions into a blocking verdict, per reason",
            labelnames=("reason",),
        )
        self.time_in_reason = scoped.histogram(
            "scheduler_time_in_reason_seconds",
            "How long a gang stayed blocked under one verdict before it "
            "bound, stopped, or the verdict changed",
            labelnames=("reason",),
            buckets=self.BIND_BUCKETS,
        )
        self.fleet_chips_total = scoped.gauge(
            "scheduler_fleet_chips_total", "TPU chips the fleet models"
        )
        self.fleet_chips_used = scoped.gauge(
            "scheduler_fleet_chips_used",
            "TPU chips held by bound gangs or blocked hosts",
        )
        self.utilization = scoped.gauge(
            "scheduler_fleet_utilization", "used/total chips, 0..1"
        )
        self.binds = scoped.counter(
            "scheduler_bind_total", "Gang placements committed"
        )
        self.preemptions = scoped.counter(
            "scheduler_preemption_total", "Gangs evicted for a senior gang"
        )
        self.time_to_bind = scoped.histogram(
            "scheduler_time_to_bind_seconds",
            "Queue-admission→bind latency distribution",
            buckets=self.BIND_BUCKETS,
        )
        self.bind_seconds_max = scoped.gauge(
            "scheduler_time_to_bind_seconds_max",
            "Largest time-to-bind observed",
        )
        self.cycles = scoped.counter(
            "scheduler_cycle_total", "Scheduling cycles run"
        )
        self.cycle_duration = scoped.histogram(
            "scheduler_cycle_duration_seconds",
            "Wall time of one full scheduling pass",
            buckets=self.CYCLE_BUCKETS,
        )
        # phase-attributed cycle cost (docs/scheduler.md fast path): which
        # of list/replay/pack/write eats the cycle is what distinguishes
        # "the apiserver is slow" from "the packing is slow"
        self.cycle_phase = scoped.histogram(
            "scheduler_cycle_phase_seconds",
            "Wall time of one scheduling-cycle phase "
            "(list/replay/pack/explain/write)",
            labelnames=("phase",),
            buckets=self.PHASE_BUCKETS,
        )
        self.fit_cache_hits = scoped.counter(
            "scheduler_fit_cache_hits_total",
            "Fit attempts skipped by the negative-fit cache",
        )
        self.fit_cache_misses = scoped.counter(
            "scheduler_fit_cache_misses_total",
            "Failed fit attempts recorded into the negative-fit cache",
        )
        # preemption handoff hold time: suspend-request→chip-release. The
        # preemptor's time-to-bind is bounded below by this — the snapshot
        # fast path (docs/sessions.md) exists to shrink it
        self.handoff_seconds = scoped.histogram(
            "scheduler_handoff_seconds",
            "Suspend-request→placement-release latency of preemption "
            "handoffs",
            buckets=self.HANDOFF_BUCKETS,
        )
        # label universes THIS instance has set (per-shard disjoint by
        # construction: pools/families belong to exactly one shard), so
        # stale series can be retired without clearing siblings'
        self._families_seen: set = set()
        self._pools_seen: set = set()

    def observe_cycle(
        self,
        fleet,
        *,
        queue_depth: int,
        unschedulable: int,
        duration_s: float | None = None,
        phases: Mapping[str, float] | None = None,
        family_depths: Mapping[str, int] | None = None,
        pool_stats: Mapping[str, tuple] | None = None,
    ) -> None:
        self.cycles.inc()
        self.queue_depth.set(queue_depth)
        self.unschedulable.set(unschedulable)
        self.fleet_chips_total.set(fleet.total_chips())
        self.fleet_chips_used.set(fleet.used_chips())
        self.utilization.set(fleet.utilization())
        if duration_s is not None:
            self.cycle_duration.observe(duration_s)
        for phase, seconds in (phases or {}).items():
            self.cycle_phase.observe(seconds, phase=phase)
        if family_depths is not None:
            # clear-and-set per THIS instance's label universe: a family
            # whose queue drained must read 0 (and one that left the fleet
            # must stop exposing) without touching sibling shards' series
            for fam in self._families_seen - set(family_depths):
                self.family_queue_depth.remove(family=fam)
            for fam, depth in family_depths.items():
                self.family_queue_depth.set(depth, family=fam)
            self._families_seen = set(family_depths)
        if pool_stats is not None:
            # (fragmentation index, largest free cuboid chips) per pool —
            # computed by the controller from the live free decompositions
            # (scheduler/explain.py), O(pools) per cycle
            for pool in self._pools_seen - set(pool_stats):
                self.pool_fragmentation.remove(pool=pool)
                self.pool_largest_free.remove(pool=pool)
            for pool, (frag, largest) in pool_stats.items():
                self.pool_fragmentation.set(frag, pool=pool)
                self.pool_largest_free.set(largest, pool=pool)
            self._pools_seen = set(pool_stats)

    def observe_reason_transition(
        self,
        reason: str | None,
        *,
        prev: str | None,
        seconds_in_prev: float,
    ) -> None:
        """A gang's blocking verdict changed (scheduler/explain.py):
        ``reason=None`` means it left the blocked set entirely (bound or
        stopped). Counts transitions INTO a reason and closes out the
        time-in-reason observation for the one it left."""
        if reason is not None:
            self.unschedulable_reasons.inc(reason=reason)
        if prev is not None:
            self.time_in_reason.observe(
                max(0.0, seconds_in_prev), reason=prev
            )

    def set_would_fit_after_defrag(self, count: int) -> None:
        self.would_fit_after_defrag.set(count)

    def fleet_fragmentation_index(self) -> float:
        """Worst per-pool fragmentation index across the registry (the
        dashboard's fleet-level series): the most-shattered pool bounds
        what the biggest waiting gang can hope for. 1.0 when no pool
        reports (empty fleet reads as unfragmented)."""
        vals = [s["value"] for s in self.pool_fragmentation.samples()]
        return min(vals) if vals else 1.0

    def total_queue_depth(self) -> float:
        """Queue depth summed across shards (the dashboard series: one
        number for the fleet even when N shard schedulers share the
        registry)."""
        return builtins_sum(
            s["value"] for s in self.queue_depth.samples()
        )

    def observe_fit_cache(self, hits: int, misses: int) -> None:
        """Per-cycle deltas from the controller's FitCache."""
        if hits:
            self.fit_cache_hits.inc(hits)
        if misses:
            self.fit_cache_misses.inc(misses)

    def observe_bind(self, seconds: float) -> None:
        self.binds.inc()
        self.time_to_bind.observe(seconds)
        if seconds > self.bind_seconds_max.get():
            self.bind_seconds_max.set(seconds)

    def observe_handoff(self, seconds: float) -> None:
        self.handoff_seconds.observe(max(0.0, seconds))


class TelemetryMetrics:
    """Data-plane telemetry (docs/observability.md): per-session duty cycle
    and HBM occupancy scraped from the in-pod agents, rolled up per node
    pool and fleet-wide, plus the collector's own scrape health.

    The ``scheduler_pool_*`` / ``scheduler_fleet_*`` families sit next to
    the scheduler's allocation gauges on purpose: `scheduler_fleet_
    utilization` says how many chips are *held*, `scheduler_fleet_duty_
    cycle` says how hard they are actually *working* — the gap between the
    two is the reclamation opportunity the culler's duty-cycle policy acts
    on. Shares a registry with the other collectors so one /metrics scrape
    carries the whole story.
    """

    # a full-fleet parallel scrape pass: ms (in-memory fakes) to seconds
    # (thousands of pods behind a shared deadline)
    PASS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0)

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.session_duty_cycle = self.registry.gauge(
            "telemetry_session_duty_cycle",
            "Device duty cycle per session (0..1), from the last fresh scrape",
            labelnames=("namespace", "notebook"),
        )
        self.session_hbm_used = self.registry.gauge(
            "telemetry_session_hbm_used_bytes",
            "HBM bytes in use per session, from the last fresh scrape",
            labelnames=("namespace", "notebook"),
        )
        self.session_hbm_total = self.registry.gauge(
            "telemetry_session_hbm_total_bytes",
            "HBM bytes available per session, from the last fresh scrape",
            labelnames=("namespace", "notebook"),
        )
        self.pool_duty_cycle = self.registry.gauge(
            "scheduler_pool_duty_cycle",
            "Mean device duty cycle of the sessions bound to a node pool",
            labelnames=("pool",),
        )
        self.pool_hbm_utilization = self.registry.gauge(
            "scheduler_pool_hbm_utilization",
            "HBM used/available of the sessions bound to a node pool, 0..1",
            labelnames=("pool",),
        )
        self.fleet_duty_cycle = self.registry.gauge(
            "scheduler_fleet_duty_cycle",
            "Mean device duty cycle across all fresh sessions (burned, not "
            "allocated — compare scheduler_fleet_utilization)",
        )
        self.fleet_hbm_utilization = self.registry.gauge(
            "scheduler_fleet_hbm_utilization",
            "Fleet-wide HBM used/available across fresh sessions, 0..1",
        )
        self.sessions = self.registry.gauge(
            "telemetry_sessions", "Sessions the collector currently tracks"
        )
        self.stale_sessions = self.registry.gauge(
            "telemetry_stale_sessions",
            "Tracked sessions whose last good scrape is older than the "
            "staleness bound (still aging toward eviction)",
        )
        self.scrapes = self.registry.counter(
            "telemetry_scrape_total",
            "Per-session scrape outcomes (ok|failed)",
            labelnames=("outcome",),
        )
        self.evicted = self.registry.counter(
            "telemetry_session_evicted_total",
            "Sessions dropped after exceeding the eviction bound",
        )
        self.pass_duration = self.registry.histogram(
            "telemetry_scrape_pass_seconds",
            "Wall time of one whole-fleet parallel scrape pass",
            buckets=self.PASS_BUCKETS,
        )
        self.culls = self.registry.counter(
            "telemetry_cull_total",
            "Culls decided on the duty-cycle signal (vs kernel fallback)",
            labelnames=("policy",),
        )


class GangMetrics:
    """Gang-level data-plane observability (telemetry/gang.py,
    docs/observability.md "gang step telemetry"): per-gang step-time
    distributions and the straggler/desync signals the aggregator derives
    from the per-host step streams. Sits next to ``TelemetryMetrics`` on the
    shared registry: duty cycle says the gang is *busy*, these families say
    whether its hosts are busy *in lockstep* — the gap is a straggling or
    desynced host dragging every peer's collectives.
    """

    # one SPMD step: sub-second decode loops to multi-minute eval passes
    STEP_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0)
    # aggregation over ~200 gangs x 8 hosts must stay well under a scrape
    # interval; bucket where the STEP_BENCH gate lives
    PASS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.step_seconds = self.registry.histogram(
            "tpu_gang_step_seconds",
            "Completed step durations across one gang's hosts (every host's "
            "steps land in the gang's histogram)",
            labelnames=("namespace", "notebook"),
            buckets=self.STEP_BUCKETS,
        )
        self.step_skew = self.registry.gauge(
            "tpu_gang_step_skew_seconds",
            "Slowest-minus-fastest finish of the latest step id every host "
            "completed (lockstep gangs read ~0)",
            labelnames=("namespace", "notebook"),
        )
        self.straggler_ratio = self.registry.gauge(
            "tpu_gang_straggler_ratio",
            "Worst host's median step time over the gang median (1.0 = "
            "balanced; the straggler alarm threshold is the aggregator's)",
            labelnames=("namespace", "notebook"),
        )
        self.host_step_lag = self.registry.gauge(
            "tpu_gang_host_step_lag",
            "Steps a host's latest completed id trails the gang's max "
            "(reset-suppressed hosts report 0 until they re-align)",
            labelnames=("namespace", "notebook", "host"),
        )
        self.fleet_step_p99 = self.registry.gauge(
            "tpu_gang_fleet_step_p99_seconds",
            "p99 completed-step duration across all tracked gangs",
        )
        self.fleet_straggler_ratio = self.registry.gauge(
            "tpu_gang_fleet_straggler_ratio",
            "Worst straggler ratio across all tracked gangs",
        )
        self.gangs = self.registry.gauge(
            "tpu_gang_sessions", "Multi-host gangs the aggregator tracks"
        )
        self.scrapes = self.registry.counter(
            "tpu_gang_scrape_total",
            "Per-host gang scrape outcomes (ok|failed)",
            labelnames=("outcome",),
        )
        self.findings = self.registry.counter(
            "tpu_gang_finding_total",
            "Straggler/desync/stall findings the aggregator recorded",
            labelnames=("kind",),
        )
        self.pass_duration = self.registry.histogram(
            "tpu_gang_pass_seconds",
            "Wall time of one whole-fleet gang aggregation pass",
            buckets=self.PASS_BUCKETS,
        )
        self.compile_total = self.registry.gauge(
            "tpu_gang_compile_total",
            "XLA compilations summed across one gang's hosts (from the "
            "agents' cumulative compile counters)",
            labelnames=("namespace", "notebook"),
        )
        self.compile_seconds = self.registry.gauge(
            "tpu_gang_compile_seconds",
            "Cumulative XLA compile seconds summed across one gang's hosts",
            labelnames=("namespace", "notebook"),
        )


class LedgerMetrics:
    """Fleet efficiency ledger (obs/ledger.py, docs/observability.md
    "efficiency ledger"): exactly-once chip-second accounting. The
    ``*_chip_seconds_total`` counters are cumulative integrals maintained by
    the ledger's integer accountant and SET to the monotone total each tick,
    so the exposed value is exactly the audited one; conservation is
    queryable straight off the scrape::

        sum by (pool) (tpu_pool_chip_seconds_total)
          == tpu_capacity_chip_seconds_total
    """

    # one ledger tick: a Node+Notebook list plus a from-scratch fleet build
    TICK_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0)

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.chip_seconds = self.registry.counter(
            "tpu_chip_seconds_total",
            "Chip-seconds attributed per namespace and bucket (busy, "
            "idle_allocated, starting, suspending, draining, parked)",
            labelnames=("namespace", "bucket"),
        )
        self.pool_chip_seconds = self.registry.counter(
            "tpu_pool_chip_seconds_total",
            "Chip-seconds per pool and bucket; over the conservation "
            "buckets this sums exactly to tpu_capacity_chip_seconds_total",
            labelnames=("pool", "bucket"),
        )
        self.family_chip_seconds = self.registry.counter(
            "tpu_family_chip_seconds_total",
            "Chip-seconds per accelerator family and bucket (pool rollup)",
            labelnames=("family", "bucket"),
        )
        self.capacity_chip_seconds = self.registry.counter(
            "tpu_capacity_chip_seconds_total",
            "Time-integral of pool capacity — the conservation invariant's "
            "right-hand side",
            labelnames=("pool",),
        )
        self.queued_chip_seconds = self.registry.counter(
            "tpu_queued_chip_seconds_total",
            "Requested chips x queue wait per accelerator family — unmet "
            "demand, the elastic-capacity scale-up trigger",
            labelnames=("family",),
        )
        self.fleet_efficiency = self.registry.gauge(
            "tpu_fleet_efficiency",
            "Cumulative busy / allocated chip-seconds across the fleet, 0..1",
        )
        self.fleet_waste_fraction = self.registry.gauge(
            "tpu_fleet_waste_fraction",
            "Cumulative wasted (idle/starting/suspending/draining/stranded) "
            "chip-seconds / capacity chip-seconds, 0..1",
        )
        self.unmet_demand_chips = self.registry.gauge(
            "tpu_unmet_demand_chips",
            "Chips currently requested by queued (unbound, feasible) gangs",
        )
        self.parked_chips = self.registry.gauge(
            "tpu_parked_chips",
            "Chips whose sessions are suspended with chips released — "
            "oversubscription headroom",
        )
        self.ticks_total = self.registry.counter(
            "tpu_ledger_ticks_total", "Ledger attribution ticks taken"
        )
        self.tick_seconds = self.registry.histogram(
            "tpu_ledger_tick_seconds",
            "Wall time of one ledger attribution tick",
            buckets=self.TICK_BUCKETS,
        )


class CapacityMetrics:
    """Elastic-capacity observability (capacity/, docs/capacity.md): the
    autoscaler's decisions and the time-to-first-chip SLO, tracked next to
    the startup SLO on the shared registry. ``slo_first_chip_total`` mirrors
    ``slo_startup_total``'s within-target judgement so the two objectives
    read off one scrape; CAPACITY_BENCH gates the decision latency and the
    first-chip distribution."""

    # demand onset -> first schedulable chip: dominated by cloud provisioning
    # (minutes), with the decision itself sub-cycle
    TTFC_BUCKETS = (5.0, 15.0, 60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0)
    DECISION_BUCKETS = (0.5, 1.0, 5.0, 15.0, 60.0, 300.0)

    def __init__(
        self,
        registry: Registry | None = None,
        *,
        first_chip_target_s: float = 600.0,
    ) -> None:
        self.registry = registry or Registry()
        self.first_chip_target_s = first_chip_target_s
        self.scale_ups = self.registry.counter(
            "capacity_scale_up_total",
            "Node-pool scale-up requests issued to the cloud provider",
            labelnames=("family", "tier"),
        )
        self.scale_downs = self.registry.counter(
            "capacity_scale_down_total",
            "Autoscaled node pools reclaimed after the idle hysteresis dwell",
            labelnames=("family",),
        )
        self.revocations = self.registry.counter(
            "capacity_revocation_total",
            "Spot revocation notices translated into suspend handoffs",
            labelnames=("family",),
        )
        self.provider_errors = self.registry.counter(
            "capacity_provider_errors_total",
            "Cloud-provider calls that failed past the adapter retry budget",
            labelnames=("op",),
        )
        self.open_requests = self.registry.gauge(
            "capacity_open_scale_requests",
            "Scale-up requests awaiting their first schedulable chip",
        )
        self.pending_chips = self.registry.gauge(
            "capacity_pending_chips",
            "Chips currently being provisioned per accelerator family",
            labelnames=("family",),
        )
        self.time_to_first_chip = self.registry.histogram(
            "capacity_time_to_first_chip_seconds",
            "Unmet-demand onset to the first schedulable chip of the "
            "capacity bought for it — the elastic-capacity SLO",
            buckets=self.TTFC_BUCKETS,
        )
        self.first_chip_max = self.registry.gauge(
            "capacity_time_to_first_chip_seconds_max",
            "Largest time-to-first-chip observed",
        )
        self.decision_latency = self.registry.histogram(
            "capacity_scale_decision_seconds",
            "Aged-demand threshold crossing to the provider scale-up call",
            buckets=self.DECISION_BUCKETS,
        )
        self.first_chips = self.registry.counter(
            "slo_first_chip_total",
            "First-chip deliveries judged against the time-to-first-chip "
            "target",
            labelnames=("within_target",),
        )

    def observe_first_chip(self, seconds: float) -> None:
        self.time_to_first_chip.observe(seconds)
        if seconds > self.first_chip_max.get():
            self.first_chip_max.set(seconds)
        self.first_chips.inc(
            within_target=(
                "true" if seconds <= self.first_chip_target_s else "false"
            )
        )

    def ttfc_p50(self) -> float:
        """Time-to-first-chip p50 off the real histogram (dashboard series
        and the JWA's provisioning ETA)."""
        return self.time_to_first_chip.quantile(0.5)


class ProfilerMetrics:
    """Finding-triggered profiling (obs/profiler.py, docs/observability.md
    "capture on demand"): the capture controller's request/outcome families.
    Lives next to ``GangMetrics`` on the shared registry — a finding there
    becomes a capture here, and the per-seed capture audit proves the two
    stay 1:1 under chaos."""

    # one capture: two host probes + chunked store writes
    CAPTURE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.captures = self.registry.counter(
            "tpu_profile_capture_total",
            "Capture requests by outcome (stored|failed|rate_limited|"
            "suppressed)",
            labelnames=("outcome",),
        )
        self.capture_findings = self.registry.counter(
            "tpu_profile_capture_finding_total",
            "Captures bound per triggering finding kind",
            labelnames=("kind",),
        )
        self.active = self.registry.gauge(
            "tpu_profile_captures_active",
            "Captures currently in flight (bounded by the global cap)",
        )
        self.stored_bytes = self.registry.counter(
            "tpu_profile_capture_bytes_total",
            "Trace payload bytes committed through the snapshot store",
        )
        self.capture_seconds = self.registry.histogram(
            "tpu_profile_capture_seconds",
            "Wall time of one finding-to-stored capture",
            buckets=self.CAPTURE_BUCKETS,
        )
        self.passes = self.registry.counter(
            "tpu_profile_pass_total",
            "Capture-controller passes taken (never on the reconcile path)",
        )
