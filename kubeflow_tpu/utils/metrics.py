"""Prometheus-style metrics with text exposition.

The platform's observability contract mirrors the reference's
(``notebook-controller/pkg/metrics/metrics.go:13-99``): a live-scraped
``notebook_running`` gauge plus create/cull counters, exposed in Prometheus
text format at ``/metrics`` by the web layer. Implemented standalone (no
prometheus_client in the image) — exposition format is stable and tiny.
"""
from __future__ import annotations

import threading
from typing import Mapping


class _Metric:
    def __init__(self, name: str, help_: str, kind: str) -> None:
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: dict[tuple, float] = {}
        self._label_names: tuple[str, ...] = ()
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, str]) -> tuple:
        names = tuple(sorted(labels))
        if not self._label_names:
            self._label_names = names
        return tuple(labels[n] for n in self._label_names)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def samples(self) -> list[dict]:
        """Public sample view: [{"labels": {...}, "value": v}, ...]."""
        with self._lock:
            return [
                {"labels": dict(zip(self._label_names, k)), "value": v}
                for k, v in sorted(self._values.items())
            ]

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                if key:
                    lbl = ",".join(
                        f'{n}="{v}"' for n, v in zip(self._label_names, key)
                    )
                    lines.append(f"{self.name}{{{lbl}}} {val:g}")
                else:
                    lines.append(f"{self.name} {val:g}")
        return "\n".join(lines)


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []
        self._pre_expose: list = []

    def pre_expose(self, fn) -> None:
        """Register a live-scrape hook run before each exposition (the
        reference's custom-collector idiom, metrics.go:82-99)."""
        self._pre_expose.append(fn)

    def counter(self, name: str, help_: str) -> _Metric:
        return self._add(_Metric(name, help_, "counter"))

    def gauge(self, name: str, help_: str) -> _Metric:
        return self._add(_Metric(name, help_, "gauge"))

    def _add(self, m: _Metric) -> _Metric:
        # same-name registration returns the existing family (two Apps
        # sharing one registry must not emit duplicate metric families —
        # strict Prometheus scrapers reject that exposition)
        for existing in self._metrics:
            if existing.name == m.name:
                if existing.kind != m.kind:
                    raise ValueError(
                        f"metric {m.name!r} already registered as "
                        f"{existing.kind}, not {m.kind}"
                    )
                return existing
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        for fn in self._pre_expose:
            fn()
        return "\n".join(m.expose() for m in self._metrics) + "\n"


class NotebookMetrics:
    """Reference collector parity (metrics.go:13-64): running gauge scraped
    live from StatefulSets, create/fail/cull counters."""

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.running = self.registry.gauge(
            "notebook_running", "Current running notebooks in the cluster"
        )
        self.tpu_chips_in_use = self.registry.gauge(
            "notebook_tpu_chips_in_use", "TPU chips held by running notebooks"
        )
        self.created = self.registry.counter(
            "notebook_create_total", "Total notebooks created"
        )
        self.create_failed = self.registry.counter(
            "notebook_create_failed_total", "Total notebook create failures"
        )
        self.culled = self.registry.counter(
            "notebook_cull_total", "Total notebooks culled"
        )

    def observe_notebooks(self, cluster) -> None:
        by_ns: dict[str, int] = {}
        chips: dict[str, int] = {}
        for sts in cluster.list("StatefulSet"):
            ns = sts["metadata"].get("namespace", "")
            ready = sts.get("status", {}).get("readyReplicas", 0)
            if ready:
                by_ns[ns] = by_ns.get(ns, 0) + 1
                tmpl = sts["spec"]["template"]["spec"]
                for c in tmpl.get("containers", []):
                    n = int(
                        c.get("resources", {})
                        .get("limits", {})
                        .get("google.com/tpu", 0)
                    )
                    chips[ns] = chips.get(ns, 0) + n * ready
        self.running.clear()
        self.tpu_chips_in_use.clear()
        for ns, n in by_ns.items():
            self.running.set(n, namespace=ns)
        for ns, n in chips.items():
            self.tpu_chips_in_use.set(n, namespace=ns)

    def notebook_created(self, namespace: str) -> None:
        self.created.inc(namespace=namespace)

    def notebook_culled(self, namespace: str) -> None:
        self.culled.inc(namespace=namespace)


class SchedulerMetrics:
    """Fleet-scheduler observability (docs/scheduler.md): queue pressure,
    time-to-bind, fleet utilization, and preemption churn — the four numbers
    an operator needs to answer "why is my notebook still pending".

    Shares a registry with :class:`NotebookMetrics` so one /metrics endpoint
    carries both; time-to-bind is exposed as a cumulative sum + count (+ max)
    rather than a histogram — the benchmark computes percentiles offline
    from its own samples, and sum/count is what a rate() query needs.
    """

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry or Registry()
        self.queue_depth = self.registry.gauge(
            "scheduler_queue_depth", "Gangs waiting for TPU capacity"
        )
        self.unschedulable = self.registry.gauge(
            "scheduler_unschedulable",
            "Gangs no node pool could ever hold (bad topology for this fleet)",
        )
        self.fleet_chips_total = self.registry.gauge(
            "scheduler_fleet_chips_total", "TPU chips the fleet models"
        )
        self.fleet_chips_used = self.registry.gauge(
            "scheduler_fleet_chips_used",
            "TPU chips held by bound gangs or blocked hosts",
        )
        self.utilization = self.registry.gauge(
            "scheduler_fleet_utilization", "used/total chips, 0..1"
        )
        self.binds = self.registry.counter(
            "scheduler_bind_total", "Gang placements committed"
        )
        self.preemptions = self.registry.counter(
            "scheduler_preemption_total", "Gangs evicted for a senior gang"
        )
        self.bind_seconds_sum = self.registry.counter(
            "scheduler_time_to_bind_seconds_sum",
            "Cumulative queue-admission→bind latency",
        )
        self.bind_seconds_max = self.registry.gauge(
            "scheduler_time_to_bind_seconds_max",
            "Largest time-to-bind observed",
        )
        self.cycles = self.registry.counter(
            "scheduler_cycle_total", "Scheduling cycles run"
        )

    def observe_cycle(self, fleet, *, queue_depth: int, unschedulable: int) -> None:
        self.cycles.inc()
        self.queue_depth.set(queue_depth)
        self.unschedulable.set(unschedulable)
        self.fleet_chips_total.set(fleet.total_chips())
        self.fleet_chips_used.set(fleet.used_chips())
        self.utilization.set(fleet.utilization())

    def observe_bind(self, seconds: float) -> None:
        self.binds.inc()
        self.bind_seconds_sum.inc(seconds)
        if seconds > self.bind_seconds_max.get():
            self.bind_seconds_max.set(seconds)
