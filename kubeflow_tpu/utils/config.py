"""Env-var-first configuration, the controllers' flag idiom.

Mirrors the reference's knob surface so operators migrate without relearning
names (``notebook-controller/README.md:44-49``, ``pkg/culler/culler.go:26-30``):
USE_ISTIO, ISTIO_GATEWAY, CLUSTER_DOMAIN, ADD_FSGROUP, ENABLE_CULLING,
CULL_IDLE_TIME (minutes), IDLENESS_CHECK_PERIOD (minutes), DEV.
TPU-native additions are namespaced ``TPU_*``.
"""
from __future__ import annotations

import dataclasses
import os


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


@dataclasses.dataclass
class ControllerConfig:
    use_istio: bool = True
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    add_fsgroup: bool = True
    default_fs_group: int = 100
    workspace_dir: str = "/home/jovyan"
    container_port: int = 8888
    serving_port: int = 80
    # Culling (minutes, matching reference units at culler.go:26-27)
    enable_culling: bool = False
    cull_idle_minutes: float = 1440.0
    idleness_check_minutes: float = 1.0
    dev: bool = False
    # TPU-native
    tpu_coordinator_port: int = 8476  # jax.distributed default coordinator port
    tpu_gang_schedule: bool = True    # all-or-nothing pod-slice admission
    # Fleet scheduler (kubeflow_tpu/scheduler/): when enabled, a TPU gang's
    # StatefulSets stay at 0 replicas until the scheduler binds it (the
    # placement annotation is the gate) and the gang is pinned to the pool
    # the scheduler chose. Off by default for programmatic construction so
    # tests that run the notebook controller alone keep their semantics;
    # the shipped controller-manager process enables it (SCHEDULER_ENABLED).
    scheduler_enabled: bool = False
    # Session lifecycle (kubeflow_tpu/sessions/): when enabled, every gang
    # teardown (stop, cull, preemption) runs the suspend barrier — pods stay
    # up until the session snapshot commits (or the force deadline), and a
    # restart resumes from the snapshot instead of cold. Off by default for
    # programmatic construction (same rationale as scheduler_enabled); the
    # shipped controller-manager process enables it (SESSIONS_ENABLED).
    sessions_enabled: bool = False
    suspend_deadline_s: float = 120.0
    # Snapshot fast path: stream a best-effort dirty-chunk pass while the
    # session is still running, so the suspend barrier writes only the
    # residual delta (docs/sessions.md "snapshot fast path"). Safe to
    # disable (every suspend then pays the full blocking save).
    sessions_precopy: bool = True
    # Session telemetry (kubeflow_tpu/telemetry/): when enabled, the fleet
    # collector scrapes every TPU notebook's in-pod agent in one parallel
    # pass per interval, and the culler prefers the device duty-cycle
    # signal over kernel activity (telemetry-when-present, kernel-activity
    # fallback). Off by default for programmatic construction (same
    # rationale as scheduler_enabled); the shipped controller-manager
    # process enables it (TELEMETRY_ENABLED).
    telemetry_enabled: bool = False
    telemetry_interval_s: float = 15.0
    telemetry_staleness_s: float = 60.0
    telemetry_duty_cycle_idle: float = 0.05
    telemetry_port: int = 8890
    # Gang-level step aggregator (telemetry/gang.py): scrapes every host of
    # every multi-host gang for per-step records and derives straggler/
    # desync verdicts. Rides the collector's loop; needs telemetry_enabled.
    gang_telemetry_enabled: bool = False
    # Finding-triggered profile capture (obs/profiler.py): the gang
    # aggregator's frozen findings trigger bounded XLA trace captures
    # (culprit + reference host) committed through the snapshot store under
    # the TensorBoard plugins/profile/ convention. Needs
    # gang_telemetry_enabled; rides the telemetry loop, never the reconcile
    # path. Rate limits: one capture per gang per cooldown, a global
    # concurrent-capture cap.
    profiler_enabled: bool = False
    profiler_cooldown_s: float = 600.0
    profiler_max_active: int = 2
    profiler_steps: int = 5
    # Fleet efficiency ledger (obs/ledger.py): exactly-once chip-second
    # accounting with waste attribution — busy/idle/starting/suspending/
    # draining/free/stranded per pool, family, and namespace, plus queued
    # unmet demand. Off by default for programmatic construction (same
    # rationale as telemetry_enabled); the shipped controller-manager
    # enables it (LEDGER_ENABLED; --no-ledger A/B via LEDGER_ENABLED=0).
    ledger_enabled: bool = False
    ledger_interval_s: float = 15.0
    # Elastic capacity (kubeflow_tpu/capacity/): scheduler-driven node-pool
    # autoscaling with a spot tier. Off by default everywhere — the loop
    # needs a cloud provider; the shipped controller-manager enables it with
    # CAPACITY_ENABLED=true plus CAPACITY_PROVIDER (fake|gke|eks; STANDALONE
    # always gets the deterministic fake). Revocations ride the sessions
    # suspend barrier, so sessions_enabled should accompany it.
    capacity_enabled: bool = False
    # a gang must wait this long unhelped before its demand buys chips
    capacity_pending_grace_s: float = 30.0
    # continuous-idle dwell before an autoscaled pool is reclaimed — the
    # anti-flap hysteresis (docs/capacity.md)
    capacity_hysteresis_s: float = 300.0
    capacity_max_pools_per_family: int = 2
    # buy the cheaper revocable tier when the provider offers one
    capacity_spot: bool = True
    # the time-to-first-chip SLO target (demand onset -> first chip)
    first_chip_target_s: float = 600.0
    # Control-plane sharding (runtime/sharding.py): partition the manager
    # plane by namespace hash and the scheduler by accelerator family into
    # SHARDS independent shards, each behind its own leader lease. 1 (the
    # default) is the single-loop control plane, bit-identical to the
    # pre-sharding behavior. shard_id: which shard THIS process runs
    # (SHARD_ID env — the production layout is one process per shard, e.g.
    # a StatefulSet ordinal); None runs every shard in one process
    # (standalone / demo / soak harnesses).
    shards: int = 1
    shard_id: int | None = None
    # Profile defaults (ref --namespace-labels-path flag, profile-controller
    # main.go; the mounted file is hot-reloaded, go:356-405)
    namespace_labels_path: str = ""
    # OpenShift companion controller (ref odh-notebook-controller): OAuth
    # sidecar objects for annotated Notebooks; the openshift overlay
    # enables it via ENABLE_OAUTH_CONTROLLER
    enable_oauth_controller: bool = False

    @classmethod
    def from_env(cls) -> "ControllerConfig":
        return cls(
            use_istio=_env_bool("USE_ISTIO", True),
            istio_gateway=os.environ.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"),
            istio_host=os.environ.get("ISTIO_HOST", "*"),
            cluster_domain=os.environ.get("CLUSTER_DOMAIN", "cluster.local"),
            add_fsgroup=_env_bool("ADD_FSGROUP", True),
            enable_culling=_env_bool("ENABLE_CULLING", False),
            cull_idle_minutes=_env_float("CULL_IDLE_TIME", 1440.0),
            idleness_check_minutes=_env_float("IDLENESS_CHECK_PERIOD", 1.0),
            dev=_env_bool("DEV", False),
            tpu_gang_schedule=_env_bool("TPU_GANG_SCHEDULE", True),
            scheduler_enabled=_env_bool("SCHEDULER_ENABLED", True),
            sessions_enabled=_env_bool("SESSIONS_ENABLED", True),
            suspend_deadline_s=_env_float("SUSPEND_DEADLINE_S", 120.0),
            sessions_precopy=_env_bool("SESSIONS_PRECOPY", True),
            telemetry_enabled=_env_bool("TELEMETRY_ENABLED", True),
            telemetry_interval_s=_env_float("TELEMETRY_INTERVAL_S", 15.0),
            telemetry_staleness_s=_env_float("TELEMETRY_STALENESS_S", 60.0),
            telemetry_duty_cycle_idle=_env_float(
                "TELEMETRY_DUTY_CYCLE_IDLE", 0.05
            ),
            telemetry_port=int(_env_float("TELEMETRY_PORT", 8890)),
            gang_telemetry_enabled=_env_bool("GANG_TELEMETRY_ENABLED", True),
            profiler_enabled=_env_bool("PROFILER_ENABLED", True),
            profiler_cooldown_s=_env_float("PROFILER_COOLDOWN_S", 600.0),
            profiler_max_active=int(_env_float("PROFILER_MAX_ACTIVE", 2)),
            profiler_steps=int(_env_float("PROFILER_STEPS", 5)),
            ledger_enabled=_env_bool("LEDGER_ENABLED", True),
            ledger_interval_s=_env_float("LEDGER_INTERVAL_S", 15.0),
            capacity_enabled=_env_bool("CAPACITY_ENABLED", False),
            capacity_pending_grace_s=_env_float(
                "CAPACITY_PENDING_GRACE_S", 30.0
            ),
            capacity_hysteresis_s=_env_float("CAPACITY_HYSTERESIS_S", 300.0),
            capacity_max_pools_per_family=int(
                _env_float("CAPACITY_MAX_POOLS_PER_FAMILY", 2)
            ),
            capacity_spot=_env_bool("CAPACITY_SPOT", True),
            first_chip_target_s=_env_float("FIRST_CHIP_TARGET_S", 600.0),
            shards=max(1, int(_env_float("SHARDS", 1))),
            shard_id=(
                int(_env_float("SHARD_ID", -1))
                if os.environ.get("SHARD_ID") is not None
                else None
            ),
            namespace_labels_path=os.environ.get("NAMESPACE_LABELS_PATH", ""),
            enable_oauth_controller=_env_bool("ENABLE_OAUTH_CONTROLLER", False),
        )
