"""TPU-native notebook platform."""
