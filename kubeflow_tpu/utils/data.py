"""Input pipeline: host→device prefetch for training loops.

The reference has no data-loading component at all (its workloads are
interactive notebooks; SURVEY.md §2) — but a TPU training framework needs
one: ``jax.device_put`` is asynchronous, so keeping a small queue of batches
in flight overlaps PCIe/DMA transfer (and host-side batch assembly) with the
previous step's compute, instead of stalling the chip at every step boundary.

    it = DevicePrefetcher(host_batches(), meshlib.batch_sharding(mesh))
    for batch in it:            # batch is already on device, sharded
        state, metrics = step(state, batch)

Design notes (TPU-first):
- transfers are dispatched ``depth`` batches ahead (default 2 — one being
  consumed, one in flight; more rarely helps and costs HBM);
- the sharding is applied at transfer time (``device_put`` with a
  NamedSharding), so each host only materializes its addressable shards —
  the multi-host-safe layout, same as the checkpoint layer's;
- any nested pytree of numpy/jax arrays works as a batch.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np


class DevicePrefetcher:
    """Wraps a host batch iterator; yields device-resident, sharded batches
    while keeping ``depth`` transfers in flight."""

    def __init__(self, batches: Iterable[Any], sharding: Any, *, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._it = iter(batches)
        self._sharding = sharding
        self._depth = depth
        self._queue: collections.deque = collections.deque()

    def _put(self, batch: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._sharding), batch
        )

    def _fill(self) -> None:
        while len(self._queue) < self._depth:
            try:
                batch = next(self._it)
            except StopIteration:
                return
            self._queue.append(self._put(batch))

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        self._fill()
        if not self._queue:
            raise StopIteration
        out = self._queue.popleft()
        self._fill()  # immediately dispatch the replacement transfer
        return out


def synthetic_token_batches(
    *, batch: int, seq_len: int, vocab_size: int, seed: int = 0,
    steps: int | None = None,
) -> Iterator[np.ndarray]:
    """Endless (or ``steps``-bounded) random token batches — the benchmark
    and smoke-test data source."""
    rng = np.random.default_rng(seed)
    n = 0
    while steps is None or n < steps:
        yield rng.integers(
            0, vocab_size, (batch, seq_len), dtype=np.int32
        )
        n += 1


def map_batches(
    batches: Iterable[Any], fn: Callable[[Any], Any]
) -> Iterator[Any]:
    """Host-side transform stage (tokenize, augment, pack) applied before
    transfer; composes with DevicePrefetcher so the transform of batch N+1
    overlaps the device compute of batch N."""
    for b in batches:
        yield fn(b)
