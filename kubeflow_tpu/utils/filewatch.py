"""Polling file watcher + TLS cert hot-reload.

The reference runs fsnotify on two hot paths: the admission webhook's TLS
certwatcher (``admission-webhook/pkg/config.go:42-60``) and the profile
controller's default-namespace-labels file
(``profile_controller.go:356-405``). Python has no stdlib inotify binding, so
this watches by polling ``os.stat`` — equivalent for the Kubernetes case:
ConfigMap and cert-manager Secret mounts update via an atomic symlink swap at
the kubelet sync period, which changes the logical path's inode/mtime, both of
which the stat signature below includes (the symlink-rewatch dance the
reference needs at go:375-380 falls out for free).
"""
from __future__ import annotations

import logging
import os
import ssl
import threading
from typing import Callable, Sequence

log = logging.getLogger("filewatch")


class FileWatcher:
    """Fire ``on_change()`` whenever any watched path's content identity
    (mtime_ns, size, inode) changes — including reappearing after deletion.

    ``poll_once()`` is the deterministic test surface; ``start()`` runs it on
    a daemon thread every ``poll_interval`` seconds.
    """

    def __init__(
        self,
        paths: str | Sequence[str],
        on_change: Callable[[], None],
        *,
        poll_interval: float = 2.0,
    ) -> None:
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        self.on_change = on_change
        self.poll_interval = poll_interval
        self._last = self._signature()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _signature(self) -> tuple:
        sig = []
        for p in self.paths:
            try:
                st = os.stat(p)
                sig.append((st.st_mtime_ns, st.st_size, st.st_ino))
            except OSError:
                sig.append(None)
        return tuple(sig)

    def poll_once(self) -> bool:
        """Returns True iff a change was seen (and on_change fired)."""
        sig = self._signature()
        if sig == self._last:
            return False
        self._last = sig
        if all(s is None for s in sig):
            # all files vanished: remember it, but a half-rotated mount is
            # not a state worth reloading into
            return False
        try:
            self.on_change()
        except Exception:
            log.exception("on_change failed for %s", self.paths)
        return True

    def start(self) -> None:
        def run():
            while not self._stop.wait(self.poll_interval):
                self.poll_once()

        self._thread = threading.Thread(target=run, daemon=True, name="filewatch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


class CertWatcher:
    """Hot-reloading TLS server context (ref certwatcher, config.go:42-60).

    One long-lived ``SSLContext`` is wrapped around the listening socket
    once; ``load_cert_chain`` on that same context swaps the cert for all
    *subsequent* handshakes, so rotation needs no socket churn. A
    half-rotated mount (cert updated, key not yet — mismatched pair) raises
    inside reload; the old pair stays active and the next poll retries.
    """

    def __init__(self, cert_path: str, key_path: str, *, poll_interval: float = 2.0) -> None:
        self.cert_path = cert_path
        self.key_path = key_path
        self.context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self.context.load_cert_chain(cert_path, key_path)
        self.reloads = 0
        self.watcher = FileWatcher(
            [cert_path, key_path], self._reload, poll_interval=poll_interval
        )

    def _reload(self) -> None:
        try:
            self.context.load_cert_chain(self.cert_path, self.key_path)
        except (ssl.SSLError, OSError) as e:
            log.warning("cert reload failed (keeping previous pair): %s", e)
            return
        self.reloads += 1
        log.info("reloaded TLS cert from %s", self.cert_path)

    def poll_once(self) -> bool:
        return self.watcher.poll_once()

    def start(self) -> None:
        self.watcher.start()

    def stop(self) -> None:
        self.watcher.stop()
