"""XLA/TPU profiler trace capture.

The reference has **no tracing** (SURVEY.md §5: "Tracing / profiling: none");
its tensorboard-controller merely serves whatever a logdir holds. This module
is the producer side the platform adds: notebooks capture device traces into
the same logdir convention the tensorboard-controller ingests
(``gs://…/<run>/plugins/profile/...`` — BASELINE.json config 5), so profiles
from a pod slice render in the platform's TensorBoard with zero setup.

Usage in a notebook cell:

    from kubeflow_tpu.utils.profiling import trace
    with trace("gs://bucket/experiments/run1"):
        state, metrics = train_step(state, batch)

Multi-host: every worker captures (JAX requires all hosts in the trace);
host 0's trace carries the ICI collectives timeline.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator


class ProfilerServerError(RuntimeError):
    """The live profiler server is in the wrong state for the request
    (double start, stop without start). Raised by :func:`server` /
    :func:`stop` instead of letting jax's own C++-level error surface."""


@contextlib.contextmanager
def trace(logdir: str, *, host_only_on_coordinator: bool = False) -> Iterator[None]:
    """Capture an XLA profiler trace around a block."""
    import jax

    worker = int(os.environ.get("TPU_WORKER_ID", "0"))
    if host_only_on_coordinator and worker != 0:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_n_steps(logdir: str, step_fn, state, batch, *, steps: int = 3):
    """Convenience: warm up one step (compile outside the trace), then capture
    ``steps`` steps — the standard recipe for a clean device timeline."""
    if steps <= 0:
        raise ValueError(f"steps must be positive, got {steps}")
    state, metrics = step_fn(state, batch)  # compile + warm outside trace
    _block(metrics)
    with trace(logdir):
        for _ in range(steps):
            state, metrics = step_fn(state, batch)
        _block(metrics)
    return state, metrics


def annotate(name: str):
    """Named region in the trace (shows on the TraceViewer timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_annotation(step_num: int, name: str = "train"):
    """Step marker in the trace (``jax.profiler.StepTraceAnnotation``): the
    TraceViewer groups device ops under step ``step_num``. The telemetry
    agent's step hook (``telemetry/agent.py``) wraps every recorded step in
    this, so the agent's step counter and a captured profile share one
    numbering — "step 1234 was slow" means the same step in both."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step_num)


def _block(tree) -> None:
    import jax

    # Hard host sync: tunneled runtimes may early-return block_until_ready on
    # sharded arrays (see bench.py); fetching a leaf is reliable everywhere.
    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        float(leaves[0].sum() if hasattr(leaves[0], "sum") else leaves[0])


# the live-server singleton: jax.profiler.start_server raises from deep
# inside the C++ layer on a double start, so the module tracks the one
# allowed server itself and fails with a typed error instead
_server_lock = threading.Lock()
_server = None
_server_port: int | None = None


def server(port: int = 9012):
    """Start the live profiler server (attach from TensorBoard's profile tab;
    the capture-on-demand path for a running mesh). Idempotent per port: a
    repeat call for the SAME port returns the running server; a second
    start on a different port raises :class:`ProfilerServerError` (jax
    allows one server per process). Returns the server handle."""
    global _server, _server_port
    import jax

    with _server_lock:
        if _server is not None:
            if _server_port == port:
                return _server
            raise ProfilerServerError(
                f"profiler server already running on port {_server_port}; "
                f"stop() it before starting on port {port}"
            )
        _server = jax.profiler.start_server(port)
        _server_port = port
        return _server


def stop() -> None:
    """Stop the live profiler server started by :func:`server`. Raises
    :class:`ProfilerServerError` when no server is running."""
    global _server, _server_port
    with _server_lock:
        if _server is None:
            raise ProfilerServerError("no profiler server is running")
        stopper = getattr(_server, "stop", None)
        try:
            if stopper is not None:
                stopper()
        finally:
            _server = None
            _server_port = None
