"""XLA/TPU profiler trace capture.

The reference has **no tracing** (SURVEY.md §5: "Tracing / profiling: none");
its tensorboard-controller merely serves whatever a logdir holds. This module
is the producer side the platform adds: notebooks capture device traces into
the same logdir convention the tensorboard-controller ingests
(``gs://…/<run>/plugins/profile/...`` — BASELINE.json config 5), so profiles
from a pod slice render in the platform's TensorBoard with zero setup.

Usage in a notebook cell:

    from kubeflow_tpu.utils.profiling import trace
    with trace("gs://bucket/experiments/run1"):
        state, metrics = train_step(state, batch)

Multi-host: every worker captures (JAX requires all hosts in the trace);
host 0's trace carries the ICI collectives timeline.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator


@contextlib.contextmanager
def trace(logdir: str, *, host_only_on_coordinator: bool = False) -> Iterator[None]:
    """Capture an XLA profiler trace around a block."""
    import jax

    worker = int(os.environ.get("TPU_WORKER_ID", "0"))
    if host_only_on_coordinator and worker != 0:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_n_steps(logdir: str, step_fn, state, batch, *, steps: int = 3):
    """Convenience: warm up one step (compile outside the trace), then capture
    ``steps`` steps — the standard recipe for a clean device timeline."""
    state, metrics = step_fn(state, batch)  # compile + warm outside trace
    _block(metrics)
    with trace(logdir):
        for _ in range(steps):
            state, metrics = step_fn(state, batch)
        _block(metrics)
    return state, metrics


def annotate(name: str):
    """Named region in the trace (shows on the TraceViewer timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_annotation(step_num: int, name: str = "train"):
    """Step marker in the trace (``jax.profiler.StepTraceAnnotation``): the
    TraceViewer groups device ops under step ``step_num``. The telemetry
    agent's step hook (``telemetry/agent.py``) wraps every recorded step in
    this, so the agent's step counter and a captured profile share one
    numbering — "step 1234 was slow" means the same step in both."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step_num)


def _block(tree) -> None:
    import jax

    # Hard host sync: tunneled runtimes may early-return block_until_ready on
    # sharded arrays (see bench.py); fetching a leaf is reliable everywhere.
    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        float(leaves[0].sum() if hasattr(leaves[0], "sum") else leaves[0])


def server(port: int = 9012) -> None:
    """Start the live profiler server (attach from TensorBoard's profile tab;
    the capture-on-demand path for a running mesh)."""
    import jax

    jax.profiler.start_server(port)
