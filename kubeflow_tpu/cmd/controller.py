"""Controller-manager process (ref: each Go controller's ``main.go``).

Hosts every reconciler on one manager against the in-cluster API server, with
Prometheus metrics + probes on the ports the manifests wire up
(``manifests/base/controller.yaml``). Set ``STANDALONE=true`` to run against an
in-memory cluster (demo / kind-less smoke tests — the platform's own envtest).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from wsgiref.simple_server import make_server

from kubeflow_tpu.controllers.notebook_controller import NotebookReconciler
from kubeflow_tpu.controllers.profile_controller import ProfileReconciler
from kubeflow_tpu.controllers.tensorboard_controller import TensorboardReconciler
from kubeflow_tpu.culler import probe
from kubeflow_tpu.culler.culler import Culler
from kubeflow_tpu.obs import (
    EventRecorder,
    HealthState,
    SLOMetrics,
    TimelineBuilder,
    TimelineRecorder,
    Tracer,
    install_probe_routes,
    install_timeline_route,
)
from kubeflow_tpu.runtime.manager import Manager
from kubeflow_tpu.utils.config import ControllerConfig
from kubeflow_tpu.utils.metrics import (
    ControlPlaneMetrics,
    NotebookMetrics,
    SchedulerMetrics,
    SessionMetrics,
)
from kubeflow_tpu.webapps.base import App

log = logging.getLogger("controller")


def _kernel_target(cfg: ControllerConfig, namespace: str, name: str) -> tuple[str, int, str]:
    """(host, port, path) for a notebook's Jupyter kernels endpoint
    (ref culler.go:149-185; DEV mode uses the kubectl-proxy URL shape from
    culler.go:156-160)."""
    if cfg.dev:
        return (
            "127.0.0.1",
            8001,
            f"/api/v1/namespaces/{namespace}/services/{name}:80/proxy"
            f"/notebook/{namespace}/{name}/api/kernels",
        )
    return (
        f"{name}.{namespace}.svc.{cfg.cluster_domain}",
        80,
        f"/notebook/{namespace}/{name}/api/kernels",
    )


def fetch_kernels_http(namespace: str, name: str):
    """Single-notebook culler probe (cache-miss path of the fleet prober)."""
    cfg = ControllerConfig.from_env()
    results = probe.probe_many([_kernel_target(cfg, namespace, name)], timeout=5.0)
    return results[0].kernels()


class FleetKernelFetcher:
    """Fleet-wide kernel probing through the native parallel prober.

    Where the reference blocks one reconcile per HTTP GET
    (``culler.go:149-185``), this probes every running notebook in one
    native pass (``native/culler_probe.cc``) and serves the culler from the
    cache; misses (notebooks created between refreshes) fall back to a
    single probe.
    """

    def __init__(self, cluster, cfg: ControllerConfig, *, timeout: float = 5.0) -> None:
        self.cluster = cluster
        self.cfg = cfg
        self.timeout = timeout
        self._cache: dict[tuple[str, str], list | None] = {}
        self._lock = threading.Lock()

    def refresh(self) -> int:
        notebooks = self.cluster.list("Notebook")
        keys, targets = [], []
        for nb in notebooks:
            ns = nb.get("metadata", {}).get("namespace", "")
            name = nb.get("metadata", {}).get("name", "")
            keys.append((ns, name))
            targets.append(_kernel_target(self.cfg, ns, name))
        results = probe.probe_many(targets, timeout=self.timeout)
        with self._lock:
            self._cache = {
                k: r.kernels() for k, r in zip(keys, results)
            }
        return len(keys)

    def __call__(self, namespace: str, name: str):
        with self._lock:
            if (namespace, name) in self._cache:
                return self._cache[(namespace, name)]
        results = probe.probe_many(
            [_kernel_target(self.cfg, namespace, name)], timeout=self.timeout
        )
        return results[0].kernels()


def build_manager(
    cluster,
    config: ControllerConfig | None = None,
    *,
    fetch_kernels=fetch_kernels_http,
    router=None,
    shard_id: int = 0,
    shared: dict | None = None,
) -> tuple[Manager, NotebookMetrics]:
    """One manager — the whole control plane when ``router`` is None (the
    historical single-loop behavior, unchanged), or one SHARD of it when a
    :class:`~kubeflow_tpu.runtime.sharding.ShardRouter` is passed: the
    manager's enqueue filter drops unowned namespaces, its scheduler owns
    only its accelerator families, and its per-manager metric families
    carry a ``shard`` label. ``shared`` carries the process-wide singletons
    (metrics registry, tracer, telemetry collector, SLO plane, culler,
    snapshot store) so N shard managers in one process — or the soaks'
    in-process fleets — share one observability plane."""
    cfg = config or ControllerConfig.from_env()
    shared = shared if shared is not None else {}
    metrics = shared.setdefault("metrics", NotebookMetrics())
    # control-plane telemetry (docs/observability.md): reconcile tracing
    # (/debug/traces), reconcile/queue-wait/apiserver histograms (shared
    # registry → one /metrics), deduplicated Kubernetes Events
    tracer = shared.setdefault("tracer", Tracer())
    shard_label = str(shard_id) if router is not None else None
    cp_metrics = ControlPlaneMetrics(metrics.registry, shard=shard_label)
    recorder = EventRecorder()
    if "telemetry" not in shared:
        telemetry = None
        if cfg.telemetry_enabled:
            # data-plane telemetry (kubeflow_tpu/telemetry/): the fleet
            # collector scrapes every TPU notebook's in-pod agent in one
            # parallel pass per interval — driven by its own loop in
            # main(), NEVER from a reconcile — and feeds the culler's
            # duty-cycle policy, the per-pool/fleet gauges, and
            # /debug/telemetry. ONE collector per process, even sharded:
            # the scrape pass is already fleet-parallel.
            from kubeflow_tpu.telemetry.collector import FleetTelemetryCollector
            from kubeflow_tpu.utils.metrics import TelemetryMetrics

            telemetry = FleetTelemetryCollector(
                cluster,
                TelemetryMetrics(metrics.registry),
                interval_s=cfg.telemetry_interval_s,
                staleness_s=cfg.telemetry_staleness_s,
                tracer=tracer,
                cluster_domain=cfg.cluster_domain,
                port=cfg.telemetry_port,
            )
        shared["telemetry"] = telemetry
    telemetry = shared["telemetry"]
    if "gang" not in shared:
        gang = None
        if telemetry is not None and cfg.gang_telemetry_enabled:
            # gang-level step aggregator (telemetry/gang.py): scrapes every
            # host of every multi-host gang — per-host step streams →
            # straggler/desync verdicts — on the same off-reconcile loop as
            # the fleet collector. ONE per process, like the collector.
            from kubeflow_tpu.telemetry.gang import GangTelemetryAggregator
            from kubeflow_tpu.utils.metrics import GangMetrics

            gang = GangTelemetryAggregator(
                cluster,
                GangMetrics(metrics.registry),
                interval_s=cfg.telemetry_interval_s,
                staleness_s=cfg.telemetry_staleness_s,
                recorder=recorder,
                cluster_domain=cfg.cluster_domain,
                port=cfg.telemetry_port,
            )
        shared["gang"] = gang
    gang = shared["gang"]
    if "ledger" not in shared:
        ledger = None
        # fleet efficiency ledger (obs/ledger.py): exactly-once chip-second
        # accounting off the reconcile path — driven by its own loop in
        # main(), like the telemetry collector. ONE ledger per FLEET, not
        # per shard: its tick reads the whole cluster, so in the
        # one-process-per-shard production layout every shard leader
        # running one would export the fleet's chip-seconds N times over
        # (and the conservation ratio would still read exactly 1, hiding
        # it). Shard 0's process owns it; the all-in-one layout builds
        # shard 0 first, so the shared singleton lands identically.
        if cfg.ledger_enabled and (router is None or shard_id == 0):
            from kubeflow_tpu.obs.ledger import FleetEfficiencyLedger
            from kubeflow_tpu.utils.metrics import LedgerMetrics

            ledger = FleetEfficiencyLedger(
                cluster,
                LedgerMetrics(metrics.registry),
                interval_s=cfg.ledger_interval_s,
                telemetry=telemetry,
            )
        shared["ledger"] = ledger
    ledger = shared["ledger"]
    if "culler" not in shared:
        # one culler: its per-notebook state is keyed by (ns, name) and
        # namespaces are shard-disjoint, so shards never contend on it
        shared["culler"] = Culler(
            enabled=cfg.enable_culling,
            cull_idle_minutes=cfg.cull_idle_minutes,
            check_period_minutes=cfg.idleness_check_minutes,
            fetch_kernels=fetch_kernels,
            clock=time.time,
            telemetry=telemetry,
            duty_cycle_idle_threshold=cfg.telemetry_duty_cycle_idle,
        )
    culler = shared["culler"]
    # startup timeline + SLO plane (obs/timeline.py, obs/slo.py): the
    # notebook controller stamps click-to-ready marks on every CR; the
    # recorder feeds the phase-attributed startup histograms and the
    # burn-rate gauges on the shared registry; the builder serves
    # /debug/timeline and the JWA detail view. Fleet-wide families (each
    # notebook starts under exactly one shard, so counts add) — shared.
    slo = shared.setdefault("slo", SLOMetrics(metrics.registry))
    timeline_rec = TimelineRecorder(slo=slo, clock=time.time)
    if router is not None:
        from kubeflow_tpu.runtime.sharding import shard_enqueue_filter

        enqueue_filter = shard_enqueue_filter(router, shard_id)
    else:
        enqueue_filter = None
    manager = Manager(
        cluster, clock=time.time, tracer=tracer, metrics=cp_metrics,
        enqueue_filter=enqueue_filter,
    )
    # the ops listeners and main loop read it off the manager (build_manager
    # keeps its two-value return for every existing caller)
    manager.telemetry = telemetry
    manager.gang = gang
    manager.ledger = ledger
    manager.slo = slo
    manager.timeline_builder = shared.setdefault(
        "timeline_builder", TimelineBuilder(cluster, telemetry=telemetry)
    )
    manager.shard_id = shard_id if router is not None else None
    if hasattr(cluster, "session") and "client_metrics" not in shared:
        # KubeClient: per-verb latency/retries. NOT cluster.tracer: the
        # Manager already wraps this cluster in a TracingCluster, so a
        # client-level tracer would double-record every reconcile write and
        # flag non-reconcile writers (the leader lease renewal loop) as
        # unattributed forever. Sharded, the one shared client gets its own
        # shard="client" series — attributing its latency to whichever
        # shard happened to register first would lie per shard.
        shared["client_metrics"] = (
            ControlPlaneMetrics(metrics.registry, shard="client")
            if router is not None
            else cp_metrics
        )
        cluster.metrics = shared["client_metrics"]
    manager.register(
        NotebookReconciler(
            cfg, culler=culler, metrics=metrics, recorder=recorder,
            timeline=timeline_rec,
        )
    )
    manager.register(ProfileReconciler())
    manager.register(TensorboardReconciler(cfg))
    if cfg.scheduler_enabled:
        # fleet scheduler (kubeflow_tpu/scheduler/): gangs bind through its
        # placement annotation; shares the metrics registry so one /metrics
        # endpoint carries queue depth / time-to-bind / utilization too.
        # With sessions enabled its preemption path runs the suspend
        # barrier instead of killing victims outright. Sharded, this
        # manager's scheduler owns only its accelerator families — pools
        # belong to exactly one family, so per-family schedulers share no
        # free space (docs/architecture.md "control-plane sharding").
        from kubeflow_tpu.scheduler.controller import SchedulerReconciler

        # per-shard instance (the shard label keeps series disjoint), but
        # any one is a fleet-wide READ handle — the dashboard's queue-depth/
        # fragmentation readers scan every label set on the family — so the
        # first one built is published for webapps/dashboard.py
        sched_metrics = SchedulerMetrics(metrics.registry, shard=shard_label)
        manager.scheduler_metrics = shared.setdefault(
            "scheduler_metrics", sched_metrics
        )
        manager.register(
            SchedulerReconciler(
                metrics=sched_metrics,
                recorder=EventRecorder(),
                suspend_deadline_s=(
                    cfg.suspend_deadline_s if cfg.sessions_enabled else None
                ),
                families=(
                    router.families_for(shard_id)
                    if router is not None
                    else None
                ),
                router=router,
                shard_id=shard_id,
            )
        )
    if cfg.sessions_enabled:
        # session lifecycle (kubeflow_tpu/sessions/): suspend/resume state
        # machine over a write-ahead snapshot store; the culler's stop and
        # the scheduler's preemption both become resumable suspends
        from kubeflow_tpu.sessions.controller import (
            HttpSessionAgent,
            SessionReconciler,
        )
        from kubeflow_tpu.sessions.store import FileObjectStore, SnapshotStore

        if "snapshot_store" not in shared:
            store_root = os.environ.get(
                "SESSIONS_STORE_DIR", "/var/lib/kubeflow-tpu/sessions"
            )
            session_metrics = SessionMetrics(metrics.registry)
            # ONE store across shard managers in a process: chunk dedup is
            # cross-session by design and the pre-copy/restore pins live in
            # the store — per-shard stores would let one shard's GC sweep
            # chunks another shard still pins
            shared["snapshot_store"] = SnapshotStore(
                FileObjectStore(store_root), metrics=session_metrics
            )
            shared["session_metrics"] = session_metrics
        manager.register(
            SessionReconciler(
                # the store emits the chunk-level families itself (bytes,
                # dedup ratio, chunk-pool queue depth)
                shared["snapshot_store"],
                HttpSessionAgent(cfg.cluster_domain),
                config=cfg,
                metrics=shared["session_metrics"],
                recorder=EventRecorder(),
            )
        )
    if "profiler" not in shared:
        profiler = None
        # finding-triggered profile capture (obs/profiler.py): turns the
        # gang aggregator's frozen findings into bounded XLA trace captures
        # stored through the snapshot store. ONE per process (it consumes
        # the one aggregator's findings); rides the telemetry loop in
        # main(), NEVER a reconcile. Without sessions (no snapshot store)
        # captures still bind/ack and serve /debug/profiles, only the
        # durable trace payload is skipped.
        if gang is not None and cfg.profiler_enabled:
            from kubeflow_tpu.obs.profiler import CaptureController
            from kubeflow_tpu.utils.metrics import ProfilerMetrics

            profiler = CaptureController(
                cluster,
                gang,
                shared.get("snapshot_store"),
                ProfilerMetrics(metrics.registry),
                interval_s=cfg.telemetry_interval_s,
                cooldown_s=cfg.profiler_cooldown_s,
                max_active=cfg.profiler_max_active,
                steps=cfg.profiler_steps,
                recorder=recorder,
                cluster_domain=cfg.cluster_domain,
                port=cfg.telemetry_port,
            )
            # crash recovery: re-adopt bound-unacked captures from the CRs
            profiler.resume()
        shared["profiler"] = profiler
    profiler = shared["profiler"]
    manager.profiler = profiler
    if "capacity" not in shared:
        capacity = None
        # elastic capacity (kubeflow_tpu/capacity/): ONE autoscaler per
        # FLEET, like the ledger — its cycle reads the whole cluster and
        # talks to one cloud account, so in the one-process-per-shard
        # layout only shard 0's process runs it
        if cfg.capacity_enabled and (router is None or shard_id == 0):
            provider = _capacity_provider(cluster)
            if provider is None:
                log.warning(
                    "CAPACITY_ENABLED with no usable provider "
                    "(set CAPACITY_PROVIDER=fake|gke|eks); skipping"
                )
            else:
                from kubeflow_tpu.capacity.autoscaler import CapacityReconciler
                from kubeflow_tpu.utils.metrics import CapacityMetrics

                capacity = CapacityReconciler(
                    provider,
                    metrics=CapacityMetrics(
                        metrics.registry,
                        first_chip_target_s=cfg.first_chip_target_s,
                    ),
                    recorder=EventRecorder(),
                    pending_grace_s=cfg.capacity_pending_grace_s,
                    hysteresis_s=cfg.capacity_hysteresis_s,
                    max_pools_per_family=cfg.capacity_max_pools_per_family,
                    spot=cfg.capacity_spot,
                    suspend_deadline_s=cfg.suspend_deadline_s,
                )
                manager.register(capacity)
        shared["capacity"] = capacity
    else:
        capacity = shared["capacity"]
    # every shard's ops surface (and the webapps) reads the one autoscaler
    manager.capacity = capacity
    if cfg.enable_oauth_controller:
        # OpenShift companion (ref odh-notebook-controller): the openshift
        # overlay's ENABLE_OAUTH_CONTROLLER env was dead until this wired it
        from kubeflow_tpu.controllers.oauth_controller import OAuthReconciler

        manager.register(OAuthReconciler())
    return manager, metrics


def _capacity_provider(cluster):
    """Build the configured cloud provider. ``fake`` (the default against
    an in-memory cluster) drives the deterministic FakeCloudProvider;
    ``gke``/``eks`` build the hardened REST adapters from their env knobs.
    None when nothing usable is configured — capacity then stays off."""
    kind = os.environ.get("CAPACITY_PROVIDER", "").lower()
    if not kind:
        kind = "fake" if not hasattr(cluster, "session") else ""
    if kind == "fake":
        if hasattr(cluster, "session"):
            return None  # the fake provider writes Nodes; in-memory only
        from kubeflow_tpu.capacity.provider import FakeCloudProvider

        return FakeCloudProvider(cluster, clock=time.time)
    if kind == "gke":
        from kubeflow_tpu.cloud.gcp import GkeNodePoolProvider

        project = os.environ.get("GKE_PROJECT", "")
        location = os.environ.get("GKE_LOCATION", "")
        name = os.environ.get("GKE_CLUSTER", "")
        if not (project and location and name):
            return None
        return GkeNodePoolProvider(project, location, name)
    if kind == "eks":
        from kubeflow_tpu.cloud.aws import EksNodeGroupProvider

        name = os.environ.get("EKS_CLUSTER", "")
        return EksNodeGroupProvider(name) if name else None
    return None


def build_managers(
    cluster,
    config: ControllerConfig | None = None,
    *,
    fetch_kernels=fetch_kernels_http,
) -> tuple[list[Manager], NotebookMetrics]:
    """The sharded control plane: one manager per shard this process runs.

    ``SHARDS=1`` (default) returns exactly the single historical manager.
    ``SHARDS=N`` with ``SHARD_ID=i`` builds shard i only — the production
    layout, one process per shard (e.g. a StatefulSet ordinal), each behind
    its own leader lease. ``SHARDS=N`` without ``SHARD_ID`` builds all N in
    this process (standalone/demo — parallelism then comes from worker
    threads, not processes, but the partition and its invariants are the
    same ones the soaks audit)."""
    cfg = config or ControllerConfig.from_env()
    if cfg.shards <= 1:
        manager, metrics = build_manager(
            cluster, cfg, fetch_kernels=fetch_kernels
        )
        return [manager], metrics
    from kubeflow_tpu.runtime.sharding import ShardRouter

    router = ShardRouter(cfg.shards)
    if cfg.shard_id is not None:
        if not (0 <= cfg.shard_id < cfg.shards):
            raise ValueError(
                f"SHARD_ID {cfg.shard_id} outside [0, {cfg.shards})"
            )
        shard_ids = [cfg.shard_id]
    else:
        shard_ids = list(range(cfg.shards))
    shared: dict = {}
    managers = []
    for i in shard_ids:
        manager, _ = build_manager(
            cluster, cfg, fetch_kernels=fetch_kernels,
            router=router, shard_id=i, shared=shared,
        )
        managers.append(manager)
    return managers, shared["metrics"]


def watch_namespace_labels(path: str, manager, cluster):
    """Hot-reload the profile controller's default namespace labels from a
    mounted YAML file (ref fsnotify watch, profile_controller.go:356-405 +
    readDefaultLabelsFromFile :743-758). Loads once eagerly, then returns a
    FileWatcher (caller starts it; tests drive poll_once).

    ``manager`` may be one Manager or a list of them: sharded Profiles
    partition by namespace hash across EVERY shard's manager, so a reload
    delivered only to shard 0 would leave the other shards' namespaces on
    the built-in defaults forever."""
    import yaml

    from kubeflow_tpu.utils.filewatch import FileWatcher

    managers = manager if isinstance(manager, list) else [manager]
    targets = [
        (m, m.reconciler_for("Profile"))
        for m in managers
        if m.reconciler_for("Profile") is not None
    ]
    if not targets:
        return None

    def reload():
        try:
            with open(path) as f:
                labels = yaml.safe_load(f) or {}
        except (OSError, yaml.YAMLError) as e:
            # unlike the reference's os.Exit(1) on a read error, a transient
            # mount blip or half-written file shouldn't kill the manager;
            # keep the previous labels and retry on the next change
            log.warning("namespace labels file unreadable (%s); keeping", e)
            return
        if not isinstance(labels, dict):
            log.warning("namespace labels file is not a mapping; ignoring")
            return
        # bare keys ("team:") parse as None; the reference's map[string]string
        # unmarshals those to "" — match it
        labels = {str(k): "" if v is None else str(v) for k, v in labels.items()}
        log.info("default namespace labels ← %s: %s", path, labels)
        for m, profile_rec in targets:
            profile_rec.set_default_labels(labels, manager=m, cluster=cluster)

    reload()
    return FileWatcher(path, reload)


def serve_ops(
    metrics: NotebookMetrics,
    port: int = 8081,
    manager: Manager | None = None,
    metrics_port: int = 8080,
    health: HealthState | None = None,
) -> list[threading.Thread]:
    """Ops listeners, split like the reference's bind addresses (main.go:56:
    metrics-addr :8080, probe-addr :8081): probes on ``port`` — the
    Deployment's liveness/readiness target, which must stay alive even when
    metrics are turned off — and the unauthenticated /metrics on
    ``metrics_port``. 0 disables either listener independently (without the
    guard make_server would bind an OS-assigned ephemeral port and a
    listener the operator turned off would still serve)."""
    threads: list[threading.Thread] = []

    def _spawn(app: App, p: int) -> None:
        server = make_server("0.0.0.0", p, app)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        threads.append(t)

    if port:
        probes = App("controller-probes", csrf_protect=False)
        if health is None:
            health = HealthState()
        if manager is not None:
            health.attach_manager(manager)
        # /healthz + /readyz (live control loop, leader, watch freshness),
        # /debug/traces (the manager's reconcile span buffer), and
        # /debug/telemetry (the fleet collector's session store) ride the
        # probe port: cluster-internal like the probes, never the gateway
        install_probe_routes(
            probes, health,
            tracer=getattr(manager, "tracer", None) if manager else None,
        )
        telemetry = getattr(manager, "telemetry", None) if manager else None
        if telemetry is not None:
            from kubeflow_tpu.telemetry.collector import install_telemetry_route

            install_telemetry_route(probes, telemetry)
        # /debug/gang (+ /<ns>/<name> drilldown): per-host step timelines
        # and the straggler/desync verdicts — same cluster-internal surface
        gang = getattr(manager, "gang", None) if manager else None
        if gang is not None:
            from kubeflow_tpu.telemetry.gang import install_gang_route

            install_gang_route(probes, gang)
        # /debug/profiles (+ /<ns>/<name> drilldown): finding-triggered
        # capture requests, rate state, and the stored TensorBoard logdirs
        profiler = getattr(manager, "profiler", None) if manager else None
        if profiler is not None:
            from kubeflow_tpu.obs.profiler import install_profiles_route

            install_profiles_route(probes, profiler)
        # /debug/timeline/<ns>/<name>: the assembled click-to-ready
        # timeline, same cluster-internal surface as /debug/traces
        builder = getattr(manager, "timeline_builder", None) if manager else None
        if builder is not None:
            install_timeline_route(probes, builder)
        # /debug/explain/<ns>/<name>: the decoded placement explanation —
        # the operator's "why is my notebook still pending" page, same
        # cluster-internal surface as /debug/traces
        cluster = getattr(manager, "cluster", None) if manager else None
        if cluster is not None:
            from kubeflow_tpu.scheduler.explain import install_explain_route

            install_explain_route(probes, cluster)
        # /debug/ledger (+ /<namespace> drilldown): the chip-second
        # efficiency ledger; /debug/ itself indexes every debug endpoint
        # wired above (install_probe_routes mounted it)
        ledger = getattr(manager, "ledger", None) if manager else None
        if ledger is not None:
            from kubeflow_tpu.obs.ledger import install_ledger_routes

            install_ledger_routes(probes, ledger)
        # /debug/capacity: the autoscaler's open scale requests, revocation
        # notices, and idle dwells — same cluster-internal surface
        capacity = getattr(manager, "capacity", None) if manager else None
        if capacity is not None:
            from kubeflow_tpu.capacity.autoscaler import (
                install_capacity_route,
            )

            install_capacity_route(probes, capacity)
        _spawn(probes, port)
    if metrics_port:
        if manager is not None:
            wq_gauge = metrics.registry.gauge(
                "workqueue_stat", "Reconcile workqueue counters (native core)"
            )

            def observe_queue():
                for k, v in manager.queue_metrics().items():
                    wq_gauge.set(float(v), stat=k)

            metrics.registry.pre_expose(observe_queue)
        # count_requests=False: scrape hits are self-monitoring traffic
        _spawn(
            App("controller-metrics", csrf_protect=False,
                metrics_registry=metrics.registry, metrics_public=True,
                count_requests=False),
            metrics_port,
        )
    return threads


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    if os.environ.get("STANDALONE", "").lower() in ("1", "true"):
        from kubeflow_tpu.runtime.fake import FakeCluster

        cluster = FakeCluster()
    else:
        from kubeflow_tpu.runtime.kubeclient import KubeClient

        cluster = KubeClient()
    cfg = ControllerConfig.from_env()
    fleet = FleetKernelFetcher(cluster, cfg)
    managers, metrics = build_managers(cluster, cfg, fetch_kernels=fleet)
    # probes/debug routes ride the first manager this process runs; in the
    # production sharded layout that is THE shard (one process per SHARD_ID)
    manager = managers[0]
    leader_elect = os.environ.get("LEADER_ELECT", "").lower() in ("1", "true")
    # under election a replica starts as standby (readyz 503 until elected);
    # without election the single replica is born leader
    health = HealthState(leader_elected=not leader_elect)
    if hasattr(cluster, "session"):  # KubeClient: watch-freshness beats
        cluster.health = health
    ops_port = int(os.environ.get("OPS_PORT", "8081"))
    metrics_port_env = os.environ.get("METRICS_PORT")
    if metrics_port_env is not None:
        metrics_port = int(metrics_port_env)
    else:
        # METRICS_PORT unset: follow OPS_PORT=0's historical "fully headless"
        # meaning (what the deploy-shape tests pass) instead of surprising
        # them with a bound 8080
        metrics_port = 8080 if ops_port else 0
    serve_ops(
        metrics, port=ops_port, manager=manager, metrics_port=metrics_port,
        health=health,
    )
    if cfg.namespace_labels_path:
        labels_watch = watch_namespace_labels(
            cfg.namespace_labels_path, managers, cluster
        )
        if labels_watch is not None:
            labels_watch.start()
    stop = threading.Event()
    n_workers = int(os.environ.get("RECONCILE_WORKERS", "4"))

    reconciling = threading.Event()

    def start_workers(mgr, shard_id=None):
        mgr.run_workers(n_workers, stop)
        reconciling.set()
        health.set_leader(True)
        log.info(
            "controller manager running with %d workers%s",
            n_workers,
            "" if shard_id is None else f" (shard {shard_id}/{cfg.shards})",
        )

    def lease_name(shard_id) -> str:
        # sharded leases embed shard AND count: shard leaders of one
        # generation never contend with each other, and a mixed-SHARDS
        # rollout (two generations leading at once — operator error, see
        # docs/architecture.md) is visible in the Lease listing instead of
        # silently split-braining one lock
        if shard_id is None or cfg.shards <= 1:
            return "kubeflow-tpu-controller"
        return f"kubeflow-tpu-controller-shard-{shard_id}-of-{cfg.shards}"

    if leader_elect:
        # ref main.go:84-91: only the lease holder reconciles; standbys
        # wait. One elector per shard manager, each on its own lease.
        from kubeflow_tpu.runtime.leader import LeaderElector

        for mgr in managers:
            shard_id = getattr(mgr, "shard_id", None)
            elector = LeaderElector(
                cluster,
                name=lease_name(shard_id),
                namespace=os.environ.get("POD_NAMESPACE", "kubeflow-system"),
            )
            threading.Thread(
                target=elector.run,
                args=(lambda m=mgr, s=shard_id: start_workers(m, s),),
                daemon=True,
            ).start()
    else:
        for mgr in managers:
            start_workers(mgr, getattr(mgr, "shard_id", None))
    telemetry = getattr(manager, "telemetry", None)
    gang = getattr(manager, "gang", None)
    profiler = getattr(manager, "profiler", None)
    if telemetry is not None:
        # the fleet scrape runs on its OWN cadence, decoupled from both the
        # reconcile workers (never on that path) and the kernel-probe loop
        # below (whose period follows the culler's check period, not the
        # telemetry interval). Standbys skip it for the same reason they
        # skip kernel probing. The gang aggregator rides the same loop: its
        # per-host pass is interval-gated internally like the collector's.
        def telemetry_loop() -> None:
            while True:
                if reconciling.is_set():
                    try:
                        telemetry.collect()
                    except Exception:
                        log.exception("fleet telemetry scrape failed")
                    if gang is not None:
                        try:
                            gang.collect()
                        except Exception:
                            log.exception("gang telemetry pass failed")
                    if profiler is not None:
                        # capture pass AFTER the gang pass: a finding frozen
                        # this interval binds its capture the same interval
                        try:
                            profiler.collect()
                        except Exception:
                            log.exception("profile capture pass failed")
                time.sleep(cfg.telemetry_interval_s)

        threading.Thread(
            target=telemetry_loop, daemon=True, name="telemetry-collector"
        ).start()
    ledger = getattr(manager, "ledger", None)
    if ledger is not None:
        # the ledger ticks on its own cadence, off the reconcile path like
        # the collector; standbys skip it — a non-leader attributing the
        # same fleet would double the fleet's chip-seconds across replicas
        def ledger_loop() -> None:
            while True:
                if reconciling.is_set():
                    try:
                        ledger.tick()
                    except Exception:
                        log.exception("efficiency ledger tick failed")
                time.sleep(cfg.ledger_interval_s)

        threading.Thread(
            target=ledger_loop, daemon=True, name="efficiency-ledger"
        ).start()
    probe_period = max(10.0, cfg.idleness_check_minutes * 60.0 / 2)
    while True:
        # Workers drain the queue continuously; this loop keeps the fleet
        # kernel cache warm ahead of the culler's idleness checks. Standby
        # replicas (leader election, not elected) don't probe — nothing on
        # them consumes the cache, and N× probing every user notebook is
        # pure waste.
        if reconciling.is_set():
            try:
                fleet.refresh()
            except Exception:
                log.exception("fleet kernel refresh failed")
        time.sleep(probe_period)


if __name__ == "__main__":
    main()
