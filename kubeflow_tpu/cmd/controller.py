"""Controller-manager process (ref: each Go controller's ``main.go``).

Hosts every reconciler on one manager against the in-cluster API server, with
Prometheus metrics + probes on the ports the manifests wire up
(``manifests/base/controller.yaml``). Set ``STANDALONE=true`` to run against an
in-memory cluster (demo / kind-less smoke tests — the platform's own envtest).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from wsgiref.simple_server import make_server

from kubeflow_tpu.controllers.notebook_controller import NotebookReconciler
from kubeflow_tpu.controllers.profile_controller import ProfileReconciler
from kubeflow_tpu.controllers.tensorboard_controller import TensorboardReconciler
from kubeflow_tpu.culler.culler import Culler
from kubeflow_tpu.runtime.manager import Manager
from kubeflow_tpu.utils.config import ControllerConfig
from kubeflow_tpu.utils.metrics import NotebookMetrics
from kubeflow_tpu.webapps.base import App

log = logging.getLogger("controller")


def fetch_kernels_http(namespace: str, name: str):
    """Culler probe over the cluster network (ref culler.go:149-185; DEV mode
    uses the proxy URL shape from culler.go:156-160)."""
    import requests

    cfg = ControllerConfig.from_env()
    if cfg.dev:
        url = f"http://127.0.0.1:8001/api/v1/namespaces/{namespace}/services/{name}:80/proxy/notebook/{namespace}/{name}/api/kernels"
    else:
        url = (
            f"http://{name}.{namespace}.svc.{cfg.cluster_domain}"
            f"/notebook/{namespace}/{name}/api/kernels"
        )
    try:
        resp = requests.get(url, timeout=5)
        if resp.status_code != 200:
            return None
        return resp.json()
    except Exception:
        return None


def build_manager(cluster, config: ControllerConfig | None = None) -> tuple[Manager, NotebookMetrics]:
    cfg = config or ControllerConfig.from_env()
    metrics = NotebookMetrics()
    culler = Culler(
        enabled=cfg.enable_culling,
        cull_idle_minutes=cfg.cull_idle_minutes,
        check_period_minutes=cfg.idleness_check_minutes,
        fetch_kernels=fetch_kernels_http,
        clock=time.time,
    )
    manager = Manager(cluster, clock=time.time)
    manager.register(NotebookReconciler(cfg, culler=culler, metrics=metrics))
    manager.register(ProfileReconciler())
    manager.register(TensorboardReconciler(cfg))
    return manager, metrics


def serve_ops(metrics: NotebookMetrics, port: int = 8081) -> threading.Thread:
    app = App("controller-ops", csrf_protect=False,
              metrics_registry=metrics.registry)
    server = make_server("0.0.0.0", port, app)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    if os.environ.get("STANDALONE", "").lower() in ("1", "true"):
        from kubeflow_tpu.runtime.fake import FakeCluster

        cluster = FakeCluster()
    else:
        from kubeflow_tpu.runtime.kubeclient import KubeClient

        cluster = KubeClient()
    manager, metrics = build_manager(cluster)
    serve_ops(metrics)
    log.info("controller manager running")
    while True:
        # Watches enqueue keys; drain continuously. Requeue timers fire off
        # the wall clock (Manager(clock=time.time)).
        manager.tick()
        time.sleep(1.0)


if __name__ == "__main__":
    main()
