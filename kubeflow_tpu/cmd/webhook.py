"""Admission webhook server: AdmissionReview v1 in, JSONPatch out.

The deployable form of the mutators (ref HTTP server
``admission-webhook/main.go:685-702``; TLS certs mounted by the manifests and
hot-reloaded like the reference's certwatcher ``config.go:42-60``). Two paths,
matching ``manifests/base/webhook.yaml``:

  /apply-poddefault   PodDefault merge (webhooks/poddefaults.py)
  /inject-tpu-env     TPU worker identity (webhooks/tpu_env.py)
  /convert            CRD multi-version ConversionReview
                      (webhooks/conversion.py; ref notebook_conversion.go)
"""
from __future__ import annotations

import copy
import json
import logging
import os
import ssl
from wsgiref.simple_server import make_server

from werkzeug.wrappers import Request, Response

from kubeflow_tpu.runtime.fake import AdmissionDenied
from kubeflow_tpu.webhooks import poddefaults, tpu_env

log = logging.getLogger("webhook")


def json_patch(before: dict, after: dict, path: str = "") -> list[dict]:
    """Minimal RFC-6902 diff (replace/add/remove) for admission responses."""
    ops: list[dict] = []
    if isinstance(before, dict) and isinstance(after, dict):
        for key in before:
            escaped = key.replace("~", "~0").replace("/", "~1")
            if key not in after:
                ops.append({"op": "remove", "path": f"{path}/{escaped}"})
            else:
                ops.extend(json_patch(before[key], after[key], f"{path}/{escaped}"))
        for key in after:
            if key not in before:
                escaped = key.replace("~", "~0").replace("/", "~1")
                ops.append({"op": "add", "path": f"{path}/{escaped}",
                            "value": after[key]})
    elif isinstance(before, list) and isinstance(after, list):
        if before != after:
            ops.append({"op": "replace", "path": path, "value": after})
    elif before != after:
        ops.append({"op": "replace", "path": path, "value": after})
    return ops


def make_wsgi_app(cluster):
    tpu_mutate = tpu_env.make_mutator()

    def handle(environ, start_response):
        request = Request(environ)
        if request.path == "/convert":
            from kubeflow_tpu.webhooks import conversion

            try:
                review = request.get_json()
            except Exception:
                resp = Response("bad ConversionReview", status=400)
                return resp(environ, start_response)
            body = json.dumps(conversion.convert_review(review or {}))
            resp = Response(body, mimetype="application/json")
            return resp(environ, start_response)
        try:
            review = request.get_json()
            obj = review["request"]["object"]
            uid = review["request"]["uid"]
        except Exception:
            resp = Response("bad AdmissionReview", status=400)
            return resp(environ, start_response)
        before = copy.deepcopy(obj)
        response: dict = {"uid": uid, "allowed": True}
        try:
            if request.path == "/apply-poddefault":
                mutated = poddefaults.mutator(obj, cluster)
            elif request.path == "/inject-tpu-env":
                mutated = tpu_mutate(obj, cluster)
            else:
                resp = Response("not found", status=404)
                return resp(environ, start_response)
            patch = json_patch(before, mutated)
            if patch:
                response["patchType"] = "JSONPatch"
                response["patch"] = __import__("base64").b64encode(
                    json.dumps(patch).encode()
                ).decode()
        except AdmissionDenied as e:
            response = {
                "uid": uid,
                "allowed": False,
                "status": {"code": 403, "message": str(e)},
            }
        body = json.dumps(
            {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
             "response": response}
        )
        resp = Response(body, mimetype="application/json")
        return resp(environ, start_response)

    return handle


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    from kubeflow_tpu.runtime.kubeclient import KubeClient

    cluster = KubeClient()
    port = int(os.environ.get("PORT", "8443"))
    cert_dir = os.environ.get("CERT_DIR", "/etc/webhook/certs")
    server = make_server("0.0.0.0", port, make_wsgi_app(cluster))
    cert, key = f"{cert_dir}/tls.crt", f"{cert_dir}/tls.key"
    if os.path.isfile(cert):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        server.socket = ctx.wrap_socket(server.socket, server_side=True)
    log.info("webhook serving on :%d", port)
    server.serve_forever()


if __name__ == "__main__":
    main()
