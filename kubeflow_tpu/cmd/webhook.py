"""Admission webhook server: AdmissionReview v1 in, JSONPatch out.

The deployable form of the mutators (ref HTTP server
``admission-webhook/main.go:685-702``; TLS certs mounted by the manifests and
hot-reloaded like the reference's certwatcher ``config.go:42-60``). Two paths,
matching ``manifests/base/webhook.yaml``:

  /apply-poddefault   PodDefault merge (webhooks/poddefaults.py)
  /inject-tpu-env     TPU worker identity (webhooks/tpu_env.py)
  /inject-oauth       OpenShift oauth-proxy sidecar (oauth_controller.py;
                      registered by the openshift overlay's webhook config)
  /convert            CRD multi-version ConversionReview
                      (webhooks/conversion.py; ref notebook_conversion.go)
"""
from __future__ import annotations

import copy
import json
import logging
import os
from wsgiref.simple_server import make_server

from werkzeug.wrappers import Request, Response

from kubeflow_tpu.runtime.fake import AdmissionDenied
from kubeflow_tpu.webhooks import poddefaults, tpu_env

log = logging.getLogger("webhook")


def json_patch(before: dict, after: dict, path: str = "") -> list[dict]:
    """Minimal RFC-6902 diff (replace/add/remove) for admission responses."""
    ops: list[dict] = []
    if isinstance(before, dict) and isinstance(after, dict):
        for key in before:
            escaped = key.replace("~", "~0").replace("/", "~1")
            if key not in after:
                ops.append({"op": "remove", "path": f"{path}/{escaped}"})
            else:
                ops.extend(json_patch(before[key], after[key], f"{path}/{escaped}"))
        for key in after:
            if key not in before:
                escaped = key.replace("~", "~0").replace("/", "~1")
                ops.append({"op": "add", "path": f"{path}/{escaped}",
                            "value": after[key]})
    elif isinstance(before, list) and isinstance(after, list):
        if before != after:
            ops.append({"op": "replace", "path": path, "value": after})
    elif before != after:
        ops.append({"op": "replace", "path": path, "value": after})
    return ops


def make_wsgi_app(cluster):
    tpu_mutate = tpu_env.make_mutator()

    def handle(environ, start_response):
        request = Request(environ)
        if request.path == "/convert":
            from kubeflow_tpu.webhooks import conversion

            try:
                review = request.get_json()
            except Exception:
                resp = Response("bad ConversionReview", status=400)
                return resp(environ, start_response)
            body = json.dumps(conversion.convert_review(review or {}))
            resp = Response(body, mimetype="application/json")
            return resp(environ, start_response)
        try:
            review = request.get_json()
            obj = review["request"]["object"]
            uid = review["request"]["uid"]
        except Exception:
            resp = Response("bad AdmissionReview", status=400)
            return resp(environ, start_response)
        before = copy.deepcopy(obj)
        response: dict = {"uid": uid, "allowed": True}
        try:
            if request.path == "/apply-poddefault":
                mutated = poddefaults.mutator(obj, cluster)
            elif request.path == "/inject-tpu-env":
                mutated = tpu_mutate(obj, cluster)
            elif request.path == "/inject-oauth":
                # OpenShift companion webhook (ref notebook_webhook.go
                # Handle/InjectOAuthProxy): oauth-proxy sidecar for
                # annotated Notebooks; registered by the openshift overlay
                from kubeflow_tpu.controllers.oauth_controller import (
                    inject_oauth_proxy,
                )

                mutated = inject_oauth_proxy(obj, cluster)
            else:
                resp = Response("not found", status=404)
                return resp(environ, start_response)
            patch = json_patch(before, mutated)
            if patch:
                response["patchType"] = "JSONPatch"
                response["patch"] = __import__("base64").b64encode(
                    json.dumps(patch).encode()
                ).decode()
        except AdmissionDenied as e:
            response = {
                "uid": uid,
                "allowed": False,
                "status": {"code": 403, "message": str(e)},
            }
        body = json.dumps(
            {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
             "response": response}
        )
        resp = Response(body, mimetype="application/json")
        return resp(environ, start_response)

    return handle


def wait_for_cert(cert_dir: str, timeout: float | None = None, poll: float = 1.0) -> bool:
    """Block until both tls.crt and tls.key exist (a webhook pod can start
    before cert-manager populates the Secret mount; serving plain HTTP in
    that window — and forever after — would break every admission call, so
    TLS-required deployments wait here instead)."""
    import time

    deadline = None if timeout is None else time.monotonic() + timeout
    while not (
        os.path.isfile(f"{cert_dir}/tls.crt")
        and os.path.isfile(f"{cert_dir}/tls.key")
    ):
        if deadline is not None and time.monotonic() >= deadline:
            return False
        log.info("waiting for TLS cert in %s", cert_dir)
        time.sleep(poll)
    return True


def make_server_with_tls(cluster, port: int, cert_dir: str):
    """HTTPS server whose cert hot-reloads on rotation (ref certwatcher,
    config.go:42-60). Returns (server, cert_watcher|None — None means plain
    HTTP, for dev runs with no cert dir); caller starts the watcher thread
    (tests drive poll_once deterministically instead)."""
    from kubeflow_tpu.utils.filewatch import CertWatcher

    server = make_server("0.0.0.0", port, make_wsgi_app(cluster))
    cert, key = f"{cert_dir}/tls.crt", f"{cert_dir}/tls.key"
    watcher = None
    if os.path.isfile(cert):
        watcher = CertWatcher(cert, key)
        server.socket = watcher.context.wrap_socket(
            server.socket, server_side=True
        )
    return server, watcher


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    from kubeflow_tpu.runtime.kubeclient import KubeClient

    cluster = KubeClient()
    port = int(os.environ.get("PORT", "8443"))
    cert_dir = os.environ.get("CERT_DIR", "/etc/webhook/certs")
    # TLS is required whenever a cert dir is deployed (explicit env or the
    # manifest's mount path exists): wait for the Secret mount to be
    # populated rather than silently serving plain HTTP forever.
    if os.environ.get("CERT_DIR") or os.path.isdir(cert_dir):
        wait_for_cert(cert_dir)
    server, watcher = make_server_with_tls(cluster, port, cert_dir)
    if watcher is not None:
        watcher.start()
    # PORT=0 binds an ephemeral port; log the REAL one so harnesses can
    # parse it (avoids the pick-a-free-port TOCTOU race)
    log.info("webhook serving on :%d", server.server_address[1])
    server.serve_forever()


if __name__ == "__main__":
    main()
