"""Process entrypoints."""
