"""Web-app server process: ``python -m kubeflow_tpu.cmd.serve <app>``.

Serves one of the WSGI backends (jupyter | volumes | tensorboards | dashboard |
kfam) against the in-cluster API (or STANDALONE in-memory cluster), the way
each reference backend runs its Flask app under gunicorn
(``crud-web-apps/*/backend/entrypoint.py``).
"""
from __future__ import annotations

import logging
import os
import sys
from wsgiref.simple_server import make_server

from kubeflow_tpu.auth.rbac import Authorizer

APPS = ("jupyter", "volumes", "tensorboards", "dashboard", "kfam")


def build_app(name: str, cluster=None):
    if cluster is None:
        if os.environ.get("STANDALONE", "").lower() in ("1", "true"):
            from kubeflow_tpu.runtime.fake import FakeCluster

            cluster = FakeCluster()
        else:
            from kubeflow_tpu.runtime.kubeclient import KubeClient

            cluster = KubeClient()
    admins = {
        a for a in os.environ.get("CLUSTER_ADMINS", "").split(",") if a
    }
    if name == "jupyter":
        from kubeflow_tpu.webapps.jupyter import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "volumes":
        from kubeflow_tpu.webapps.volumes import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "tensorboards":
        from kubeflow_tpu.webapps.tensorboards import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "dashboard":
        from kubeflow_tpu.webapps.dashboard import create_app

        return create_app(cluster, cluster_admins=admins)
    if name == "kfam":
        from kubeflow_tpu.webapps.kfam_app import create_app

        return create_app(cluster, cluster_admins=admins)
    raise SystemExit(f"unknown app {name!r}; choose from {APPS}")


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    name = sys.argv[1] if len(sys.argv) > 1 else "jupyter"
    port = int(os.environ.get("PORT", "5000"))
    app = build_app(name)
    # unauthenticated /metrics lives on a dedicated ops port (OPS_PORT=0
    # disables), like the controller's serve_ops; the app-port /metrics
    # requires an authenticated caller. Default derives from PORT (5000 →
    # 8082, the port the manifests scrape) so two apps on one dev host
    # don't collide on a shared hard-coded ops port.
    ops_port = int(os.environ.get("OPS_PORT", str(port + 3082)))
    if ops_port:
        import threading

        ops_server = make_server("0.0.0.0", ops_port, app.ops_app())
        threading.Thread(target=ops_server.serve_forever, daemon=True).start()
        logging.info("serving %s ops (metrics) on :%d", name, ops_port)
    logging.info("serving %s on :%d", name, port)
    make_server("0.0.0.0", port, app).serve_forever()


if __name__ == "__main__":
    main()
