"""Web-app server process: ``python -m kubeflow_tpu.cmd.serve <app>``.

Serves one of the WSGI backends (jupyter | volumes | tensorboards | dashboard |
kfam) against the in-cluster API (or STANDALONE in-memory cluster), the way
each reference backend runs its Flask app under gunicorn
(``crud-web-apps/*/backend/entrypoint.py``).
"""
from __future__ import annotations

import logging
import os
import sys
from wsgiref.simple_server import make_server

from kubeflow_tpu.auth.rbac import Authorizer

APPS = ("jupyter", "volumes", "tensorboards", "dashboard", "kfam")


def build_app(name: str, cluster=None):
    if cluster is None:
        if os.environ.get("STANDALONE", "").lower() in ("1", "true"):
            from kubeflow_tpu.runtime.fake import FakeCluster

            cluster = FakeCluster()
        else:
            from kubeflow_tpu.runtime.kubeclient import KubeClient

            cluster = KubeClient()
    admins = {
        a for a in os.environ.get("CLUSTER_ADMINS", "").split(",") if a
    }
    if name == "jupyter":
        from kubeflow_tpu.webapps.jupyter import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "volumes":
        from kubeflow_tpu.webapps.volumes import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "tensorboards":
        from kubeflow_tpu.webapps.tensorboards import create_app

        return create_app(cluster, authorizer=Authorizer(cluster, cluster_admins=admins))
    if name == "dashboard":
        from kubeflow_tpu.webapps.dashboard import create_app

        return create_app(cluster, cluster_admins=admins)
    if name == "kfam":
        from kubeflow_tpu.webapps.kfam_app import create_app

        return create_app(cluster, cluster_admins=admins)
    raise SystemExit(f"unknown app {name!r}; choose from {APPS}")


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    name = sys.argv[1] if len(sys.argv) > 1 else "jupyter"
    port = int(os.environ.get("PORT", "5000"))
    app = build_app(name)
    logging.info("serving %s on :%d", name, port)
    make_server("0.0.0.0", port, app).serve_forever()


if __name__ == "__main__":
    main()
