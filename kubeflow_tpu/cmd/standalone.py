"""Single-process platform demo: every app + controllers on one port.

``python -m kubeflow_tpu.cmd.standalone`` boots the whole platform against the
in-memory cluster — the runnable analog of the reference's KinD smoke tests
(SURVEY.md §4 "kind tests"), with a fake kubelet driving pods to Ready:

    /            central dashboard (iframes the child apps, like the reference)
    /jupyter/    spawner + notebook management
    /volumes/    PVC management
    /tensorboards/
    /kfam/       access management REST

An authenticating-gateway middleware injects the identity header (the role
Istio plays in production). Seeded with a demo profile and TPU node pools so
the spawner's topology picker is live.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable
from wsgiref.simple_server import WSGIRequestHandler, make_server

from werkzeug.middleware.dispatcher import DispatcherMiddleware

from kubeflow_tpu.api import types as api
from kubeflow_tpu.auth.rbac import Authorizer
from kubeflow_tpu.cmd.controller import build_manager
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.utils.config import ControllerConfig
from kubeflow_tpu.webapps import dashboard, jupyter, kfam_app, tensorboards, volumes
from kubeflow_tpu.webapps.cache import ReadCache
from kubeflow_tpu.webhooks import poddefaults, tpu_env

log = logging.getLogger("standalone")


@dataclasses.dataclass
class Platform:
    wsgi: Callable
    cluster: FakeCluster
    manager: object
    tick: Callable[[], None]   # one control-loop turn: kubelet + reconciles

    # tuple-compat with earlier call sites: (gateway, cluster, manager, loop)
    def __iter__(self):
        return iter((self.wsgi, self.cluster, self.manager, self._control_loop))

    def _control_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.tick()
            except Exception:
                log.exception("control loop iteration failed")
            stop.wait(0.5)


def build_platform(
    demo_user: str = "demo@example.com",
    config: ControllerConfig | None = None,
) -> Platform:
    cluster = FakeCluster()
    tpu_env.install(cluster)
    poddefaults.install(cluster)
    # programmatic defaults (scheduler/sessions/telemetry off): the
    # in-memory demo has no real pods to scrape or preempt. An embedder
    # passing its own config gets the full wiring.
    manager, metrics = build_manager(cluster, config or ControllerConfig())

    # seed: demo tenant + schedulable TPU node pools
    cluster.add_tpu_node_pool("v4", "2x2x2")
    cluster.add_tpu_node_pool("v4", "2x2x1")
    cluster.add_tpu_node_pool("v5e", "4x4")
    cluster.create(api.profile(demo_user.split("@")[0], demo_user))
    manager.run_until_idle()

    admins = {demo_user}
    # None under the default in-memory config; build_manager hangs the
    # collector off the manager when a caller-supplied config enables
    # telemetry, and the webapps then serve its series
    telemetry = getattr(manager, "telemetry", None)
    gang = getattr(manager, "gang", None)
    profiler = getattr(manager, "profiler", None)
    ledger = getattr(manager, "ledger", None)
    capacity = getattr(manager, "capacity", None)
    # ONE watch-backed read layer for every app (webapps/cache.py): each
    # create_app adds its kinds to the shared cache instead of building its
    # own, so one watch set feeds every serving surface
    read_cache = ReadCache(cluster).start()
    wsgi = DispatcherMiddleware(
        dashboard.create_app(
            cluster, cluster_admins=admins, metrics=metrics,
            telemetry=telemetry,
            gang=gang,
            profiler=profiler,
            slo=getattr(manager, "slo", None),
            scheduler=getattr(manager, "scheduler_metrics", None),
            ledger=ledger,
            capacity=capacity,
            cache=read_cache,
        ),
        {
            "/jupyter": jupyter.create_app(
                cluster,
                authorizer=Authorizer(cluster, cluster_admins=admins),
                metrics=metrics,
                telemetry=telemetry,
                gang=gang,
                profiler=profiler,
                timeline=getattr(manager, "timeline_builder", None),
                ledger=ledger,
                capacity=capacity,
                cache=read_cache,
            ),
            "/volumes": volumes.create_app(
                cluster,
                authorizer=Authorizer(cluster, cluster_admins=admins),
                cache=read_cache,
            ),
            "/tensorboards": tensorboards.create_app(
                cluster,
                authorizer=Authorizer(cluster, cluster_admins=admins),
                cache=read_cache,
            ),
            "/kfam": kfam_app.create_app(cluster, cluster_admins=admins),
        },
    )

    def gateway(environ, start_response):
        # the Istio-gateway role: OVERWRITE any inbound identity header (real
        # gateways strip client-supplied identity; honoring it would let any
        # network peer impersonate any user)
        environ["HTTP_KUBEFLOW_USERID"] = demo_user
        return wsgi(environ, start_response)

    def tick() -> None:
        cluster.step_kubelet()
        if capacity is not None and hasattr(capacity.provider, "step"):
            # the demo's cloud: finish due provisioning / land revocation
            # kills (infrastructure-side, like the fake kubelet above)
            capacity.provider.step()
        manager.tick()
        if ledger is not None:
            # interval-gated, off the reconcile path (the controller
            # process runs this on its own thread; the demo's single loop
            # is the same cadence contract)
            ledger.tick()

    return Platform(wsgi=gateway, cluster=cluster, manager=manager, tick=tick)


class QuietHandler(WSGIRequestHandler):
    def log_message(self, *args):  # keep the demo console readable
        pass


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    port = int(os.environ.get("PORT", "8000"))
    # loopback by default: the demo gateway grants a fixed admin identity, so
    # exposing it beyond the host must be an explicit operator choice
    host = os.environ.get("HOST", "127.0.0.1")
    user = os.environ.get("DEMO_USER", "demo@example.com")
    platform = build_platform(user)
    stop = threading.Event()
    threading.Thread(
        target=platform._control_loop, args=(stop,), daemon=True
    ).start()
    log.info("platform demo on http://%s:%d (user %s)", host, port, user)
    try:
        make_server(host, port, platform.wsgi, handler_class=QuietHandler).serve_forever()
    finally:
        stop.set()


if __name__ == "__main__":
    main()
