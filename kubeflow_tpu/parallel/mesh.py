"""Device-mesh construction and sharding rules.

The platform's scaling model (SURVEY.md §7, "How to Scale Your Model" recipe):
pick a mesh, annotate shardings, let XLA insert the collectives over ICI.
Axis vocabulary used across the framework:

    dcn      data parallelism across slices over the data-center network
             (multislice: gradient psum rides DCN, everything else stays
             inside a slice — SURVEY.md §7 stage 3, MEGASCALE_* env)
    stage    pipeline parallelism (layer groups; ppermute'd activations —
             parallel/pipeline.py)
    data     pure data parallelism (batch split, psum'd grads over DCN/ICI)
    fsdp     data parallelism with parameter/optimizer sharding (ZeRO-3 style:
             params all-gathered per layer, grads reduce-scattered)
    tensor   tensor/model parallelism (matmul column/row splits)
    seq      sequence/context parallelism (ring attention, blockwise KV)
    expert   expert parallelism (MoE expert-dim sharding + all_to_all
             dispatch — models/moe.py)

Meshes are constructed so the fastest-varying axes map to the tightest ICI
neighborhoods (tensor innermost), matching TPU torus locality.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dcn", "stage", "data", "fsdp", "seq", "expert", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A named parallelism layout, e.g. MeshPlan(data=2, fsdp=2, tensor=2)."""

    dcn: int = 1
    stage: int = 1
    data: int = 1
    fsdp: int = 1
    seq: int = 1
    expert: int = 1
    tensor: int = 1

    @property
    def size(self) -> int:
        return (
            self.dcn * self.stage * self.data * self.fsdp
            * self.seq * self.expert * self.tensor
        )

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXES}


def create_mesh(
    plan: MeshPlan,
    devices: Sequence | None = None,
    *,
    physical_topology: Sequence[int] | None = None,
) -> Mesh:
    """Build the named Mesh; with ``physical_topology`` (the slice's torus
    shape, e.g. ``(4, 4, 4)``), devices are ordered by the native placement
    solver (``tpu/placement.py``) so high-traffic logical axes ride
    contiguous ICI rings instead of whatever order ``jax.devices()`` returns.
    """
    devices = list(devices if devices is not None else jax.devices())
    if plan.size != len(devices):
        raise ValueError(
            f"mesh plan needs {plan.size} devices "
            f"({plan.axis_sizes()}), have {len(devices)}"
        )
    shape = tuple(plan.axis_sizes()[a] for a in AXES)
    if physical_topology is not None and len(devices) > 1:
        from kubeflow_tpu.tpu import placement

        order = placement.mesh_device_order(
            physical_topology,
            shape,
            weights=[placement.DEFAULT_WEIGHTS[a] for a in AXES],
        )
        # The solver's indices are row-major torus coordinates; jax.devices()
        # enumerates by (process, local id), which need not match. Sort by
        # device.coords when the runtime exposes it (TPU does).
        devices = _torus_row_major(devices, physical_topology)
        arr = np.asarray(devices, dtype=object)[order.ravel()].reshape(shape)
    else:
        arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXES)


def _torus_row_major(devices: Sequence, phys_dims: Sequence[int]) -> list:
    """Order devices by row-major physical torus coordinates.

    TPU devices expose ``.coords`` (chip position in the torus) and
    ``.core_on_chip``; backends without coords (CPU fixtures) keep their
    enumeration order, which tests treat as the torus order by construction.
    """
    if not all(
        getattr(d, "coords", None) is not None
        and len(getattr(d, "coords") or ()) == len(phys_dims)
        for d in devices
    ):
        return list(devices)

    def key(d):
        idx = 0
        for c, dim in zip(d.coords, phys_dims):
            idx = idx * dim + int(c)
        return (idx, getattr(d, "core_on_chip", 0))

    return sorted(devices, key=key)


def auto_plan(n_devices: int, *, tensor: int = 1, seq: int = 1) -> MeshPlan:
    """Default layout: requested tensor/seq degree, rest goes to fsdp."""
    rest, rem = divmod(n_devices, tensor * seq)
    if rem:
        raise ValueError(
            f"{n_devices} devices not divisible by tensor={tensor} * seq={seq}"
        )
    return MeshPlan(fsdp=rest, tensor=tensor, seq=seq)


def batch_spec() -> P:
    """Batch dims shard over every data-ish axis (dcn × data × fsdp)."""
    return P(("dcn", "data", "fsdp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------- param rules


def fsdp_param_spec(path: tuple[str, ...], value) -> P:
    """ZeRO-3-style parameter sharding rule.

    Shard the largest dim of every >=2-d parameter over ``fsdp`` (XLA turns the
    per-layer use into all-gather, and grad accumulation into reduce-scatter).
    1-d params (biases, norm scales) stay replicated — sharding them buys
    nothing and costs collective launches.
    """
    shape = getattr(value, "shape", ())
    if len(shape) < 2:
        return P()
    largest = int(np.argmax(shape))
    if shape[largest] < 128:  # don't shard tiny dims below tile size
        return P()
    spec: list = [None] * len(shape)
    spec[largest] = "fsdp"
    return P(*spec)


def tensor_param_spec(path: tuple[str, ...], value) -> P:
    """Megatron-style TP rule for transformer blocks, composed with fsdp.

    Column-parallel for QKV/up projections (last dim over ``tensor``),
    row-parallel for output/down projections (first dim over ``tensor``).
    Identified by path naming convention: *_col / *_row markers set by the
    model code (models/transformer.py).
    """
    shape = getattr(value, "shape", ())
    joined = "/".join(path)
    if len(shape) < 2:
        return P()
    if any(m in joined for m in ("q_proj", "k_proj", "v_proj", "up_proj", "gate_proj")):
        return P("fsdp", "tensor")
    if any(m in joined for m in ("o_proj", "down_proj")):
        return P("tensor", "fsdp")
    if "embed" in joined:
        return P(None, "fsdp")
    return fsdp_param_spec(path, value)


def moe_param_spec(path: tuple[str, ...], value) -> P:
    """Expert-parallel rule for MoE models, composed with the TP rule.

    Contract (leaf names set by models/moe.py, same idea as the *_proj
    convention in tensor_param_spec): expert tables are 3-d params whose leaf
    is named ``experts_wi`` / ``experts_wo`` — dim 0 shards over ``expert``,
    the hidden dim over ``tensor`` (column-parallel wi, row-parallel wo).
    ``router`` leaves are tiny and stay replicated so every device computes
    identical gating. Everything else follows the transformer TP rule.
    """
    shape = getattr(value, "shape", ())
    leaf = path[-1] if path else ""
    if len(shape) == 3 and leaf == "experts_wi":
        return P("expert", "fsdp", "tensor")
    if len(shape) == 3 and leaf == "experts_wo":
        return P("expert", "tensor", "fsdp")
    if leaf == "router":
        return P()
    return tensor_param_spec(path, value)


def _legalize(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Drop axis assignments a dim can't honor (size not divisible by the mesh
    axis product) — odd mesh degrees degrade to replication, never error."""
    out = []
    for i, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        degree = math.prod(mesh.shape[a] for a in axes)
        out.append(entry if shape[i] % degree == 0 else None)
    return P(*out)


def param_shardings(mesh: Mesh, params, rule=fsdp_param_spec):
    """Map a param pytree to NamedShardings via a rule(path, value) -> P."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def path_str(kp):
        return tuple(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)

    specs = {
        jax.tree_util.keystr(kp): NamedSharding(
            mesh, _legalize(rule(path_str(kp), v), getattr(v, "shape", ()), mesh)
        )
        for kp, v in flat
    }

    def lookup(kp, v):
        return specs[jax.tree_util.keystr(kp)]

    return jax.tree_util.tree_map_with_path(lookup, params)
