"""Pipeline parallelism over the ``stage`` mesh axis (GPipe schedule).

The reference has no training fan-out at all (SURVEY.md §2 note — its
StatefulSets are pinned to one replica); pipeline parallelism is part of the
distributed compute path this framework adds. Expressed the TPU way:

- transformer blocks are grouped into ``n_stages`` stages whose parameters are
  stacked on a leading stage dim sharded over ``stage`` — every device holds
  only its own stage's weights;
- the schedule is a single ``lax.scan`` over ``n_micro + n_stages - 1`` ticks
  inside one ``shard_map``: each tick runs every stage in parallel on its
  in-flight microbatch, then rotates activations to the next stage with
  ``lax.ppermute`` (ICI neighbor traffic, no host round-trips);
- backward is plain ``jax.grad`` through the scan — the transpose of
  ``ppermute`` is the reverse rotation, so AD derives the reverse-pipeline
  schedule automatically;
- each stage step is ``jax.checkpoint``-ed (GPipe rematerialization), so live
  activation memory is one microbatch per stage, not the whole batch.

Composes with data parallelism (batch dims sharded over ``data``/``fsdp``
inside the same shard_map). Tensor/sequence parallelism inside a stage would
need manual collectives in the stage body and lives in the non-pipelined
configs for now (``parallel/train.py``).
"""
from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.transformer import (
    Block,
    RMSNorm,
    TransformerConfig,
    lm_loss,
)
from kubeflow_tpu.parallel import compat


class PipelineStage(nn.Module):
    """``num_blocks`` consecutive transformer blocks — one pipeline stage."""

    cfg: TransformerConfig
    num_blocks: int

    @nn.compact
    def __call__(self, x, positions):
        for i in range(self.num_blocks):
            x = Block(self.cfg, name=f"block_{i}")(x, positions)
        return x


def init_pipeline_lm(cfg: TransformerConfig, mesh: Mesh, rng, tokens):
    """Initialize {embed, stages, final_norm} with stage weights stacked on a
    leading dim and placed shard-per-device over the ``stage`` axis."""
    n_stages = mesh.shape["stage"]
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by "
            f"{n_stages} pipeline stages"
        )
    blocks_per_stage = cfg.num_layers // n_stages
    B, S = tokens.shape
    rng_e, rng_s, rng_n = jax.random.split(rng, 3)

    embed = _embed(cfg)
    embed_params = embed.init(rng_e, tokens)["params"]

    stage = PipelineStage(cfg, blocks_per_stage)
    x = jnp.zeros((B, S, cfg.embed_dim), cfg.dtype)
    positions = jnp.arange(S)
    stage_params = jax.vmap(
        lambda r: stage.init(r, x, positions)["params"]
    )(jax.random.split(rng_s, n_stages))

    norm_params = RMSNorm().init(rng_n, x)["params"]

    repl = NamedSharding(mesh, P())
    params = {
        "embed": jax.device_put(embed_params, repl),
        "stages": jax.device_put(
            stage_params, NamedSharding(mesh, P("stage"))
        ),
        "final_norm": jax.device_put(norm_params, repl),
    }
    return params


def pipeline_forward(
    cfg: TransformerConfig,
    mesh: Mesh,
    params,
    tokens,
    *,
    num_microbatches: int,
):
    """Full forward: embed → pipelined stages → final norm → tied logits."""
    n_stages = mesh.shape["stage"]
    B, S = tokens.shape
    if B % num_microbatches != 0:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches"
        )
    mb = B // num_microbatches

    embed = _embed(cfg)
    x = embed.apply({"params": params["embed"]}, tokens)
    xs = x.reshape(num_microbatches, mb, S, cfg.embed_dim)
    positions = jnp.arange(S)

    stage = PipelineStage(cfg, cfg.num_layers // n_stages)

    @jax.checkpoint
    def stage_fn(p, x, positions):
        return stage.apply({"params": p}, x, positions)

    ys = _pipelined(stage_fn, mesh, n_stages, num_microbatches)(
        params["stages"], xs, positions
    )
    y = ys.reshape(B, S, cfg.embed_dim)
    y = RMSNorm().apply({"params": params["final_norm"]}, y)
    return embed.apply(
        {"params": params["embed"]},
        y.astype(jnp.float32),
        method=nn.Embed.attend,
    )


def _pipelined(stage_fn, mesh: Mesh, n_stages: int, n_micro: int):
    """shard_map wrapper running the GPipe tick loop on every stage at once."""
    batch_axes = ("data", "fsdp")

    def body(stage_params, xs, positions):
        # Each device sees its stage's slice with a leading dim of 1.
        local = jax.tree_util.tree_map(
            lambda p: jnp.squeeze(p, 0), stage_params
        )
        idx = lax.axis_index("stage")
        rotate = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 feeds microbatch t (clamped — bubble ticks recompute the
            # last microbatch and write nothing); others take the rotated
            # activations from their predecessor.
            feed = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            x_in = jnp.where(idx == 0, feed, state)
            y = stage_fn(local, x_in, positions)
            out_t = t - (n_stages - 1)
            written = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_t, 0, n_micro - 1), 0
            )
            outputs = jnp.where(
                (idx == n_stages - 1) & (out_t >= 0), written, outputs
            )
            state = lax.ppermute(y, "stage", rotate)
            return (state, outputs), None

        zeros = jnp.zeros_like(xs)
        (state, outputs), _ = lax.scan(
            tick,
            (jnp.zeros_like(xs[0]), zeros),
            jnp.arange(n_micro + n_stages - 1),
        )
        # Only the last stage holds real outputs; broadcast them to every
        # stage so the result is stage-replicated for the code outside.
        return lax.psum(
            jnp.where(idx == n_stages - 1, outputs, zeros), "stage"
        )

    return compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("stage"), P(None, batch_axes), P(None)),
        out_specs=P(None, batch_axes),
        check_vma=False,
    )


def make_pipeline_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    tx,
    *,
    num_microbatches: int,
):
    """(init, step): a jitted LM training step over the pipelined forward."""

    def init(rng, tokens):
        params = init_pipeline_lm(cfg, mesh, rng, tokens)
        opt_state = tx.init(params)
        return params, opt_state

    forward = partial(
        pipeline_forward, cfg, mesh, num_microbatches=num_microbatches
    )

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            return lm_loss(forward(p, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state_ = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state_, loss

    return init, step


def _embed(cfg: TransformerConfig) -> nn.Embed:
    return nn.Embed(
        cfg.vocab_size,
        cfg.embed_dim,
        dtype=cfg.dtype,
        param_dtype=jnp.float32,
    )
