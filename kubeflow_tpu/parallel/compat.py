"""JAX API compatibility layer for the image's pinned JAX build.

The platform's images pin one JAX build per release; notebook code and the
parallel/ modules must run on whatever that build ships. Two surfaces have
moved across the JAX versions the fleet sees, and every caller in-tree goes
through this module instead of probing ``jax`` itself:

``shard_map``
    jax >= 0.8 exposes ``jax.shard_map(..., check_vma=)``; older builds ship
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (same semantics,
    renamed replication-check flag). :func:`shard_map` resolves whichever
    exists at import time and translates the flag — callers always pass the
    modern ``check_vma`` spelling. ``parallel/pipeline.py``,
    ``parallel/ring_attention.py``, and ``models/moe.py`` all compile their
    explicit-collective bodies through this single resolver.

``cross-process reduction``
    The multi-host smoke path (``tests/test_distributed_e2e.py``, and the
    documented real-pod path in ``docs/spmd.md``) reduces a value across every
    process of the slice. On TPU/GPU backends a jitted global-array reduction
    lowers to ICI/DCN collectives; the CPU backend of some builds refuses
    multi-process computations outright ("Multiprocess computations aren't
    implemented on the CPU backend"). :func:`global_sum` tries the XLA
    collective first and falls back to the distributed coordinator's
    key-value store — the one transport ``jax.distributed.initialize``
    guarantees on every backend — so the admission env contract stays
    verifiable end-to-end even on CPU fixtures.
"""
from __future__ import annotations

from typing import Any

__all__ = [
    "HAS_NATIVE_SHARD_MAP",
    "shard_map",
    "axis_size",
    "global_sum",
]


def _resolve_shard_map():
    """(callable, uses_check_vma): the build's shard_map and its flag name."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, True
    from jax.experimental.shard_map import shard_map as experimental

    return experimental, False


def _native() -> bool:
    import jax

    return getattr(jax, "shard_map", None) is not None


# resolved lazily so importing this module never imports jax eagerly in
# control-plane processes; cached after the first call
_RESOLVED: tuple[Any, bool] | None = None

def __getattr__(name: str):
    # True when the modern jax.shard_map exists; informational (tests pin
    # that the shim resolves regardless of which spelling the build has).
    # Served via module __getattr__ so merely importing this module never
    # imports jax eagerly in control-plane processes.
    if name == "HAS_NATIVE_SHARD_MAP":
        return _native()
    raise AttributeError(name)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """``jax.shard_map`` on every supported JAX build.

    Callers use the modern keyword (``check_vma``); on builds that predate
    the rename the flag is passed as ``check_rep`` — identical meaning
    (disable the output-replication check for bodies whose replication the
    tracer cannot prove, e.g. psum-broadcast patterns).
    """
    global _RESOLVED
    if _RESOLVED is None:
        _RESOLVED = _resolve_shard_map()
    fn, uses_vma = _RESOLVED
    kwargs: dict[str, Any] = {}
    if check_vma is not None:
        kwargs["check_vma" if uses_vma else "check_rep"] = check_vma
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def axis_size(axis_name):
    """Size of a named mesh axis inside a collective body.

    ``lax.axis_size`` only exists on newer JAX; ``psum(1, axis)`` is the
    classic spelling and constant-folds to the same static size under
    shard_map on every build.
    """
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def global_sum(x) -> float:
    """Sum a (possibly process-sharded) array across every process.

    Fast path: one jitted reduction — XLA inserts the cross-process
    collective on backends that support it. Fallback: each process publishes
    its addressable-shard sum through the coordinator's key-value store and
    sums everyone's contribution locally — O(processes) tiny payloads, exact
    for the integer-valued smoke workloads that use it, and available on
    every backend ``jax.distributed.initialize`` supports. Single-process
    arrays never touch the coordinator.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.process_count() <= 1:
        return float(jax.jit(jnp.sum)(x))
    try:
        return float(jax.jit(jnp.sum)(x))
    except Exception:  # backend refuses multi-process computations (CPU)
        pass
    local = float(
        np.sum([np.sum(np.asarray(s.data)) for s in x.addressable_shards])
    )
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:  # pragma: no cover - initialize() precedes use
        raise RuntimeError(
            "global_sum fallback needs jax.distributed.initialize() "
            "(the admission env contract drives it; parallel/bootstrap.py)"
        )
    pid, nprocs = jax.process_index(), jax.process_count()
    # repr round-trips float64 exactly; keys are namespaced per call site
    # epoch so repeated reductions never collide
    epoch = _next_epoch()
    client.key_value_set(f"/kftpu/global_sum/{epoch}/{pid}", repr(local))
    total = 0.0
    for p in range(nprocs):
        total += float(
            client.blocking_key_value_get(
                f"/kftpu/global_sum/{epoch}/{p}", 60_000
            )
        )
    return total


_EPOCH = 0


def _next_epoch() -> int:
    global _EPOCH
    _EPOCH += 1
    return _EPOCH
