"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context is first-class in this platform (SURVEY.md §5: the reference has
no model/SP code at all; the north star requires the *infrastructure* analog —
here is the compute analog). Sequences shard over the ``seq`` mesh axis; K/V
blocks rotate around the ring with ``lax.ppermute`` over ICI neighbors while
every host's queries accumulate the streaming softmax
(``ops/attention.py``), overlapping the permute with the local matmul. Memory
per host is O(S/n · block), total communication is the classic ring all-gather
cost paid incrementally — ICI-bandwidth-bound, never materializing S×S.

Public pattern: Ring Attention (Liu et al. 2023) / blockwise transformers,
re-expressed with shard_map + ppermute so XLA schedules the overlap.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.ops.attention import (
    _block_update,
    _init_carry,
    blockwise_scores,
    finalize,
)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool):
    """Per-shard body (runs under shard_map): q/k/v are the local sequence
    chunk [B, S_local, H, D]."""
    B, S_local, H, D = q.shape
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = D ** -0.5
    # device i sends its current K/V to i+1: after r steps we hold the chunk
    # originally living on (my_idx - r) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]

    # checkpointed like blockwise_attention's body: autodiff would otherwise
    # save per-step f32 probabilities [n, B, H, S_local, S_local] — the local
    # S^2 chunk stack — defeating ring attention's O(S/n) memory point. The
    # backward re-runs the ppermute ring to recompute scores, which is the
    # published ring-attention backward anyway.
    @partial(jax.checkpoint, prevent_cse=False)
    def step(carry, r):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - r) % n
        s = blockwise_scores(
            q, k_cur, scale, my_idx * S_local, src * S_local, causal
        )
        o, m, l = _block_update((o, m, l), s, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o, m, l = _init_carry(B, H, S_local, D)
    (o, m, l, _, _), _ = lax.scan(
        step, (o, m, l, k, v), jnp.arange(n)
    )
    return finalize(o, m, l).transpose(0, 2, 1, 3).astype(q.dtype)


@partial(jax.jit, static_argnames=("mesh", "axis_name", "causal"))
def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "seq", causal: bool = True):
    """Exact attention with sequences sharded over ``axis_name``.

    q/k/v: [B, S, H, D] global shape, S sharded over the ring axis; batch
    sharded over data axes as usual. Output sharding matches q.
    """
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = shard_map_attention(mesh, axis_name=axis_name, causal=causal, spec=spec)
    return fn(q, k, v)


def shard_map_attention(mesh: Mesh, *, axis_name: str, causal: bool, spec: P):
    body = partial(_ring_attention_local, axis_name=axis_name, causal=causal)
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
