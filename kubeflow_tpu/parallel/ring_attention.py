"""Ring attention: exact causal attention over a sequence-sharded mesh axis,
with the Pallas flash kernels doing the per-chunk work.

Long-context is first-class in this platform (SURVEY.md §5: the reference has
no model/SP code at all; the north star requires the *infrastructure* analog —
here is the compute analog). Sequences shard over the ``seq`` mesh axis; K/V
chunks rotate around the ring with ``lax.ppermute`` over ICI neighbors while
every host's queries run the flash-attention kernels on the resident chunk
(``ops/pallas_attention.py``). Per ring step a 3-way ``lax.switch`` picks:

  * src < my_idx  — fully-visible chunk: non-causal flash kernel;
  * src == my_idx — the diagonal: causal flash kernel (block skipping on);
  * src > my_idx  — fully-masked: no kernel at all (zero + empty-lse), so the
    causal ring does ~half the FLOPs of the non-causal one.

Chunk partials (o_r, lse_r) merge by streaming logsumexp. The whole per-shard
ring is one ``jax.custom_vjp``: the forward saves only (q, k, v, o, lse) —
O(S/n) per host, never S×S — and the backward re-runs the ring, calling the
Pallas dq/dk/dv kernels per chunk with the *global* lse and rotating f32
dk/dv accumulators together with k/v so each chunk's gradient arrives back at
its owner after the full circle.

Public pattern: Ring Attention (Liu et al. 2023) / blockwise transformers,
re-expressed with shard_map + ppermute + Pallas so XLA schedules the overlap.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.ops.pallas_attention import (
    LSE_LANES,
    _auto_interpret,
    _flash_backward,
    _flash_forward,
)
from kubeflow_tpu.parallel import compat


def _merge(o, lse, o_r, lse_r):
    """Streaming-softmax merge of two normalized partials.

    o/o_r [B,H,S,D] f32; lse/lse_r [B,H,S,1] f32 with +inf meaning "empty"
    (the kernels' convention for fully-masked rows). Forward-only numerics —
    the ring's backward never differentiates through this (custom_vjp).
    """
    a = jnp.where(jnp.isposinf(lse), -jnp.inf, lse)
    b = jnp.where(jnp.isposinf(lse_r), -jnp.inf, lse_r)
    lse_new = jnp.logaddexp(a, b)
    w_a = jnp.where(jnp.isneginf(a), 0.0, jnp.exp(a - lse_new))
    w_b = jnp.where(jnp.isneginf(b), 0.0, jnp.exp(b - lse_new))
    return o * w_a + o_r * w_b, lse_new


def _chunk_fwd(q, k, v, causal, block, interpret):
    """One chunk's flash forward; BHSD operands. Returns (o f32, lse [.,1])."""
    o, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block, block_k=block,
        interpret=interpret, save_residuals=True,
    )
    return o.astype(jnp.float32), lse[..., :1]


def _ring_fwd_local(q, k, v, *, axis_name, causal, block, interpret):
    """Forward ring (shard_map body, BHSD layout). Returns (o bf16, lse)."""
    B, H, S, D = q.shape
    n = compat.axis_size(axis_name)
    # only the causal schedule needs the shard's ring position; emitting a
    # dead axis_index in the non-causal program trips some builds' SPMD
    # partitioner (PartitionId outside the manual region)
    my_idx = lax.axis_index(axis_name) if causal else None
    perm = [(i, (i + 1) % n) for i in range(n)]

    def full_chunk(k_cur, v_cur):
        return _chunk_fwd(q, k_cur, v_cur, False, block, interpret)

    def diag_chunk(k_cur, v_cur):
        return _chunk_fwd(q, k_cur, v_cur, True, block, interpret)

    def empty_chunk(k_cur, v_cur):
        return (
            jnp.zeros((B, H, S, D), jnp.float32),
            jnp.full((B, H, S, 1), jnp.inf, jnp.float32),
        )

    def step(carry, r):
        o, lse, k_cur, v_cur = carry
        if causal:
            src = (my_idx - r) % n
            branch = jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2))
            o_r, lse_r = lax.switch(
                branch, (full_chunk, diag_chunk, empty_chunk), k_cur, v_cur
            )
        else:
            o_r, lse_r = full_chunk(k_cur, v_cur)
        o, lse = _merge(o, lse, o_r, lse_r)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    lse0 = jnp.full((B, H, S, 1), jnp.inf, jnp.float32)  # empty
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_bwd_local(q, k, v, o, lse, do, *, axis_name, causal, block,
                    interpret):
    """Backward ring (shard_map body, BHSD). Per step the Pallas dq/dkv
    kernels run against the resident chunk with the GLOBAL lse (so per-chunk
    probabilities are globally normalized); dk/dv f32 accumulators rotate
    with k/v and complete the circle back to each chunk's owner."""
    B, H, S, D = q.shape
    n = compat.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name) if causal else None  # as in forward
    perm = [(i, (i + 1) % n) for i in range(n)]
    # [B,H,S,1] -> the kernels' LSE_LANES-replicated layout; guard all-empty
    # rows (only possible non-causally with a fully-masked input, but cheap)
    lse_k = jnp.broadcast_to(
        jnp.where(jnp.isneginf(lse), jnp.inf, lse), (B, H, S, LSE_LANES)
    )

    def grads(k_cur, v_cur, chunk_causal):
        # f32 partials: each chunk's grads feed the rotating accumulators,
        # so rounding to bf16 per chunk would compound with ring size
        return _flash_backward(
            q, k_cur, v_cur, o, lse_k, do, causal=chunk_causal,
            block_q=block, block_k=block, interpret=interpret,
            grad_dtype=jnp.float32,
        )

    def full_chunk(k_cur, v_cur):
        return grads(k_cur, v_cur, False)

    def diag_chunk(k_cur, v_cur):
        return grads(k_cur, v_cur, True)

    def empty_chunk(k_cur, v_cur):
        z = jnp.zeros((B, H, S, D), jnp.float32)
        return z, z, z

    def step(carry, r):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        if causal:
            src = (my_idx - r) % n
            branch = jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2))
            dq_r, dk_r, dv_r = lax.switch(
                branch, (full_chunk, diag_chunk, empty_chunk), k_cur, v_cur
            )
        else:
            dq_r, dk_r, dv_r = full_chunk(k_cur, v_cur)
        dq += dq_r
        dk_cur += dk_r
        dv_cur += dv_r
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    dq0 = jnp.zeros((B, H, S, D), jnp.float32)
    dkv0 = jnp.zeros((B, H, S, D), jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dkv0, dkv0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ring_local_factory(axis_name, causal, block, interpret):
    """Per-shard ring attention as a custom_vjp (BSHD in/out, matching
    ops/attention.py's layout convention)."""

    @jax.custom_vjp
    def ring_local(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o, _ = _ring_fwd_local(
            qt, kt, vt, axis_name=axis_name, causal=causal, block=block,
            interpret=interpret,
        )
        return o.transpose(0, 2, 1, 3)

    def fwd(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o, lse = _ring_fwd_local(
            qt, kt, vt, axis_name=axis_name, causal=causal, block=block,
            interpret=interpret,
        )
        return o.transpose(0, 2, 1, 3), (qt, kt, vt, o, lse)

    def bwd(res, g):
        qt, kt, vt, o, lse = res
        do = g.transpose(0, 2, 1, 3)
        dq, dk, dv = _ring_bwd_local(
            qt, kt, vt, o, lse, do, axis_name=axis_name, causal=causal,
            block=block, interpret=interpret,
        )
        return tuple(x.transpose(0, 2, 1, 3) for x in (dq, dk, dv))

    ring_local.defvjp(fwd, bwd)
    return ring_local


@partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "causal", "block", "interpret"),
)
def ring_attention(
    q, k, v, mesh: Mesh, *, axis_name: str = "seq", causal: bool = True,
    block: int = 512, interpret: bool | None = None,
):
    """Exact attention with sequences sharded over ``axis_name``.

    q/k/v: [B, S, H, D] global shape, S sharded over the ring axis; batch
    sharded over data axes as usual. Output sharding matches q.
    """
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = shard_map_attention(
        mesh, axis_name=axis_name, causal=causal, spec=spec, block=block,
        interpret=interpret,
    )
    return fn(q, k, v)


def shard_map_attention(
    mesh: Mesh, *, axis_name: str, causal: bool, spec: P, block: int = 512,
    interpret: bool | None = None,
):
    if interpret is None:
        interpret = _auto_interpret()
    body = _ring_local_factory(axis_name, causal, block, interpret)
    return compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
