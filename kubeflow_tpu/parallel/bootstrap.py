"""In-image distributed bootstrap: env contract → JAX mesh, zero user code.

This is the workload half of the platform's distributed backend (the control
half is ``webhooks/tpu_env.py``, which injects the env at pod admission). The
reference ships NCCL opaquely inside CUDA wheels and has no coordination code
at all (SURVEY.md §5 "Distributed communication backend"); here the contract is
explicit and testable:

    TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID

``auto_initialize()`` is called by the image's sitecustomize (or the first
``kubeflow_tpu`` import inside a notebook): single-host slices skip
``jax.distributed`` entirely; multi-host slices join the coordinator that
admission pointed them at, forming the ICI/DCN mesh before user code runs.
"""
from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_initialized = False


def env_worker_context() -> dict | None:
    """Parse the injected worker-identity env; None when not on a slice."""
    if "TPU_WORKER_ID" not in os.environ:
        return None
    hostnames = [
        h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    return {
        "worker_id": int(os.environ["TPU_WORKER_ID"]),
        "hostnames": hostnames,
        "num_processes": int(
            os.environ.get("JAX_NUM_PROCESSES", str(max(1, len(hostnames))))
        ),
        "process_id": int(
            os.environ.get("JAX_PROCESS_ID", os.environ["TPU_WORKER_ID"])
        ),
        "coordinator": os.environ.get("JAX_COORDINATOR_ADDRESS"),
        "topology": os.environ.get("TPU_TOPOLOGY"),
        "accelerator_type": os.environ.get("TPU_ACCELERATOR_TYPE"),
    }


def auto_initialize(*, force: bool = False) -> dict | None:
    """Join the slice-wide JAX runtime if (and only if) this is a multi-host pod.

    Idempotent; safe to call from notebook kernels that restart (the culler
    restart path re-forms the identical mesh because admission re-injects the
    same identity, ``webhooks/tpu_env.py``).
    """
    global _initialized
    ctx = env_worker_context()
    if ctx is None:
        return None
    if ctx["num_processes"] <= 1:
        return ctx  # single host: local runtime is already the whole mesh
    if _initialized and not force:
        return ctx
    import jax

    jax.distributed.initialize(
        coordinator_address=ctx["coordinator"],
        num_processes=ctx["num_processes"],
        process_id=ctx["process_id"],
    )
    _initialized = True
    log.info(
        "joined TPU slice %s as process %d/%d (coordinator %s)",
        ctx["topology"],
        ctx["process_id"],
        ctx["num_processes"],
        ctx["coordinator"],
    )
    return ctx
