"""TPU-native notebook platform."""
