"""Sharded training-step builder.

One jitted SPMD program per (model, mesh-plan): params/optimizer sharded by the
mesh rules (``parallel/mesh.py``), batch sharded over data axes, XLA inserting
all-gather/reduce-scatter/psum over ICI. No pmap, no per-device Python loops —
the scaling-book recipe (SURVEY.md §7). State is donated so params update
in-place in HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.parallel import mesh as meshlib


@dataclasses.dataclass
class TrainStepBundle:
    """Everything a notebook (or bench harness) needs to run training."""

    init: Callable  # (rng, sample_batch) -> state (sharded)
    step: Callable  # (state, batch) -> (state, metrics); jitted
    state_shardings: Any = None


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def _make_bundle(init, make) -> TrainStepBundle:
    """Shared bundle wiring: init computes (state, shardings); make jits the
    step for those shardings. One implementation for every step builder."""
    bundle = TrainStepBundle(init=None, step=None)

    def bundled_init(rng, sample):
        state, shardings = init(rng, sample)
        bundle.state_shardings = shardings
        bundle.step = make(shardings)
        return state

    bundle.init = bundled_init
    return bundle


def make_classifier_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    param_rule=meshlib.fsdp_param_spec,
    loss_fn: Callable = cross_entropy_loss,
    donate: bool = True,
) -> TrainStepBundle:
    """Build a sharded train step for a flax classifier with BatchNorm state.

    The returned ``step`` consumes batches of ``{"image": [B,H,W,C],
    "label": [B]}`` with B sharded over (data, fsdp).
    """
    batch_sh = meshlib.batch_sharding(mesh)
    repl = meshlib.replicated(mesh)

    def init(rng, sample_batch):
        def init_fn(rng, image):
            variables = model.init(rng, image, train=False)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            return {
                "params": params,
                "batch_stats": batch_stats,
                "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32),
            }

        abstract = jax.eval_shape(init_fn, rng, sample_batch["image"])
        shardings = _state_shardings(abstract, mesh, param_rule)
        state = jax.jit(init_fn, out_shardings=shardings)(
            rng, sample_batch["image"]
        )
        return state, shardings

    def train_step(state, batch):
        def compute_loss(params):
            logits, updates = model.apply(
                {"params": params, "batch_stats": state["batch_stats"]},
                batch["image"],
                train=True,
                mutable=["batch_stats"],
            )
            return loss_fn(logits, batch["label"]), (logits, updates)

        (loss, (logits, updates)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state["params"])
        updates_tx, new_opt_state = tx.update(
            grads, state["opt_state"], state["params"]
        )
        new_params = optax.apply_updates(state["params"], updates_tx)
        new_state = {
            "params": new_params,
            "batch_stats": updates["batch_stats"],
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
        }
        accuracy = jnp.mean(
            (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.float32)
        )
        return new_state, {"loss": loss, "accuracy": accuracy}

    def make(state_shardings):
        return jax.jit(
            train_step,
            in_shardings=(state_shardings, {"image": batch_sh, "label": batch_sh}),
            out_shardings=(state_shardings, {"loss": repl, "accuracy": repl}),
            donate_argnums=(0,) if donate else (),
        )

    return _make_bundle(init, make)


def make_lm_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    param_rule=meshlib.fsdp_param_spec,
    loss_fn: Callable | None = None,
    accum_steps: int = 1,
    chunk: int = 512,
    loss_dtype=None,
    donate: bool = True,
) -> TrainStepBundle:
    """Build a sharded LM train step (tokens [B, S] → next-token loss).

    ``loss_fn(params, tokens) -> scalar`` defaults to the chunked tied-head
    loss for ``TransformerLM``-shaped models (the benches' hand-rolled step,
    promoted to the library). ``loss_dtype`` is the default loss's head
    matmul operand dtype (``lm_loss_chunked``'s ``compute_dtype``); leave
    None for bf16-operand/f32-accumulate, pass ``jnp.float32`` when the
    caller needs bit-parity with the unchunked reference loss (grad-accum
    order changes then commute exactly).

    ``accum_steps > 1`` runs gradient accumulation: the global batch is
    split into A microbatches along dim 0, a ``lax.scan`` accumulates the
    MEAN gradient in f32 (each microbatch carries equal token count, so the
    mean of per-microbatch means equals the full-batch gradient), and ONE
    optimizer update applies. This is how a small chip count trains a large
    global batch without holding its activations at once — activation
    memory scales with B/A while optimizer traffic stays per-step.
    """
    batch_sh = meshlib.batch_sharding(mesh)
    repl = meshlib.replicated(mesh)

    if loss_fn is None:
        from kubeflow_tpu.models.transformer import lm_loss_chunked

        def loss_fn(params, tokens):
            hidden = model.apply(
                {"params": params}, tokens, return_hidden=True
            )
            return lm_loss_chunked(
                hidden, params["embed"]["embedding"], tokens, chunk=chunk,
                compute_dtype=loss_dtype,
            )

    def init(rng, sample_tokens):
        def init_fn(rng, tokens):
            params = model.init(rng, tokens)["params"]
            return {
                "params": params,
                "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32),
            }

        abstract = jax.eval_shape(init_fn, rng, sample_tokens)
        shardings = _state_shardings(abstract, mesh, param_rule)
        state = jax.jit(init_fn, out_shardings=shardings)(rng, sample_tokens)
        return state, shardings

    def grads_of(params, tokens):
        return jax.value_and_grad(loss_fn)(params, tokens)

    def train_step(state, tokens):
        if accum_steps == 1:
            loss, grads = grads_of(state["params"], tokens)
        else:
            B = tokens.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"accum_steps {accum_steps} must divide batch {B}"
                )
            micro = tokens.reshape(accum_steps, B // accum_steps, *tokens.shape[1:])

            def body(acc, mb):
                loss_acc, grad_acc = acc
                loss, grads = grads_of(state["params"], mb)
                grad_acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32) / accum_steps,
                    grad_acc, grads,
                )
                return (loss_acc + loss / accum_steps, grad_acc), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state["params"]
            )
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros), micro
            )
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), grads, state["params"]
            )
        updates, new_opt_state = tx.update(
            grads, state["opt_state"], state["params"]
        )
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
        }, {"loss": loss}

    def make(state_shardings):
        return jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_sh),
            out_shardings=(state_shardings, {"loss": repl}),
            donate_argnums=(0,) if donate else (),
        )

    return _make_bundle(init, make)


def optimizer_state_shardings(abstract_opt_state, abstract_params, param_sh, repl):
    """Optimizer slots whose treedef matches params (momentum, nu, …) follow
    the param shardings; everything else (counts, scalars) is replicated.
    Public: benches/training loops that build their own state need it too —
    replicating AdamW moments for a sharded model silently wastes HBM."""
    params_treedef = jax.tree_util.tree_structure(abstract_params)

    def assign(subtree):
        try:
            if jax.tree_util.tree_structure(subtree) == params_treedef:
                return param_sh
        except Exception:
            pass
        return None

    return _map_matching_subtrees(abstract_opt_state, assign, repl)


def _state_shardings(abstract_state, mesh, param_rule):
    """Shard params and matching optimizer slots by the rule; replicate rest
    (any extra slots — batch_stats, step counters — are replicated)."""
    param_sh = meshlib.param_shardings(mesh, abstract_state["params"], param_rule)
    repl = meshlib.replicated(mesh)
    out = {
        "params": param_sh,
        "opt_state": optimizer_state_shardings(
            abstract_state["opt_state"], abstract_state["params"], param_sh, repl
        ),
    }
    for key, sub in abstract_state.items():
        if key not in out:
            out[key] = jax.tree_util.tree_map(lambda _: repl, sub)
    return out


def _map_matching_subtrees(tree, assign, default):
    """Replace subtrees for which assign() returns non-None; leaves -> default."""
    hit = assign(tree)
    if hit is not None:
        return hit
    if isinstance(tree, (list, tuple)):
        mapped = [ _map_matching_subtrees(t, assign, default) for t in tree ]
        return type(tree)(mapped) if not hasattr(tree, "_fields") else type(tree)(*mapped)
    if isinstance(tree, dict):
        return {k: _map_matching_subtrees(v, assign, default) for k, v in tree.items()}
    if dataclasses.is_dataclass(tree):
        kwargs = {
            f.name: _map_matching_subtrees(getattr(tree, f.name), assign, default)
            for f in dataclasses.fields(tree)
        }
        return type(tree)(**kwargs)
    return default
