"""Sharded training-step builder.

One jitted SPMD program per (model, mesh-plan): params/optimizer sharded by the
mesh rules (``parallel/mesh.py``), batch sharded over data axes, XLA inserting
all-gather/reduce-scatter/psum over ICI. No pmap, no per-device Python loops —
the scaling-book recipe (SURVEY.md §7). State is donated so params update
in-place in HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.parallel import mesh as meshlib


@dataclasses.dataclass
class TrainStepBundle:
    """Everything a notebook (or bench harness) needs to run training."""

    init: Callable  # (rng, sample_batch) -> state (sharded)
    step: Callable  # (state, batch) -> (state, metrics); jitted
    state_shardings: Any = None


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def make_classifier_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    *,
    param_rule=meshlib.fsdp_param_spec,
    loss_fn: Callable = cross_entropy_loss,
    donate: bool = True,
) -> TrainStepBundle:
    """Build a sharded train step for a flax classifier with BatchNorm state.

    The returned ``step`` consumes batches of ``{"image": [B,H,W,C],
    "label": [B]}`` with B sharded over (data, fsdp).
    """
    batch_sh = meshlib.batch_sharding(mesh)
    repl = meshlib.replicated(mesh)

    def init(rng, sample_batch):
        def init_fn(rng, image):
            variables = model.init(rng, image, train=False)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            return {
                "params": params,
                "batch_stats": batch_stats,
                "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32),
            }

        abstract = jax.eval_shape(init_fn, rng, sample_batch["image"])
        shardings = _state_shardings(abstract, mesh, param_rule)
        state = jax.jit(init_fn, out_shardings=shardings)(
            rng, sample_batch["image"]
        )
        return state, shardings

    def train_step(state, batch):
        def compute_loss(params):
            logits, updates = model.apply(
                {"params": params, "batch_stats": state["batch_stats"]},
                batch["image"],
                train=True,
                mutable=["batch_stats"],
            )
            return loss_fn(logits, batch["label"]), (logits, updates)

        (loss, (logits, updates)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state["params"])
        updates_tx, new_opt_state = tx.update(
            grads, state["opt_state"], state["params"]
        )
        new_params = optax.apply_updates(state["params"], updates_tx)
        new_state = {
            "params": new_params,
            "batch_stats": updates["batch_stats"],
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
        }
        accuracy = jnp.mean(
            (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.float32)
        )
        return new_state, {"loss": loss, "accuracy": accuracy}

    def make(state_shardings):
        return jax.jit(
            train_step,
            in_shardings=(state_shardings, {"image": batch_sh, "label": batch_sh}),
            out_shardings=(state_shardings, {"loss": repl, "accuracy": repl}),
            donate_argnums=(0,) if donate else (),
        )

    bundle = TrainStepBundle(init=None, step=None)

    def bundled_init(rng, sample_batch):
        state, shardings = init(rng, sample_batch)
        bundle.state_shardings = shardings
        bundle.step = make(shardings)
        return state

    bundle.init = bundled_init
    return bundle


def optimizer_state_shardings(abstract_opt_state, abstract_params, param_sh, repl):
    """Optimizer slots whose treedef matches params (momentum, nu, …) follow
    the param shardings; everything else (counts, scalars) is replicated.
    Public: benches/training loops that build their own state need it too —
    replicating AdamW moments for a sharded model silently wastes HBM."""
    params_treedef = jax.tree_util.tree_structure(abstract_params)

    def assign(subtree):
        try:
            if jax.tree_util.tree_structure(subtree) == params_treedef:
                return param_sh
        except Exception:
            pass
        return None

    return _map_matching_subtrees(abstract_opt_state, assign, repl)


def _state_shardings(abstract_state, mesh, param_rule):
    """Shard params and matching optimizer slots by the rule; replicate rest."""
    param_sh = meshlib.param_shardings(mesh, abstract_state["params"], param_rule)
    repl = meshlib.replicated(mesh)
    return {
        "params": param_sh,
        "batch_stats": jax.tree_util.tree_map(
            lambda _: repl, abstract_state["batch_stats"]
        ),
        "opt_state": optimizer_state_shardings(
            abstract_state["opt_state"], abstract_state["params"], param_sh, repl
        ),
        "step": repl,
    }


def _map_matching_subtrees(tree, assign, default):
    """Replace subtrees for which assign() returns non-None; leaves -> default."""
    hit = assign(tree)
    if hit is not None:
        return hit
    if isinstance(tree, (list, tuple)):
        mapped = [ _map_matching_subtrees(t, assign, default) for t in tree ]
        return type(tree)(mapped) if not hasattr(tree, "_fields") else type(tree)(*mapped)
    if isinstance(tree, dict):
        return {k: _map_matching_subtrees(v, assign, default) for k, v in tree.items()}
    if dataclasses.is_dataclass(tree):
        kwargs = {
            f.name: _map_matching_subtrees(getattr(tree, f.name), assign, default)
            for f in dataclasses.fields(tree)
        }
        return type(tree)(**kwargs)
    return default
