"""CRD manifest generation (the codegen step, SURVEY.md §7 stage 1).

The reference checks in generated CRD YAML
(``notebook-controller/config/crd/bases/kubeflow.org_notebooks.yaml``); here
the schemas are emitted from the API-type definitions so schema and code can't
drift. ``python -m kubeflow_tpu.api.crds manifests/crds`` renders them.
"""
from __future__ import annotations

import sys

import yaml

from kubeflow_tpu.tpu.topology import ACCELERATORS


def _obj(props: dict | None = None, **kw) -> dict:
    out: dict = {"type": "object", **kw}
    if props is not None:
        out["properties"] = props
    return out


_TPU_SPEC = _obj(
    {
        "accelerator": {
            "type": "string",
            "enum": sorted(ACCELERATORS),
            "description": "TPU generation of the requested slice.",
        },
        "topology": {
            "type": "string",
            "pattern": r"^\d+(x\d+)*$",
            "description": "Chip torus shape, e.g. 2x2x2 (v4/v5p) or 2x4 (v5e/v6e). "
            "Must tile onto whole hosts; one pod per host is created.",
        },
        "numSlices": {
            "type": "integer",
            "minimum": 1,
            "default": 1,
            "description": "Multislice degree: N identical slices joined over "
            "the data-center network (MEGASCALE_* env injected per pod; one "
            "StatefulSet per slice).",
        },
    },
    required=["accelerator", "topology"],
    description="First-class TPU slice request. Drives StatefulSet replicas, "
    "google.com/tpu limits, GKE topology nodeSelectors, and per-pod worker "
    "identity injection.",
)

# x-kubernetes-preserve-unknown-fields for PodSpec (matching the pragmatic
# schema the reference ships, which embeds the full PodSpec).
_POD_SPEC = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}


def crd(
    *,
    group: str,
    kind: str,
    plural: str,
    versions: list[tuple[str, bool, dict]],
    scope: str = "Namespaced",
    short_names: list[str] | None = None,
    conversion_webhook: bool = False,
) -> dict:
    conversion = (
        {
            "conversion": {
                "strategy": "Webhook",
                "webhook": {
                    "conversionReviewVersions": ["v1"],
                    # no explicit port: the Service exposes 443 (targetPort
                    # https=8443), matching the admission webhook configs
                    "clientConfig": {
                        "service": {
                            "name": "kubeflow-tpu-webhook",
                            "namespace": "kubeflow",
                            "path": "/convert",
                        }
                    },
                },
            }
        }
        if conversion_webhook
        else {}
    )
    metadata: dict = {"name": f"{plural}.{group}"}
    if conversion_webhook:
        # apiserver must trust the webhook cert, same injection as the
        # MutatingWebhookConfiguration (manifests/base/webhook.yaml:43)
        metadata["annotations"] = {
            "cert-manager.io/inject-ca-from": "kubeflow/kubeflow-tpu-webhook-cert"
        }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": metadata,
        "spec": {
            "group": group,
            "scope": scope,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
                **({"shortNames": short_names} if short_names else {}),
            },
            "versions": [
                {
                    "name": name,
                    "served": True,
                    "storage": storage,
                    "schema": {"openAPIV3Schema": schema},
                    "subresources": {"status": {}},
                }
                for name, storage, schema in versions
            ],
            **conversion,
        },
    }


def notebook_crd() -> dict:
    schema = _obj(
        {
            "spec": _obj(
                {
                    "template": _obj({"spec": _POD_SPEC}),
                    "tpu": _TPU_SPEC,
                }
            ),
            "status": _obj(
                {
                    "conditions": {"type": "array", "items": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True})},
                    "readyReplicas": {"type": "integer"},
                    "containerState": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                    "tpu": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                }
            ),
        }
    )
    # v1alpha1/v1beta1/v1 mirror the reference's served versions
    # (notebook-controller/api/{v1alpha1,v1beta1,v1}); structurally identical
    # (as in the reference), converted by the /convert webhook
    # (webhooks/conversion.py, ref notebook_conversion.go).
    return crd(
        group="kubeflow.org",
        kind="Notebook",
        plural="notebooks",
        versions=[
            ("v1alpha1", False, schema),
            ("v1beta1", True, schema),
            ("v1", False, schema),
        ],
        short_names=["nb"],
        conversion_webhook=True,
    )


def profile_crd() -> dict:
    schema = _obj(
        {
            "spec": _obj(
                {
                    "owner": _obj(
                        {"kind": {"type": "string"}, "name": {"type": "string"}}
                    ),
                    "plugins": {
                        "type": "array",
                        "items": _obj(
                            {
                                "kind": {"type": "string"},
                                "spec": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                            }
                        ),
                    },
                    "resourceQuotaSpec": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                    "tpu": _obj({"maxChips": {"type": "integer"}}),
                }
            ),
            "status": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
        }
    )
    return crd(
        group="kubeflow.org",
        kind="Profile",
        plural="profiles",
        scope="Cluster",
        versions=[("v1beta1", False, schema), ("v1", True, schema)],
    )


def poddefault_crd() -> dict:
    schema = _obj(
        {
            "spec": _obj(
                {
                    "selector": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                    "desc": {"type": "string"},
                    **{
                        k: {"type": "array", "items": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True})}
                        for k in ("env", "envFrom", "volumes", "volumeMounts",
                                  "tolerations", "imagePullSecrets")
                    },
                    "labels": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                    "annotations": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
                    "serviceAccountName": {"type": "string"},
                    "command": {"type": "array", "items": {"type": "string"}},
                    "args": {"type": "array", "items": {"type": "string"}},
                },
                required=["selector"],
            )
        }
    )
    return crd(
        group="kubeflow.org",
        kind="PodDefault",
        plural="poddefaults",
        versions=[("v1alpha1", True, schema)],
    )


def tensorboard_crd() -> dict:
    schema = _obj(
        {
            "spec": _obj(
                {"logspath": {"type": "string"}}, required=["logspath"]
            ),
            "status": _obj(None, **{"x-kubernetes-preserve-unknown-fields": True}),
        }
    )
    return crd(
        group="tensorboard.kubeflow.org",
        kind="Tensorboard",
        plural="tensorboards",
        versions=[("v1alpha1", True, schema)],
    )


ALL_CRDS = {
    "kubeflow.org_notebooks.yaml": notebook_crd,
    "kubeflow.org_profiles.yaml": profile_crd,
    "kubeflow.org_poddefaults.yaml": poddefault_crd,
    "tensorboard.kubeflow.org_tensorboards.yaml": tensorboard_crd,
}


def render_all(outdir: str) -> list[str]:
    import os

    os.makedirs(outdir, exist_ok=True)
    written = []
    for filename, fn in ALL_CRDS.items():
        path = os.path.join(outdir, filename)
        with open(path, "w") as f:
            yaml.safe_dump(fn(), f, sort_keys=False)
        written.append(path)
    return written


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else "manifests/crds"
    for path in render_all(outdir):
        print(path)
