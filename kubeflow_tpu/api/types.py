"""Platform API types (the CRD schema layer, SURVEY.md L1).

API surface parity with the reference, TPU-first extensions marked:

- ``Notebook``  (ref: ``notebook-controller/api/v1beta1/notebook_types.go:27-76``)
  spec.template.spec = PodSpec, status = {conditions, readyReplicas,
  containerState}. **New**: ``spec.tpu = {accelerator, topology, multislice?}``
  — the first-class slice request (SURVEY.md §7 stage 1).
- ``Profile``   (ref: ``profile-controller/api/v1/profile_types.go:36-45``)
  cluster-scoped; owner Subject, plugins, resourceQuotaSpec.
- ``PodDefault``(ref: ``admission-webhook/pkg/apis/settings/v1alpha1/poddefault_types.go:27-81``)
- ``Tensorboard``(ref: ``tensorboard-controller/api/v1alpha1``): spec.logspath.

Objects travel as wire-format dicts; these helpers construct/validate them and
emit the CRD manifests (``manifests/crds.py`` renders to YAML).
"""
from __future__ import annotations

from typing import Mapping

from kubeflow_tpu.tpu.topology import SliceTopology, parse_topology

GROUP = "kubeflow.org"
NOTEBOOK_API_VERSION = f"{GROUP}/v1beta1"
PROFILE_API_VERSION = f"{GROUP}/v1"
PODDEFAULT_API_VERSION = f"{GROUP}/v1alpha1"
TENSORBOARD_API_VERSION = f"tensorboard.{GROUP}/v1alpha1"

# Annotation contract (kept name-compatible with the reference so existing
# Kubeflow tooling keeps working against this platform):
STOP_ANNOTATION = "kubeflow-resource-stopped"          # culler.go:46
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"  # culler.go:39
LAST_ACTIVITY_CHECK_TS = "notebooks.kubeflow.org/last_activity_check_timestamp"
SERVER_TYPE_ANNOTATION = "notebooks.kubeflow.org/server-type"
CREATOR_ANNOTATION = "notebooks.kubeflow.org/creator"
OWNER_ANNOTATION = "owner"                              # profile_controller.go namespace owner


def notebook(
    name: str,
    namespace: str,
    *,
    image: str = "kubeflow-tpu/jupyter-jax:latest",
    cpu: str = "0.5",
    memory: str = "1Gi",
    cpu_limit: str | None = None,
    memory_limit: str | None = None,
    tpu_accelerator: str | None = None,
    tpu_topology: str | None = None,
    tpu_num_slices: int = 1,
    env: list | None = None,
    volumes: list | None = None,
    volume_mounts: list | None = None,
    annotations: Mapping | None = None,
    labels: Mapping | None = None,
) -> dict:
    """Build a Notebook CR (what the spawner backend assembles from the form;
    ref template: ``apps/common/yaml/notebook_template.yaml:1-24``)."""
    container: dict = {
        "name": name,
        "image": image,
        "resources": {
            "requests": {"cpu": cpu, "memory": memory},
            # limits default to the requests (Guaranteed QoS); the spawner
            # passes limitFactor-scaled values (ref form.py:117-175)
            "limits": {"cpu": cpu_limit or cpu, "memory": memory_limit or memory},
        },
    }
    if env:
        container["env"] = list(env)
    if volume_mounts:
        container["volumeMounts"] = list(volume_mounts)
    spec: dict = {"template": {"spec": {"containers": [container]}}}
    if volumes:
        spec["template"]["spec"]["volumes"] = list(volumes)
    if tpu_accelerator or tpu_topology:
        if not (tpu_accelerator and tpu_topology):
            raise ValueError("spec.tpu requires both accelerator and topology")
        parse_topology(tpu_accelerator, tpu_topology)  # validate early
        if int(tpu_num_slices) < 1:
            # reject at construction, not runtime: a clamped-to-1 zero would
            # silently run a different shape than the user asked for
            raise ValueError(
                f"tpu_num_slices must be a positive integer, got "
                f"{tpu_num_slices!r}"
            )
        spec["tpu"] = {"accelerator": tpu_accelerator, "topology": tpu_topology}
        if tpu_num_slices > 1:
            # multislice: N identical slices joined over DCN (MEGASCALE)
            spec["tpu"]["numSlices"] = int(tpu_num_slices)
        # family label (runtime/sharding.py): lets a sharded scheduler's
        # list/watch select only its own families server-side. Stamped from
        # the validated spec at construction; the owning shard heals drift.
        from kubeflow_tpu.runtime.sharding import FAMILY_LABEL

        labels = {**(labels or {}), FAMILY_LABEL: tpu_accelerator}
    return {
        "apiVersion": NOTEBOOK_API_VERSION,
        "kind": "Notebook",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels or {}),
            "annotations": dict(annotations or {}),
        },
        "spec": spec,
    }


def notebook_topology(nb: Mapping) -> SliceTopology | None:
    """The validated slice a Notebook requests, or None for CPU-only."""
    tpu = nb.get("spec", {}).get("tpu")
    if not tpu:
        return None
    return parse_topology(tpu.get("accelerator", ""), tpu.get("topology", ""))


def notebook_num_slices(nb: Mapping) -> int:
    """Requested multislice degree (1 = a single slice, the default)."""
    tpu = nb.get("spec", {}).get("tpu") or {}
    return max(1, int(tpu.get("numSlices", 1)))


def validate_notebook(nb: Mapping) -> list[str]:
    """Admission-time validation; returns user-facing error strings."""
    errors = []
    spec = nb.get("spec", {})
    containers = (
        spec.get("template", {}).get("spec", {}).get("containers") or []
    )
    if not containers:
        errors.append("spec.template.spec.containers must have at least one container")
    if spec.get("tpu"):
        try:
            parse_topology(
                spec["tpu"].get("accelerator", ""),
                spec["tpu"].get("topology", ""),
            )
        except ValueError as e:
            errors.append(f"spec.tpu: {e}")
        # numSlices <= 0 / non-integer used to be accepted here and silently
        # clamped at runtime (notebook_num_slices max(1, ...)): the gang then
        # ran a different multislice degree than the CR declared. Reject at
        # validation time with a message that names the field.
        raw = spec["tpu"].get("numSlices", 1)
        valid = False
        if isinstance(raw, int) and not isinstance(raw, bool):
            valid = raw >= 1
        elif isinstance(raw, str):
            # try/int, not str.isdigit(): isdigit() accepts unicode digits
            # ("²") that int() rejects — a validator must never raise
            try:
                valid = int(raw) >= 1
            except ValueError:
                valid = False
        if not valid:
            errors.append(
                f"spec.tpu.numSlices must be a positive integer, got {raw!r}"
            )
    return errors


def profile(
    name: str,
    owner_name: str,
    owner_kind: str = "User",
    plugins: list | None = None,
    resource_quota: Mapping | None = None,
) -> dict:
    spec: dict = {"owner": {"kind": owner_kind, "name": owner_name}}
    if plugins:
        spec["plugins"] = list(plugins)
    if resource_quota:
        spec["resourceQuotaSpec"] = dict(resource_quota)
    return {
        "apiVersion": PROFILE_API_VERSION,
        "kind": "Profile",
        "metadata": {"name": name},
        "spec": spec,
    }


def pod_default(
    name: str,
    namespace: str,
    *,
    selector: Mapping,
    desc: str = "",
    env: list | None = None,
    env_from: list | None = None,
    volumes: list | None = None,
    volume_mounts: list | None = None,
    tolerations: list | None = None,
    labels: Mapping | None = None,
    annotations: Mapping | None = None,
    service_account_name: str | None = None,
    image_pull_secrets: list | None = None,
    command: list | None = None,
    args: list | None = None,
) -> dict:
    spec: dict = {"selector": dict(selector), "desc": desc}
    for key, val in (
        ("env", env),
        ("envFrom", env_from),
        ("volumes", volumes),
        ("volumeMounts", volume_mounts),
        ("tolerations", tolerations),
        ("labels", dict(labels) if labels else None),
        ("annotations", dict(annotations) if annotations else None),
        ("serviceAccountName", service_account_name),
        ("imagePullSecrets", image_pull_secrets),
        ("command", command),
        ("args", args),
    ):
        if val:
            spec[key] = val
    return {
        "apiVersion": PODDEFAULT_API_VERSION,
        "kind": "PodDefault",
        "metadata": {"name": name, "namespace": namespace},
        "spec": spec,
    }


def tensorboard(name: str, namespace: str, logspath: str) -> dict:
    return {
        "apiVersion": TENSORBOARD_API_VERSION,
        "kind": "Tensorboard",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"logspath": logspath},
    }
