"""TPU-native notebook platform."""
