"""Parallel kernel-activity probing — binding to the native prober.

The reference probes one notebook per reconcile with a blocking Go HTTP GET
(``notebook-controller/pkg/culler/culler.go:149-185``). Here the controller
probes the whole fleet in one native pass (``native/culler_probe.cc``): raw
sockets, a thread pool, one deadline. Falls back to ``urllib`` threads when
the compiled library is absent so behavior is identical everywhere.
"""
from __future__ import annotations

import ctypes
import dataclasses
import json
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from kubeflow_tpu.runtime import workqueue as _wq

_BODY_BUFLEN = 1 << 20  # 1 MiB per body; kernels JSON is tiny


@dataclasses.dataclass
class ProbeResult:
    status: int  # HTTP status; -1 connect fail, -2 timeout, -3 malformed
    body: str

    @property
    def ok(self) -> bool:
        return self.status == 200

    def kernels(self) -> list | None:
        """Parsed /api/kernels payload, or None when the probe failed."""
        if not self.ok:
            return None
        try:
            parsed = json.loads(self.body)
        except ValueError:
            return None
        return parsed if isinstance(parsed, list) else None


def probe_many(
    targets: Sequence[tuple[str, int, str]],
    *,
    timeout: float = 5.0,
    max_concurrency: int = 64,
) -> list[ProbeResult]:
    """HTTP GET every (host, port, path) target concurrently."""
    if not targets:
        return []
    lib = _wq._load_library()
    if lib is not None:
        return _probe_native(lib, targets, timeout, max_concurrency)
    return _probe_python(targets, timeout, max_concurrency)


def _probe_native(lib, targets, timeout, max_concurrency):
    if not hasattr(lib.probe_http_many, "_kf_typed"):
        lib.probe_http_many.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
        ]
        lib.probe_http_many._kf_typed = True
    n = len(targets)
    hosts = (ctypes.c_char_p * n)(*[t[0].encode() for t in targets])
    ports = (ctypes.c_int * n)(*[int(t[1]) for t in targets])
    paths = (ctypes.c_char_p * n)(*[t[2].encode() for t in targets])
    statuses = (ctypes.c_int * n)()
    bufs = [ctypes.create_string_buffer(_BODY_BUFLEN) for _ in range(n)]
    bodies = (ctypes.c_char_p * n)(
        *[ctypes.cast(b, ctypes.c_char_p) for b in bufs]
    )
    lib.probe_http_many(
        hosts, ports, paths, n,
        ctypes.c_double(timeout), int(max_concurrency),
        statuses, bodies, _BODY_BUFLEN,
    )
    return [
        ProbeResult(status=statuses[i], body=bufs[i].value.decode(errors="replace"))
        for i in range(n)
    ]


def _probe_python(targets, timeout, max_concurrency):
    import socket
    import urllib.error
    import urllib.request

    def classify(exc: BaseException) -> int:
        # Status parity with the native prober (native/culler_probe.cc):
        # -1 connect/resolve failure, -2 deadline expired. urllib wraps the
        # socket timeout in URLError(reason=timeout) for connect stalls but
        # raises it bare for read stalls — unwrap before classifying, so
        # the fallback never reports a timeout as a connect failure.
        if isinstance(exc, urllib.error.URLError):
            exc = exc.reason if isinstance(exc.reason, BaseException) else exc
        if isinstance(exc, (TimeoutError, socket.timeout)):
            return -2
        return -1

    def one(target):
        host, port, path = target
        url = f"http://{host}:{port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return ProbeResult(resp.status, resp.read().decode(errors="replace"))
        except urllib.error.HTTPError as e:
            return ProbeResult(e.code, "")
        except Exception as e:
            return ProbeResult(classify(e), "")

    with ThreadPoolExecutor(max_workers=max_concurrency) as pool:
        return list(pool.map(one, targets))
