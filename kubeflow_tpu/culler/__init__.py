"""TPU-native notebook platform."""
