"""Idle-notebook culling.

Behavioral parity with the reference culler (``notebook-controller/pkg/culler/
culler.go``): track Jupyter kernel activity via the server's ``/api/kernels``
endpoint, persist ``last-activity`` on the CR, and set the stop annotation when
idle longer than CULL_IDLE_TIME. TPU generalization (SURVEY.md §7 stage 4 and
hard part #3): for a multi-host slice, idleness is decided at the *coordinator*
(host 0 — the only host running the kernel manager), and stopping scales the
whole gang N→0; restart re-derives the identical topology so the ICI mesh
re-forms with the same worker IDs.

Kernel probing is injected (``KernelFetcher``) so tests can run against a fake
kernel API — the fixture the reference lacks (SURVEY.md §4 takeaway).

Idleness policy precedence (docs/observability.md): **telemetry when
present, kernel activity as fallback**. With a fresh device-telemetry
sample (``telemetry/collector.py``), the duty cycle decides — a notebook
idle-spinning under a live "busy" kernel on an 8-chip slice finally becomes
cullable, and a genuinely busy one is protected even if its kernel API
flakes. When the sample is missing or stale (CPU notebook, agentless image,
collector outage) the reference's kernel-activity logic applies unchanged,
so enabling telemetry can never make culling *less* safe than before.
"""
from __future__ import annotations

import datetime as _dt
import time
from typing import Callable, Mapping

from kubeflow_tpu import scheduler as sched
from kubeflow_tpu.api import types as api
from kubeflow_tpu.runtime import objects as ko

KERNEL_EXECUTION_STATES = ("busy", "idle", "starting")
TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"

# fetch(namespace, name) -> list of kernel dicts
# [{"execution_state": "idle", "last_activity": "..."}] or None if unreachable.
KernelFetcher = Callable[[str, str], list | None]


def format_time(ts: float) -> str:
    return _dt.datetime.fromtimestamp(ts, _dt.timezone.utc).strftime(TIME_FORMAT)


def parse_time(s: str) -> float:
    return (
        _dt.datetime.strptime(s, TIME_FORMAT)
        .replace(tzinfo=_dt.timezone.utc)
        .timestamp()
    )


def stop_annotation_is_set(nb: Mapping) -> bool:
    return api.STOP_ANNOTATION in ko.annotations(nb)


def set_stop_annotation(nb: dict, now: float) -> None:
    ko.set_annotation(nb, api.STOP_ANNOTATION, format_time(now))
    # Drop last-activity so a later restart re-initializes the idle clock
    # instead of instantly re-culling (ref: SetStopAnnotation culler.go:130-134).
    ko.remove_annotation(nb, api.LAST_ACTIVITY_ANNOTATION)


def remove_stop_annotation(nb: dict) -> None:
    ko.remove_annotation(nb, api.STOP_ANNOTATION)


def all_kernels_idle(kernels: list) -> bool:
    """True iff every kernel reports execution_state == idle
    (ref: ``allKernelsAreIdle`` culler.go:187-204)."""
    return all(k.get("execution_state") == "idle" for k in kernels)


def latest_kernel_activity(kernels: list) -> str | None:
    """Most recent kernel ``last_activity`` (ref: culler.go:257-279)."""
    best = None
    for k in kernels:
        la = k.get("last_activity")
        if not la:
            continue
        try:
            t = parse_time(la)
        except ValueError:
            continue
        if best is None or t > best:
            best = t
    return format_time(best) if best is not None else None


class Culler:
    def __init__(
        self,
        *,
        enabled: bool,
        cull_idle_minutes: float,
        check_period_minutes: float,
        fetch_kernels: KernelFetcher | None = None,
        clock: Callable[[], float] = time.time,
        telemetry=None,
        duty_cycle_idle_threshold: float = 0.05,
    ) -> None:
        self.enabled = enabled
        self.cull_idle_s = cull_idle_minutes * 60.0
        self.check_period_s = check_period_minutes * 60.0
        self.fetch_kernels = fetch_kernels
        self.clock = clock
        # device-telemetry view (telemetry/collector.py): activity(ns, name)
        # -> fresh ActivitySample | None. A pure memory read — the culler
        # never waits on a scrape, so a wedged agent cannot block culling.
        self.telemetry = telemetry
        self.duty_cycle_idle_threshold = duty_cycle_idle_threshold
        # which signal last drove each notebook's idle clock — provenance
        # must name the policy that RAN the clock, not whatever signal
        # happens to be fresh at cull-commit time (a collector outage in
        # the final check window would otherwise mislabel a duty-cycle
        # cull as kernel-activity and hide it from the telemetry audit).
        # In-memory: a restarted controller re-derives on its next check.
        self._last_policy: dict[tuple[str, str], tuple[str, object]] = {}

    def _telemetry_sample(self, nb: Mapping):
        """Fresh sample with a KNOWN duty cycle, else None (fallback). An
        agent that cannot measure duty (blind backend, uninstrumented
        notebook) reports it unknown — unknown must not read as idle."""
        if self.telemetry is None:
            return None
        sample = self.telemetry.activity(ko.namespace(nb), ko.name(nb))
        if sample is None or sample.duty_cycle is None:
            return None
        return sample

    # -- annotation maintenance (ref: UpdateNotebookLastActivityAnnotation
    #    culler.go:207-237) ---------------------------------------------------

    def needs_check(self, nb: Mapping) -> bool:
        anns = ko.annotations(nb)
        last_check = anns.get(api.LAST_ACTIVITY_CHECK_TS)
        if last_check is None:
            return True
        try:
            return self.clock() - parse_time(last_check) >= self.check_period_s
        except ValueError:
            return True

    def update_last_activity(
        self, nb: dict, warnings: list[str] | None = None
    ) -> bool:
        """Probe the coordinator's kernel API and refresh annotations in place.

        Returns True if annotations changed. An unreachable server leaves
        last-activity untouched (the server may be culled or still starting;
        ref behavior at culler.go:217-226). Anomalies found while
        maintaining annotations (e.g. a hand-edited, unparseable
        last-activity) are appended to the caller's ``warnings`` list — the
        reconciler turns them into Warning events; a per-call out-param
        (not instance state) because one Culler is shared by every
        reconcile worker, and shared state would misattribute a warning to
        whichever notebook drained it first.
        """
        now = self.clock()
        anns = ko.annotations(nb)
        if stop_annotation_is_set(nb):
            # Stopped: never (re-)seed last-activity — set_stop_annotation
            # removed it deliberately so a restart re-initializes the idle
            # clock (would instantly re-cull otherwise). The idle clock is
            # gone, so its policy bookkeeping goes with it.
            self._last_policy.pop((ko.namespace(nb), ko.name(nb)), None)
            if not self.needs_check(nb):
                return False
            ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
            return True
        if api.LAST_ACTIVITY_ANNOTATION not in anns:
            ko.set_annotation(nb, api.LAST_ACTIVITY_ANNOTATION, format_time(now))
            ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
            return True
        try:
            parse_time(anns[api.LAST_ACTIVITY_ANNOTATION])
        except ValueError:
            # A malformed (hand-edited, wrong-format, missing-tz) timestamp
            # must not wedge the culling loop: unparseable means the idle
            # clock is unknowable — treat it as missing, re-stamp from now,
            # and surface the anomaly. (Before this, needs_culling silently
            # returned False forever: the notebook became unkillable and
            # held its slice indefinitely.)
            if warnings is not None:
                warnings.append(
                    f"unparseable last-activity annotation "
                    f"{anns[api.LAST_ACTIVITY_ANNOTATION]!r} (want "
                    f"{TIME_FORMAT}); re-stamping and restarting the idle "
                    f"clock"
                )
            ko.set_annotation(nb, api.LAST_ACTIVITY_ANNOTATION, format_time(now))
            ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
            return True
        if not self.needs_check(nb):
            return False
        if sched.condition_is_true(nb, sched.COND_QUEUED):
            # Queued for capacity: the gang has zero pods, so its kernel API
            # is unreachable and its idle clock would keep running through
            # the whole queue wait — then cull it the moment it finally
            # binds. Waiting in line is not idleness: freeze the clock.
            ko.set_annotation(nb, api.LAST_ACTIVITY_ANNOTATION, format_time(now))
            ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
            return True
        key = (ko.namespace(nb), ko.name(nb))
        sample = self._telemetry_sample(nb)
        if sample is not None:
            # Telemetry-when-present: the devices themselves say whether the
            # session is working. Busy devices refresh the idle clock; idle
            # devices let it run — even under a live "busy" kernel, which is
            # exactly the idle-spinning case kernel presence cannot see.
            self._last_policy[key] = ("duty-cycle", sample)
            if sample.duty_cycle >= self.duty_cycle_idle_threshold:
                ko.set_annotation(
                    nb, api.LAST_ACTIVITY_ANNOTATION, format_time(now)
                )
            ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
            return True
        self._last_policy[key] = ("kernel-activity", None)
        kernels = (
            self.fetch_kernels(ko.namespace(nb), ko.name(nb))
            if self.fetch_kernels
            else None
        )
        if kernels is not None:
            if not kernels:
                # A server with zero kernels is idle by definition; keep the
                # existing last-activity so the idle clock keeps running.
                pass
            elif not all_kernels_idle(kernels):
                ko.set_annotation(
                    nb, api.LAST_ACTIVITY_ANNOTATION, format_time(now)
                )
            else:
                recent = latest_kernel_activity(kernels)
                if recent:
                    ko.set_annotation(nb, api.LAST_ACTIVITY_ANNOTATION, recent)
        # The check timestamp always advances once the period elapsed.
        ko.set_annotation(nb, api.LAST_ACTIVITY_CHECK_TS, format_time(now))
        return True

    # -- culling decision (ref: NotebookNeedsCulling culler.go:303-318) ------

    def needs_culling(self, nb: Mapping) -> bool:
        if not self.enabled:
            return False
        if stop_annotation_is_set(nb):
            return False
        if sched.condition_is_true(nb, sched.COND_QUEUED):
            # A queued gang has zero pods — its "idleness" is the fleet
            # being full, not the user being gone. Culling it would also
            # drop its queue seniority (the scheduler clears queued-at for
            # stopped gangs so capacity accounting stays exact), so a
            # long queue wait must never cost the user their place in it.
            return False
        la = ko.annotations(nb).get(api.LAST_ACTIVITY_ANNOTATION)
        if not la:
            return False
        try:
            idle_for = self.clock() - parse_time(la)
        except ValueError:
            return False
        return idle_for >= self.cull_idle_s

    def cull_provenance(self, nb: Mapping):
        """Which signal drove this cull: ``("duty-cycle", sample)`` when
        the duty-cycle policy ran the idle clock at its last check, else
        ``("kernel-activity", None)`` — the reference's probe semantics.
        Read from the per-notebook policy record the last
        ``update_last_activity`` wrote (NOT re-sampled at commit time — a
        collector outage in the final window must not relabel the
        decision); a cold cache (controller restart between the check and
        the cull) re-derives from the live sample. Consumed at cull commit,
        so the entry is popped. Recorded into the Culled event and the
        collector's decision log so a cull is explainable after the fact."""
        key = (ko.namespace(nb), ko.name(nb))
        recorded = self._last_policy.pop(key, None)
        if recorded is not None:
            return recorded
        sample = self._telemetry_sample(nb)
        if (
            sample is not None
            and sample.duty_cycle < self.duty_cycle_idle_threshold
        ):
            return "duty-cycle", sample
        return "kernel-activity", None
