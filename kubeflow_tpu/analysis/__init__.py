"""tpulint: project-invariant static analysis (docs/analysis.md).

The AST engine, the five project rule families (TPU001-TPU005), and the
justified-baseline machinery behind ``tools/tpulint.py``. The dynamic half
of the same program — the lost-update race detector — lives with the chaos
layer in ``kubeflow_tpu/testing/chaos.py``.
"""
from kubeflow_tpu.analysis.engine import (
    Baseline,
    BaselineEntry,
    BaselineResult,
    Finding,
    LintEngine,
    Rule,
)
from kubeflow_tpu.analysis.rules import RULE_IDS, default_rules

__all__ = [
    "Baseline",
    "BaselineEntry",
    "BaselineResult",
    "Finding",
    "LintEngine",
    "Rule",
    "RULE_IDS",
    "default_rules",
]
