"""TPU004: kubeflow.org annotation/label keys are imported constants."""
from __future__ import annotations

import ast
import re

from kubeflow_tpu.analysis.engine import Finding, Rule
from kubeflow_tpu.analysis.rules import qualname_of

# a key-shaped literal: <prefix>.kubeflow.org/<name>. The bare apiGroup form
# ("kubeflow.org/v1") has no subdomain and never names an annotation key.
KEY_RE = re.compile(r"^[a-z0-9-]+(\.[a-z0-9-]+)*\.kubeflow\.org/[A-Za-z0-9._/-]+$")

# "tensorboard.kubeflow.org/v1alpha1" is an apiVersion VALUE, not a key
VERSION_SEGMENT_RE = re.compile(r"^v\d+((alpha|beta)\d+)?$")

CONST_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


class AnnotationLiteralRule(Rule):
    id = "TPU004"
    title = "annotation keys are named constants"
    invariant = (
        "every *.kubeflow.org/* annotation or label key appears exactly "
        "once as a module-level UPPER_CASE constant; all other sites "
        "import that constant"
    )
    rationale = (
        "these keys are crash-safe wire contracts: the suspend barrier, the "
        "bind annotation, the sharding ownership stamp, and the timeline "
        "marks all survive controller restarts ONLY because reader and "
        "writer agree on the key byte-for-byte. A retyped literal fails "
        "silently — the reader just never sees the state — and the soaks "
        "surface it as a convergence mystery instead of a grep-able "
        "constant (the sessions/sharding/timeline contracts all centralize "
        "keys for exactly this reason)."
    )
    approximation = (
        "matches string literals shaped like <subdomain>.kubeflow.org/<name> "
        "anywhere except the right-hand side of a module-level UPPER_CASE "
        "assignment. ApiVersion values (path segment v1/v1beta1/...) are "
        "exempt. Keys built with f-strings or concatenation are invisible; "
        "so are literals for other API groups."
    )

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        exempt: set[int] = set()
        for node in tree.body:
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if all(
                isinstance(t, ast.Name) and CONST_NAME_RE.match(t.id)
                for t in targets
            ):
                for sub in ast.walk(value):
                    exempt.add(id(sub))
        out: list[Finding] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            if id(node) in exempt or not KEY_RE.match(node.value):
                continue
            segment = node.value.split("/", 1)[1].split("/", 1)[0]
            if VERSION_SEGMENT_RE.match(segment):
                continue
            out.append(
                Finding(
                    self.id, path, node.lineno,
                    f'bare annotation key "{node.value}" — import the '
                    f"module-level constant that owns this wire contract",
                    qualname_of(node),
                )
            )
        return out
