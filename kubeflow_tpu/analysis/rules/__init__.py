"""tpulint rule families: one module per project invariant.

Shared AST helpers live here; each rule module imports them. The registry
(:func:`default_rules`) constructs FRESH rule instances per engine run —
TPU005 accumulates cross-file state, so instances must not be reused.
"""
from __future__ import annotations

import ast

# ------------------------------------------------------------- AST helpers


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def chain_parts(node: ast.AST) -> list[str]:
    """Attribute-chain parts left to right (``cluster.inner.patch`` →
    ``["cluster", "inner", "patch"]``); empty when the root is dynamic."""
    d = dotted(node)
    return d.split(".") if d else []


def qualname_of(node: ast.AST) -> str:
    """Enclosing ``Class.method`` / ``function`` qualname (the engine
    annotates parent links once per parsed file, before any rule runs)."""
    parts: list[str] = []
    cur = getattr(node, "_tpulint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_tpulint_parent", None)
    return ".".join(reversed(parts)) or "<module>"


def reconciler_classes(tree: ast.Module) -> list[ast.ClassDef]:
    """Classes that define a ``reconcile`` method — the reconciler shape
    TPU002/TPU003 scope to (subclassing is invisible across modules to a
    single-file AST pass; defining reconcile() is the honest local signal)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
            isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
            and item.name == "reconcile"
            for item in node.body
        ):
            out.append(node)
    return out


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# --------------------------------------------------------------- registry


def default_rules():
    from kubeflow_tpu.analysis.rules.annotations import AnnotationLiteralRule
    from kubeflow_tpu.analysis.rules.determinism import DeterminismRule
    from kubeflow_tpu.analysis.rules.metrics_rules import MetricsRegistrationRule
    from kubeflow_tpu.analysis.rules.reconcile_io import ReconcileIORule
    from kubeflow_tpu.analysis.rules.write_surface import WriteSurfaceRule

    return [
        DeterminismRule(),
        WriteSurfaceRule(),
        ReconcileIORule(),
        AnnotationLiteralRule(),
        MetricsRegistrationRule(),
    ]


RULE_IDS = ("TPU001", "TPU002", "TPU003", "TPU004", "TPU005")
