"""TPU002: cluster mutations flow through the traced client surface, once."""
from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import Finding, Rule
from kubeflow_tpu.analysis.rules import (
    chain_parts,
    qualname_of,
    reconciler_classes,
)

WRITE_VERBS = {
    "create", "update", "update_status", "patch", "strategic_patch",
    "delete", "finalize", "emit_event",
}

RAW_HANDLE_CTORS = {"FakeCluster", "KubeClient", "ChaosCluster"}

STATUS_WRITE_VERBS = {"update_status"}


class WriteSurfaceRule(Rule):
    id = "TPU002"
    title = "one traced write surface, one status write per path"
    invariant = (
        "reconcilers mutate the cluster only through the client surface "
        "injected into reconcile() (the Manager passes the TracingCluster "
        "wrapper): never through .inner, never through a handle they "
        "construct themselves — and a single reconcile path issues at most "
        "one status write to one object"
    )
    rationale = (
        "the trace audit proves every write attributable to a reconcile "
        "span, and the chaos layer injects faults, ONLY on the wrapped "
        "surface — a write on a raw handle is invisible to both. The "
        "one-write barrier is the bind/ack atomicity contract: PR 2's "
        "double-booking and PR 4's ack-loss race were both cured by "
        "collapsing multi-write sequences into ONE crash-safe write."
    )
    approximation = (
        "scoped to files defining a class with a reconcile() method. "
        "Raw-handle writes are caught at the .inner attribute chain and at "
        "FakeCluster()/KubeClient() construction inside reconciler classes; "
        "a handle smuggled through another module is invisible (the dynamic "
        "trace audit still catches it per seed). The one-write check flags "
        "two update_status calls on the same expression in one function "
        "unless they sit in mutually exclusive branches of the same "
        "if/try — write helpers called twice are not followed."
    )

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        classes = reconciler_classes(tree)
        if not classes:
            return []
        out: list[Finding] = []

        # (a) writes that bypass the wrapped surface — anywhere in the file
        # (module-level helpers are part of the reconcile path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            parts = chain_parts(node.func)
            if not parts or parts[-1] not in WRITE_VERBS:
                continue
            if "inner" in parts[:-1]:
                out.append(
                    Finding(
                        self.id, path, node.lineno,
                        f"write {'.'.join(parts)}(...) reaches through "
                        f".inner — bypasses the traced/chaos client surface",
                        qualname_of(node),
                    )
                )

        # (b) raw handle construction inside a reconciler class
        for cls in classes:
            for node in ast.walk(cls):
                if isinstance(node, ast.Call):
                    parts = chain_parts(node.func)
                    if parts and parts[-1] in RAW_HANDLE_CTORS:
                        out.append(
                            Finding(
                                self.id, path, node.lineno,
                                f"{parts[-1]}(...) constructed inside "
                                f"reconciler {cls.name} — use the client "
                                f"surface injected into reconcile()",
                                qualname_of(node),
                            )
                        )

        # (c) the one-write barrier: two status writes to one object on one
        # non-exclusive path through a function
        for cls in classes:
            for fn in ast.walk(cls):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(self._double_status_writes(path, fn))
        return out

    def _double_status_writes(
        self, path: str, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> list[Finding]:
        calls: list[tuple[ast.Call, tuple, str]] = []

        def visit(node: ast.AST, branch_path: tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                return  # nested defs are their own paths
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in STATUS_WRITE_VERBS and node.args:
                    calls.append(
                        (node, branch_path, ast.unparse(node.args[0]))
                    )
            if isinstance(node, ast.If):
                visit_all(node.test, branch_path)
                for child in node.body:
                    visit(child, branch_path + ((id(node), "then"),))
                for child in node.orelse:
                    visit(child, branch_path + ((id(node), "else"),))
                return
            if isinstance(node, ast.Try):
                for child in node.body:
                    visit(child, branch_path + ((id(node), "try"),))
                for i, handler in enumerate(node.handlers):
                    for child in handler.body:
                        visit(child, branch_path + ((id(node), f"except{i}"),))
                for child in node.orelse:
                    visit(child, branch_path + ((id(node), "try"),))
                for child in node.finalbody:
                    visit(child, branch_path)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, branch_path)

        def visit_all(node: ast.AST, branch_path: tuple) -> None:
            for child in ast.walk(node):
                if isinstance(child, ast.Call) and isinstance(child.func, ast.Attribute):
                    if child.func.attr in STATUS_WRITE_VERBS and child.args:
                        calls.append(
                            (child, branch_path, ast.unparse(child.args[0]))
                        )

        for stmt in fn.body:
            visit(stmt, ())

        out: list[Finding] = []
        flagged: set[int] = set()
        for i, (a, pa, arg_a) in enumerate(calls):
            for b, pb, arg_b in calls[i + 1:]:
                if arg_a != arg_b or id(b) in flagged:
                    continue
                if _mutually_exclusive(pa, pb):
                    continue
                flagged.add(id(b))
                out.append(
                    Finding(
                        self.id, path, b.lineno,
                        f"second status write to {arg_b} on one path "
                        f"through {fn.name}() — the one-write barrier "
                        f"requires a single crash-safe status write",
                        qualname_of(b),
                    )
                )
        return out


def _mutually_exclusive(pa: tuple, pb: tuple) -> bool:
    arms_a = dict(pa)
    for nid, arm in pb:
        if nid in arms_a and arms_a[nid] != arm:
            return True
    return False
