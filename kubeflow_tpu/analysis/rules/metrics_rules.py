"""TPU005: metric families registered once, with valid names and labels."""
from __future__ import annotations

import ast
import dataclasses
import re

from kubeflow_tpu.analysis.engine import Finding, Rule
from kubeflow_tpu.analysis.rules import const_str, qualname_of

REGISTER_ATTRS = {"counter", "gauge", "histogram"}

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


@dataclasses.dataclass
class _Registration:
    path: str
    line: int
    context: str
    kind: str
    labels: tuple[str, ...] | None  # None = schema frozen at first use


class MetricsRegistrationRule(Rule):
    id = "TPU005"
    title = "metric families registered once, labels validated"
    invariant = (
        "every registry.counter/gauge/histogram(...) family name is a valid "
        "Prometheus identifier, its declared label names are valid and not "
        "__-reserved, and no family name is registered twice with a "
        "conflicting kind or label schema anywhere in the tree"
    )
    rationale = (
        "the Registry dedups identical re-registration (two apps sharing a "
        "registry) but a conflicting schema raises at RUNTIME — wherever "
        "the second process happens to start, which is how a sharded and an "
        "unsharded collector on one registry once let a crash-every-cycle "
        "scheduler look green. This folds the CI metrics-lint step into the "
        "analyzer: the exposition-grammar half stays dynamic "
        "(tests/test_metrics_exposition.py in the pytest sweep); the "
        "registration-discipline half is static and fails at commit time."
    )
    approximation = (
        "sees registrations whose family name is a string literal at a "
        ".counter/.gauge/.histogram call (wrappers forwarding a name "
        "variable, like the shard scope, are checked at their literal call "
        "sites). Labelnames are validated when passed as a literal "
        "list/tuple; identical duplicate registrations are allowed — only "
        "kind/schema conflicts fail. The schema comparison is "
        "order-sensitive, exactly like the runtime Registry's."
    )

    def __init__(self) -> None:
        self._families: dict[str, list[_Registration]] = {}

    def applies_to(self, path: str) -> bool:
        # cross-file registered-once needs the WHOLE scanned tree — a
        # tools/ or benchmarks/ script sharing a registry with the package
        # is exactly the second-process conflict the rationale cites
        return path.endswith(".py")

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in REGISTER_ATTRS
                and node.args
            ):
                continue
            name = const_str(node.args[0])
            if name is None:
                continue  # dynamic name: a forwarding wrapper, not a family
            ctx = qualname_of(node)
            if not METRIC_NAME_RE.match(name):
                out.append(
                    Finding(
                        self.id, path, node.lineno,
                        f'metric family "{name}" is not a valid Prometheus '
                        f"metric name",
                        ctx,
                    )
                )
            labels = _label_names(node)
            if labels is not None:
                for label in labels:
                    if not LABEL_NAME_RE.match(label) or label.startswith("__"):
                        out.append(
                            Finding(
                                self.id, path, node.lineno,
                                f'label "{label}" on family "{name}" is not '
                                f"a valid (non-reserved) Prometheus label "
                                f"name",
                                ctx,
                            )
                        )
            self._families.setdefault(name, []).append(
                _Registration(path, node.lineno, ctx, node.func.attr, labels)
            )
        return out

    def finalize(self) -> list[Finding]:
        out: list[Finding] = []
        for name, regs in sorted(self._families.items()):
            first = regs[0]
            for reg in regs[1:]:
                if reg.kind != first.kind:
                    out.append(
                        Finding(
                            self.id, reg.path, reg.line,
                            f'family "{name}" registered as {reg.kind} here '
                            f"but as {first.kind} in {first.path} "
                            f"({first.context}) — one family, one kind",
                            reg.context,
                        )
                    )
                elif (
                    reg.labels is not None
                    and first.labels is not None
                    # order-sensitive, like Registry._add: ["a","b"] vs
                    # ["b","a"] raises at the second process's startup
                    and tuple(reg.labels) != tuple(first.labels)
                ):
                    out.append(
                        Finding(
                            self.id, reg.path, reg.line,
                            f'family "{name}" registered with labels '
                            f"{list(reg.labels)} here but "
                            f"{list(first.labels)} in {first.path} "
                            f"({first.context}) — one registry, one schema "
                            f"per family (label order included)",
                            reg.context,
                        )
                    )
        return out


def _label_names(node: ast.Call) -> tuple[str, ...] | None:
    expr = None
    if len(node.args) >= 3:
        expr = node.args[2]
    for kw in node.keywords:
        if kw.arg == "labelnames":
            expr = kw.value
    if expr is None or isinstance(expr, ast.Constant) and expr.value is None:
        return None
    if isinstance(expr, (ast.List, ast.Tuple)):
        labels = []
        for elt in expr.elts:
            s = const_str(elt)
            if s is None:
                return None  # dynamic element: cannot verify statically
            labels.append(s)
        return tuple(labels)
    return None
