"""TPU003: no blocking I/O or telemetry scrapes reachable from reconcile()."""
from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import Finding, Rule
from kubeflow_tpu.analysis.rules import (
    chain_parts,
    dotted,
    reconciler_classes,
)

# module roots whose calls mean the reconcile worker is waiting on a network
# or process, holding its workqueue key the whole time
BANNED_ROOTS = {
    "socket", "requests", "urllib", "http", "subprocess", "ftplib",
    "smtplib", "telnetlib", "shutil",
}

BANNED_CALLS = {"open", "time.sleep", "input"}

# the telemetry collector's verbs; scraping from a reconcile was PR 5's
# founding prohibition. "capture" joined when obs/profiler.py landed: a
# trace capture probes N steps of a live gang — wiring the capture
# controller (or an agent's capture endpoint) into a reconcile is the same
# head-of-line block, only longer.
SCRAPE_ATTRS = {"collect", "scrape", "probe", "capture"}
SCRAPE_RECEIVER_HINTS = ("collector", "telemetry", "prober", "profiler")


class ReconcileIORule(Rule):
    id = "TPU003"
    title = "reconcile bodies never block on I/O"
    invariant = (
        "no socket/HTTP/file/subprocess I/O, sleeps, telemetry scrapes, or "
        "profile captures are reachable from a reconcile() body through "
        "same-module calls — slow externals run in dedicated loops (the "
        "fleet collector, the culler's prober, the capture controller) and "
        "reconcilers read their in-memory results"
    )
    rationale = (
        "a reconcile holds its workqueue key; one slow scrape inside it "
        "head-of-line-blocks every queued event for that key and skews the "
        "reconcile-duration SLO. PR 5 built the fleet collector around "
        "exactly this rule (one parallel scrape pass per interval, NEVER on "
        "the reconcile path) and the chaos soak asserts it dynamically per "
        "tick; this makes the regression a commit-time failure."
    )
    approximation = (
        "reachability is a same-module call graph: reconcile() plus "
        "module-level functions and self.* methods it transitively calls. "
        "Calls that cross modules are not followed (the soak's runtime "
        "scrape-pass assertion covers those); receivers are matched by "
        "name, so a collector bound to an innocuous local name passes "
        "statically."
    )

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        classes = reconciler_classes(tree)
        if not classes:
            return []
        module_funcs = {
            n.name: n
            for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        out: list[Finding] = []
        for cls in classes:
            methods = {
                n.name: n
                for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            entry = methods.get("reconcile")
            if entry is None:
                continue
            # same-module reachability from reconcile(); `seen` is the
            # revisit guard, `via` carries the call chain for the finding
            frontier = [(entry, f"{cls.name}.reconcile")]
            seen = {id(entry)}
            while frontier:
                fn, via = frontier.pop()
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = None
                    label = None
                    if isinstance(node.func, ast.Name):
                        callee = module_funcs.get(node.func.id)
                        label = node.func.id
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                    ):
                        callee = methods.get(node.func.attr)
                        label = f"{cls.name}.{node.func.attr}"
                    if callee is not None and id(callee) not in seen:
                        seen.add(id(callee))
                        frontier.append((callee, f"{via} -> {label}"))
                    out.extend(self._banned(path, node, via))
        return out

    def _banned(self, path: str, node: ast.Call, via: str) -> list[Finding]:
        name = dotted(node.func)
        findings: list[Finding] = []

        def flag(message: str) -> None:
            findings.append(Finding(self.id, path, node.lineno, message, via))

        if name in BANNED_CALLS:
            flag(f"{name}() on the reconcile path ({via}) — reconcilers "
                 f"must not block; move it to a dedicated loop")
        elif name is not None and name.split(".")[0] in BANNED_ROOTS:
            flag(f"{name}(...) on the reconcile path ({via}) — network/"
                 f"process I/O never runs inside a reconcile")
        elif isinstance(node.func, ast.Attribute) and node.func.attr in SCRAPE_ATTRS:
            parts = chain_parts(node.func)[:-1]
            if any(
                hint in part.lower()
                for part in parts
                for hint in SCRAPE_RECEIVER_HINTS
            ):
                flag(
                    f"telemetry scrape {'.'.join(parts)}.{node.func.attr}() "
                    f"on the reconcile path ({via}) — the collector runs in "
                    f"its own loop; reconcilers read its in-memory store"
                )
        return findings
