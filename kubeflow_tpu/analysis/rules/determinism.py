"""TPU001: seeded-world determinism in the control plane's replayable core."""
from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import Finding, Rule
from kubeflow_tpu.analysis.rules import dotted, qualname_of

# directories whose behavior must replay bit-identically from a seed: the
# chaos/sched/sessions soaks promise "any failure reproduces from its printed
# seed alone", which is only true while every draw and every timestamp flows
# from the injected clock / seeded RNG
SCOPED_DIRS = (
    "kubeflow_tpu/scheduler/",
    "kubeflow_tpu/sessions/",
    "kubeflow_tpu/runtime/",
    "kubeflow_tpu/testing/",
    # the capacity soak promises the same seed-alone reproducibility: the
    # autoscaler runs on the injected clock and the fake provider draws
    # every fault from its own seeded stream
    "kubeflow_tpu/capacity/",
    # the SPMD runtime's whole contract is that every host derives the same
    # mesh/identity from its env alone — any nondeterminism here desyncs a
    # gang, and the soak audit (spmd/fanout.py) replays from the seed
    "kubeflow_tpu/spmd/",
    # the telemetry pipeline rides the soaks' seed-alone promise too: the
    # collector, the gang aggregator, and the fake agents all run on the
    # injected clock (wall time only through the clock/perf params), and
    # the gang attribution audit replays plants from the seed
    "kubeflow_tpu/telemetry/",
    # same for the observability layer: events dedup, traces, timelines,
    # the SLO ring, and the efficiency ledger are all audited per seed
    "kubeflow_tpu/obs/",
)

WALL_CLOCK_CALLS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.process_time",
}

DATETIME_CALLS = {
    "datetime.datetime.now",
    "datetime.now",
    "datetime.datetime.utcnow",
    "datetime.utcnow",
    "datetime.datetime.today",
    "datetime.today",
    "datetime.date.today",
    "date.today",
}

UUID_CALLS = {"uuid.uuid4", "uuid.uuid1"}

# module-level draws consume global (unseeded) state; drawing from a named
# random.Random(seed) stream is the sanctioned form
RANDOM_DRAWS = {
    "random." + f
    for f in (
        "random", "uniform", "randint", "randrange", "choice", "choices",
        "shuffle", "sample", "gauss", "betavariate", "expovariate",
        "normalvariate", "lognormvariate", "vonmisesvariate", "paretovariate",
        "weibullvariate", "triangular", "getrandbits", "randbytes", "seed",
    )
}


class DeterminismRule(Rule):
    id = "TPU001"
    title = "seeded-world determinism"
    invariant = (
        "scheduler/, sessions/, runtime/, and testing/ never read the wall "
        "clock, draw from unseeded RNG state, mint uuids, or iterate an "
        "unordered set — time comes from the injected clock parameter, "
        "randomness from a named random.Random(seed) stream, iteration "
        "order from sorted()"
    )
    rationale = (
        "the soaks' whole contract is seed-replay (docs/chaos.md): PR 10 "
        "shipped a latent nondeterminism where store-fault draws were keyed "
        "on uuid4-bearing object keys, so two runs of the same seed drew "
        "different faults — found by luck, fixed by hand. This rule makes "
        "that class of bug a commit-time failure."
    )
    approximation = (
        "flags direct CALLS (time.time(), random.uniform(), uuid.uuid4(), "
        "datetime.now()) and iteration whose target is literally a set "
        "display/comprehension or set()/frozenset() call. Bare references "
        "(clock: Callable = time.time as a default parameter) are the "
        "injection seam itself and pass; draws on a local rng variable "
        "(rng.random()) pass — the seeded-stream discipline is enforced at "
        "the construction site (random.Random() with no seed is flagged)."
    )

    def applies_to(self, path: str) -> bool:
        return path.startswith(SCOPED_DIRS)

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        out: list[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            out.append(
                Finding(
                    self.id, path, getattr(node, "lineno", 0), message,
                    qualname_of(node),
                )
            )

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in WALL_CLOCK_CALLS:
                    flag(node, f"wall-clock call {name}() — take the clock "
                               f"as an injected parameter")
                elif name in DATETIME_CALLS:
                    flag(node, f"wall-clock call {name}() — derive "
                               f"timestamps from the injected clock")
                elif name in UUID_CALLS:
                    flag(node, f"{name}() mints a nondeterministic id — "
                               f"derive ids from seeded/content state")
                elif name in RANDOM_DRAWS:
                    flag(node, f"{name}() draws from the global RNG — draw "
                               f"from a named random.Random(seed) stream")
                elif name == "random.Random" and not (node.args or node.keywords):
                    flag(node, "random.Random() without a seed — name the "
                               "seed so the stream replays")
                elif name == "random.SystemRandom":
                    flag(node, "random.SystemRandom is entropy-backed and "
                               "can never replay from a seed")
            iter_expr = None
            if isinstance(node, ast.For):
                iter_expr = node.iter
            elif isinstance(node, ast.comprehension):
                iter_expr = node.iter
            if iter_expr is not None and _is_unordered(iter_expr):
                flag(
                    iter_expr,
                    "iteration over an unordered set — wrap in sorted() so "
                    "visit order replays from the seed",
                )
        return out


def _is_unordered(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        return name in ("set", "frozenset")
    return False
