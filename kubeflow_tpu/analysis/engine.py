"""tpulint engine: AST-based project-invariant lint with a justified baseline.

Eleven PRs of control-plane growth rest on invariants that were, until now,
enforced only dynamically — by thousand-seed chaos soaks that catch
violations late (PR 10's seed-replay nondeterminism from uuid4-keyed fault
draws, PR 4's ack-loss race, PR 2's double-booking all shipped first and
were caught by soak luck). This package moves the machine-checkable part of
those contracts to commit time, the way TensorFlow moved graph invariants
into static validation (PAPERS.md):

- :class:`Rule` subclasses (``analysis/rules/``) each codify ONE project
  invariant as an AST check, with an id (TPU001..TPU005), a one-line
  invariant statement, and a rationale linking back to the soak/PR that
  motivated it (``tools/tpulint.py --explain TPU001``);
- :class:`LintEngine` parses each file once and fans the tree out to every
  applicable rule; rules may also carry cross-file state resolved in
  :meth:`Rule.finalize` (TPU005's registered-once check needs the whole
  tree);
- :class:`Baseline` grandfathers pre-existing findings: a committed JSON
  file maps finding fingerprints (line-number independent) to one-line
  justifications. A finding not in the baseline fails the build; a baseline
  entry whose finding disappeared is STALE and also fails the build (the
  baseline can only shrink or be consciously re-justified); an entry with
  an empty justification is rejected. ``--update-baseline`` rewrites the
  file from the current tree, preserving existing justifications;
- inline suppression: ``# tpulint: disable=TPU001 — <why>`` on the
  offending line suppresses that rule there. The justification text is
  REQUIRED — a bare pragma suppresses nothing.

Stdlib-only (the astlint precedent: a gate nobody can run locally rots).
Static analysis is necessarily approximate; every rule documents what it
can and cannot see in its ``--explain`` text, and the chaos soaks keep the
dynamic half of each contract (docs/analysis.md).
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
from pathlib import Path
from typing import Iterable, Sequence

# one pragma grammar everywhere: "# tpulint: disable=TPU001[,TPU002] — why"
PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Z0-9,]+)\s*(?:[-—–:]+\s*)?(.*?)\s*$"
)

SKIP_DIR_PARTS = {"__pycache__", ".git", "node_modules"}

# the default scan: the package plus every production-adjacent script dir,
# so cross-file rules (TPU005's registered-once check) really do see the
# whole tree a process could import at runtime
DEFAULT_SCAN_DIRS = ("kubeflow_tpu", "tools", "benchmarks", "loadtest")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    The fingerprint deliberately excludes the line number: moving code must
    not churn the baseline. It hashes (rule, path, enclosing qualname,
    message); messages therefore name symbols, never positions.
    """

    rule: str
    path: str
    line: int
    message: str
    context: str = "<module>"

    @property
    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.path}|{self.context}|{self.message}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "context": self.context,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} {self.message} "
            f"[{self.context}] {{{self.fingerprint}}}"
        )


class Rule:
    """One project invariant as an AST check.

    Subclasses set the class attributes and implement :meth:`check`. Rules
    are stateful per engine run (TPU005 accumulates registrations across
    files); construct fresh instances per run via :func:`default_rules`.
    """

    id: str = ""
    title: str = ""
    invariant: str = ""       # one line: what must hold
    rationale: str = ""       # why: the soak/PR that motivated it
    approximation: str = ""   # what the static check can and cannot see

    def applies_to(self, path: str) -> bool:
        return path.startswith("kubeflow_tpu/")

    def check(self, path: str, tree: ast.Module, source: str) -> list[Finding]:
        raise NotImplementedError

    def finalize(self) -> list[Finding]:
        """Cross-file findings, reported once after every file was checked."""
        return []

    @classmethod
    def explain(cls) -> str:
        lines = [
            f"{cls.id} — {cls.title}",
            "",
            f"Invariant: {cls.invariant}",
            "",
            f"Why: {cls.rationale}",
        ]
        if cls.approximation:
            lines += ["", f"Approximation: {cls.approximation}"]
        lines += [
            "",
            "Suppress: add the finding's fingerprint to the committed",
            "baseline (tools/tpulint.py --update-baseline, then fill in a",
            "one-line justification), or inline on the offending line:",
            f"  # tpulint: disable={cls.id} — <why this site is exempt>",
            "Both forms REQUIRE the justification text (docs/analysis.md).",
        ]
        return "\n".join(lines)


def annotate_parents(tree: ast.AST) -> None:
    """One parent-link pass per parsed file, done by the engine before any
    rule runs — rules' ``qualname_of`` walks these links."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._tpulint_parent = node  # type: ignore[attr-defined]


def parse_pragmas(source: str) -> dict[int, tuple[set[str], str]]:
    """``{line: (rule_ids, justification)}`` for every tpulint pragma."""
    out: dict[int, tuple[set[str], str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(text)
        if m:
            out[i] = (set(m.group(1).split(",")), m.group(2).strip())
    return out


def _suppressed(finding: Finding, pragmas: dict[int, tuple[set[str], str]]) -> bool:
    entry = pragmas.get(finding.line)
    if entry is None:
        return False
    rules, justification = entry
    # a pragma with no justification suppresses nothing — the rule catalog
    # promises every exemption carries its why
    return finding.rule in rules and bool(justification)


class LintEngine:
    """Parses each file once; fans the tree out to every applicable rule."""

    def __init__(self, root: Path | str, rules: Sequence[Rule] | None = None) -> None:
        self.root = Path(root)
        if rules is None:
            from kubeflow_tpu.analysis.rules import default_rules

            rules = default_rules()
        self.rules = list(rules)
        self.parse_errors: list[Finding] = []
        self.scanned_paths: set[str] = set()

    # ------------------------------------------------------------- file walk

    def iter_sources(self, paths: Sequence[str] | None = None) -> Iterable[tuple[str, str]]:
        """Yield (repo-relative posix path, source) for every .py file."""
        pairs = (
            [(p, self.root / p) for p in paths]
            if paths
            else [
                (d, self.root / d)
                for d in DEFAULT_SCAN_DIRS
                if (self.root / d).exists()
            ]
        )
        for given, target in pairs:
            # a typo'd or out-of-tree path must not read as "0 findings,
            # exit 0" — that would silently disable every gate while green
            if not target.exists():
                raise FileNotFoundError(
                    f"tpulint: no such file or directory: {given}"
                )
            try:
                target.relative_to(self.root)
            except ValueError:
                raise FileNotFoundError(
                    f"tpulint: path is outside the repo root: {given}"
                )
            files = [target] if target.is_file() else sorted(target.rglob("*.py"))
            for f in files:
                if SKIP_DIR_PARTS.intersection(f.parts):
                    continue
                rel = f.relative_to(self.root).as_posix()
                yield rel, f.read_text()

    # ------------------------------------------------------------------- run

    def run(
        self,
        paths: Sequence[str] | None = None,
        only: set[str] | None = None,
    ) -> list[Finding]:
        return self.run_sources(self.iter_sources(paths), only=only)

    def run_sources(
        self,
        sources: Iterable[tuple[str, str]],
        only: set[str] | None = None,
    ) -> list[Finding]:
        """Lint in-memory (path, source) pairs — the engine's real entry
        point; ``run`` feeds it from disk, tests feed planted fixtures."""
        rules = [r for r in self.rules if only is None or r.id in only]
        pragma_maps: dict[str, dict[int, tuple[set[str], str]]] = {}
        findings: list[Finding] = []
        self.parse_errors = []
        self.scanned_paths = set()
        for rel, source in sources:
            self.scanned_paths.add(rel)
            applicable = [r for r in rules if r.applies_to(rel)]
            if not applicable:
                continue
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                # a file that does not parse is astlint/ruff's finding, not
                # ours — but silently skipping it would hide every invariant
                # in it, so surface it as an engine-level parse error
                self.parse_errors.append(
                    Finding("PARSE", rel, e.lineno or 0, f"syntax error: {e.msg}")
                )
                continue
            annotate_parents(tree)
            pragmas = parse_pragmas(source)
            pragma_maps[rel] = pragmas
            for rule in applicable:
                for f in rule.check(rel, tree, source):
                    if not _suppressed(f, pragmas):
                        findings.append(f)
        for rule in rules:
            for f in rule.finalize():
                if not _suppressed(f, pragma_maps.get(f.path, {})):
                    findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        return findings


# ------------------------------------------------------------------ baseline


@dataclasses.dataclass
class BaselineEntry:
    fingerprint: str
    rule: str
    path: str
    context: str
    message: str
    justification: str = ""
    # identical violations in one context share a fingerprint (it is
    # line-independent by design); the count pins HOW MANY are
    # grandfathered, so adding one more identical violation next to a
    # baselined one still fails the gate
    count: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BaselineResult:
    new: list[Finding]              # findings with no baseline entry
    matched: list[Finding]          # grandfathered findings
    stale: list[BaselineEntry]      # entries whose finding disappeared
    unjustified: list[BaselineEntry]  # matched entries missing their why

    @property
    def clean(self) -> bool:
        return not (self.new or self.stale or self.unjustified)


class Baseline:
    """Committed set of grandfathered findings, each with a justification.

    The contract (docs/analysis.md): the baseline can only shrink or be
    consciously re-justified. New findings fail; stale entries fail (fixing
    a finding must delete its entry, or the file rots into an allowlist of
    things that no longer exist); empty justifications fail.
    """

    def __init__(self, entries: Iterable[BaselineEntry] = ()) -> None:
        self.entries: dict[str, BaselineEntry] = {
            e.fingerprint: e for e in entries
        }

    @classmethod
    def load(cls, path: Path | str) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls()
        data = json.loads(p.read_text())
        return cls(
            BaselineEntry(**e) for e in data.get("entries", [])
        )

    def save(self, path: Path | str) -> None:
        entries = sorted(
            self.entries.values(), key=lambda e: (e.rule, e.path, e.message)
        )
        Path(path).write_text(
            json.dumps(
                {"version": 1, "entries": [e.to_dict() for e in entries]},
                indent=1,
            )
            + "\n"
        )

    def apply(
        self,
        findings: Sequence[Finding],
        only: set[str] | None = None,
        paths: set[str] | None = None,
    ) -> BaselineResult:
        """``only``/``paths`` scope STALENESS the same way they scoped the
        run: an entry whose rule was not run, or whose file was not
        scanned, cannot be judged gone — only the full-tree run (CI's
        gate) can shrink the baseline.

        Counts are exact per fingerprint: an entry grandfathers exactly
        ``count`` identical findings — the (count+1)th identical violation
        is NEW, and a count that shrank makes the entry stale (fixing one
        of three must re-record, or the headroom silently grandfathers a
        future regression)."""
        current: dict[str, int] = {}
        for f in findings:
            current[f.fingerprint] = current.get(f.fingerprint, 0) + 1
        new, matched = [], []
        used: dict[str, int] = {}
        for f in findings:
            entry = self.entries.get(f.fingerprint)
            used[f.fingerprint] = used.get(f.fingerprint, 0) + 1
            if entry is not None and used[f.fingerprint] <= entry.count:
                matched.append(f)
            else:
                new.append(f)
        stale = [
            e
            for fp, e in sorted(self.entries.items())
            if current.get(fp, 0) < e.count
            and (only is None or e.rule in only)
            and (paths is None or e.path in paths)
        ]
        unjustified = [
            self.entries[fp]
            for fp in sorted({f.fingerprint for f in matched})
            if not self.entries[fp].justification.strip()
        ]
        return BaselineResult(new, matched, stale, unjustified)

    def updated_with(
        self,
        findings: Sequence[Finding],
        paths: set[str] | None = None,
        only: set[str] | None = None,
    ) -> "Baseline":
        """The ``--update-baseline`` rewrite: one entry per current finding,
        preserving the justification of entries that still match (new ones
        get an empty justification the operator must fill in — an empty
        justification fails the next run, so the TODO cannot ship silently).
        Entries outside the run's scope — a file not in ``paths``, a rule
        not in ``only`` — are kept verbatim: a scoped update must not
        silently ungrandfather (and unjustify) the rest of the tree."""
        out = [
            e for e in self.entries.values()
            if (paths is not None and e.path not in paths)
            or (only is not None and e.rule not in only)
        ]
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
        seen: set[str] = set()
        for f in findings:
            if f.fingerprint in seen:
                continue
            seen.add(f.fingerprint)
            prev = self.entries.get(f.fingerprint)
            out.append(
                BaselineEntry(
                    fingerprint=f.fingerprint,
                    rule=f.rule,
                    path=f.path,
                    context=f.context,
                    message=f.message,
                    justification=prev.justification if prev else "",
                    count=counts[f.fingerprint],
                )
            )
        return Baseline(out)
