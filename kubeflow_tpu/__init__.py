"""TPU-native notebook platform."""
