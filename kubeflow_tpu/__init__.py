"""TPU-native notebook platform.

Two halves, one package:

- **Control plane** (no jax import required): CRD types and reconcilers for
  Notebook/Profile/Tensorboard, admission webhooks, the web-app backends,
  and the runtime (manager, workqueue, clients).
- **Compute plane** (jax/flax/pallas): models, kernels, mesh/sharding rules,
  training-step builders, decoding.

Top-level names below lazy-import on first access, so importing
``kubeflow_tpu`` stays cheap for control-plane processes that never touch
jax — and vice versa.
"""
from __future__ import annotations

import importlib

# public name -> defining module (lazy; see __getattr__)
_EXPORTS = {
    # control plane
    "FakeCluster": "kubeflow_tpu.runtime.fake",
    "KubeClient": "kubeflow_tpu.runtime.kubeclient",
    "Manager": "kubeflow_tpu.runtime.manager",
    "NotebookReconciler": "kubeflow_tpu.controllers.notebook_controller",
    "ProfileReconciler": "kubeflow_tpu.controllers.profile_controller",
    "TensorboardReconciler": "kubeflow_tpu.controllers.tensorboard_controller",
    "ControllerConfig": "kubeflow_tpu.utils.config",
    # compute plane
    "MeshPlan": "kubeflow_tpu.parallel.mesh",
    "create_mesh": "kubeflow_tpu.parallel.mesh",
    "make_classifier_train_step": "kubeflow_tpu.parallel.train",
    "make_lm_train_step": "kubeflow_tpu.parallel.train",
    "TransformerConfig": "kubeflow_tpu.models.transformer",
    "TransformerLM": "kubeflow_tpu.models.transformer",
    "MoEConfig": "kubeflow_tpu.models.moe",
    "MoETransformerLM": "kubeflow_tpu.models.moe",
    "ResNet50": "kubeflow_tpu.models.resnet",
    "generate": "kubeflow_tpu.models.decoding",
    "decode_config": "kubeflow_tpu.models.decoding",
    "flash_attention": "kubeflow_tpu.ops.pallas_attention",
    "flash_decode": "kubeflow_tpu.ops.flash_decode",
    "ring_attention": "kubeflow_tpu.parallel.ring_attention",
    "adamw_lowmem": "kubeflow_tpu.ops.optimizers",
    "with_f32_master": "kubeflow_tpu.ops.optimizers",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache for subsequent lookups
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
