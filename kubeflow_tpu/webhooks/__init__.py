"""TPU-native notebook platform."""
