"""PodDefault admission mutator.

Behavioral parity with the reference admission-webhook
(``admission-webhook/main.go``): on pod CREATE, select the namespace's
PodDefault CRs whose label selector matches the pod, check that they can be
applied without conflicting with each other or the pod, then merge
env/envFrom/volumes/volumeMounts/tolerations/imagePullSecrets/labels/
annotations/serviceAccountName/command/args into the pod. The applied set is
recorded as ``poddefault.admission.kubeflow.org/<name>: <resourceVersion>``
annotations (ref: ``applyPodDefaultsOnPod`` main.go:422-486).

TPU-native detail: sidecar-ish containers (``istio-proxy``) are skipped for
command/args exactly as the reference does (main.go:514); additionally the TPU
worker env injected by ``tpu_env.py`` is protected — a PodDefault may not
shadow ``TPU_*``/``JAX_*`` worker identity variables (conflict → deny), since
a mesh with two pods disagreeing about TPU_WORKER_ID is undebuggable.
"""
from __future__ import annotations

from typing import Mapping

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import AdmissionDenied, FakeCluster

ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org/"
PROTECTED_ENV_PREFIXES = ("TPU_", "JAX_COORDINATOR", "JAX_PROCESS", "JAX_NUM")
SKIP_CONTAINERS = ("istio-proxy",)


def filter_pod_defaults(pod: Mapping, pod_defaults: list[dict]) -> list[dict]:
    """PodDefaults whose selector matches the pod (ref main.go:70-95)."""
    return [
        pd
        for pd in pod_defaults
        if ko.matches_selector(pod, pd.get("spec", {}).get("selector"))
    ]


def _merge_named(existing: list, incoming: list, what: str, key: str = "name") -> list:
    """Merge lists of named items; identical duplicates are dropped, same-name
    different-content items conflict (ref safeToApplyPodDefaults main.go:99-139)."""
    out = list(existing or [])
    index = {item.get(key): item for item in out}
    for item in incoming or []:
        cur = index.get(item.get(key))
        if cur is None:
            out.append(item)
            index[item.get(key)] = item
        elif cur != item:
            raise AdmissionDenied(
                f"conflicting {what} {item.get(key)!r} from PodDefaults"
            )
    return out


def check_safe(pod: Mapping, pds: list[dict]) -> None:
    """Raise AdmissionDenied if the PodDefault set conflicts with itself or the
    pod. Runs the same merges apply will run, against scratch copies."""
    merged_env = list(
        pod.get("spec", {}).get("containers", [{}])[0].get("env") or []
    )
    merged_vols = list(pod.get("spec", {}).get("volumes") or [])
    merged_mounts = list(
        pod.get("spec", {}).get("containers", [{}])[0].get("volumeMounts") or []
    )
    for pd in pds:
        spec = pd.get("spec", {})
        for e in spec.get("env") or []:
            # A PodDefault may neither override NOR introduce worker-identity
            # env: with N gang pods sharing one PodDefault, any TPU_*/JAX_*
            # value it sets is necessarily identical on every host — a broken
            # mesh regardless of webhook ordering.
            if any(e["name"].startswith(p) for p in PROTECTED_ENV_PREFIXES):
                raise AdmissionDenied(
                    f"PodDefault {ko.name(pd)} sets protected TPU worker env "
                    f"{e['name']!r}; worker identity is injected per-pod by "
                    "the platform"
                )
        merged_env = _merge_named(merged_env, spec.get("env"), "env var")
        merged_vols = _merge_named(merged_vols, spec.get("volumes"), "volume")
        merged_mounts = _merge_named(
            merged_mounts, spec.get("volumeMounts"), "volumeMount"
        )


def apply(pod: dict, pds: list[dict]) -> dict:
    """Merge PodDefaults into the pod (ref main.go:422-527). Mutates a copy."""
    pod = ko.deep_copy(pod)
    spec = pod.setdefault("spec", {})
    for pd in pds:
        pdspec = pd.get("spec", {})
        spec["volumes"] = _merge_named(
            spec.get("volumes"), pdspec.get("volumes"), "volume"
        )
        for secret in pdspec.get("imagePullSecrets") or []:
            if secret not in (spec.get("imagePullSecrets") or []):
                spec.setdefault("imagePullSecrets", []).append(secret)
        if pdspec.get("serviceAccountName") and not spec.get("serviceAccountName"):
            spec["serviceAccountName"] = pdspec["serviceAccountName"]
        for tol in pdspec.get("tolerations") or []:
            if tol not in (spec.get("tolerations") or []):
                spec.setdefault("tolerations", []).append(tol)
        for c in spec.get("containers", []) + spec.get("initContainers", []):
            c["env"] = _merge_named(c.get("env"), pdspec.get("env"), "env var")
            c["envFrom"] = (c.get("envFrom") or []) + list(pdspec.get("envFrom") or [])
            c["volumeMounts"] = _merge_named(
                c.get("volumeMounts"), pdspec.get("volumeMounts"), "volumeMount"
            )
            if not c["envFrom"]:
                del c["envFrom"]
            if c.get("name") not in SKIP_CONTAINERS:
                # ref setCommandAndArgs main.go:512-527: only set when unset
                if pdspec.get("command") and not c.get("command"):
                    c["command"] = list(pdspec["command"])
                if pdspec.get("args") and not c.get("args"):
                    c["args"] = list(pdspec["args"])
        meta = pod.setdefault("metadata", {})
        for k, v in (pdspec.get("labels") or {}).items():
            meta.setdefault("labels", {}).setdefault(k, v)
        for k, v in (pdspec.get("annotations") or {}).items():
            meta.setdefault("annotations", {}).setdefault(k, v)
        ko.set_annotation(
            pod,
            ANNOTATION_PREFIX + ko.name(pd),
            pd.get("metadata", {}).get("resourceVersion", "0"),
        )
    return pod


def mutator(pod: dict, cluster: FakeCluster) -> dict:
    """The webhook entrypoint registered on Pod CREATE
    (ref HTTP handler main.go:685-702, mutatePods main.go:529-634)."""
    ns = ko.namespace(pod)
    if not ns:
        return pod
    pds = filter_pod_defaults(pod, cluster.list("PodDefault", ns))
    if not pds:
        return pod
    pds.sort(key=ko.name)
    check_safe(pod, pds)
    return apply(pod, pds)


def install(cluster: FakeCluster) -> None:
    cluster.register_mutator("Pod", mutator)
