"""TPU worker-identity env injection (admission-time).

The piece with **no reference analog** (SURVEY.md §5 "distributed communication
backend: none in-repo"): the reference's GPU images get NCCL implicitly from
CUDA wheels and never coordinate across pods. Here, a multi-host slice needs
every pod to know (a) which host it is, (b) who its peers are, and (c) where
the coordinator lives — *before* user code runs, so
``jax.distributed.initialize()`` (driven by ``kubeflow_tpu.parallel.bootstrap``
inside the image) forms the ICI/DCN mesh with zero user configuration.

The reconciler cannot put per-pod values in a shared pod template; admission
can, because each pod CREATE carries its ordinal in the name. This mirrors how
the reference solves per-pod concerns at admission time rather than reconcile
time (PodDefaults, ``admission-webhook/main.go:529-634``).

Injected contract (read by ``parallel/bootstrap.py``):
  TPU_WORKER_ID         ordinal of this host in the slice (0..N-1)
  TPU_WORKER_HOSTNAMES  comma-separated stable DNS names of all hosts
  TPU_ACCELERATOR_TYPE  e.g. v4-16
  TPU_TOPOLOGY          e.g. 2x2x2
  JAX_COORDINATOR_ADDRESS  host0-dns:8476
  JAX_NUM_PROCESSES / JAX_PROCESS_ID
  TPU_SKIP_MDS_QUERY    skip GCE metadata lookups inside k8s pods
"""
from __future__ import annotations

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.tpu.topology import parse_topology
from kubeflow_tpu.utils.config import ControllerConfig

ACCEL_ANNOTATION = "tpu.kubeflow.org/accelerator"
TOPOLOGY_ANNOTATION = "tpu.kubeflow.org/topology"
NOTEBOOK_ANNOTATION = "tpu.kubeflow.org/notebook"


def _ordinal(pod_name: str) -> int | None:
    base, _, tail = pod_name.rpartition("-")
    return int(tail) if base and tail.isdigit() else None


def make_mutator(config: ControllerConfig | None = None):
    cfg = config or ControllerConfig()

    def mutate(pod: dict, cluster: FakeCluster) -> dict:
        anns = ko.annotations(pod)
        accel = anns.get(ACCEL_ANNOTATION)
        topo_str = anns.get(TOPOLOGY_ANNOTATION)
        notebook = anns.get(NOTEBOOK_ANNOTATION)
        if not (accel and topo_str and notebook):
            return pod
        ordinal = _ordinal(ko.name(pod))
        if ordinal is None:
            return pod
        topo = parse_topology(accel, topo_str)
        pod = ko.deep_copy(pod)
        hostnames = topo.worker_hostnames(
            notebook, ko.namespace(pod), cfg.cluster_domain
        )
        if topo.num_hosts == 1:
            # Single-host slice: no coordination needed; localhost identity.
            hostnames = ["localhost"]
        env = {
            "TPU_WORKER_ID": str(ordinal),
            "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
            "TPU_ACCELERATOR_TYPE": topo.slice_name,
            "TPU_TOPOLOGY": topo.topology_str,
            "TPU_CHIPS_PER_HOST_BOUNDS": "x".join(
                map(str, topo.accelerator.host_block)
            ),
            "TPU_SKIP_MDS_QUERY": "true",
            "JAX_COORDINATOR_ADDRESS": f"{hostnames[0]}:{cfg.tpu_coordinator_port}",
            "JAX_NUM_PROCESSES": str(topo.num_hosts),
            "JAX_PROCESS_ID": str(ordinal),
        }
        for c in pod.get("spec", {}).get("containers", []):
            if c.get("name") in ("istio-proxy",):
                continue
            existing = c.setdefault("env", [])
            have = {e.get("name") for e in existing}
            for k in sorted(env):
                if k not in have:  # user-set values win (explicit override)
                    existing.append({"name": k, "value": env[k]})
        return pod

    return mutate


def install(cluster: FakeCluster, config: ControllerConfig | None = None) -> None:
    cluster.register_mutator("Pod", make_mutator(config))
