"""TPU worker-identity env injection (admission-time).

The piece with **no reference analog** (SURVEY.md §5 "distributed communication
backend: none in-repo"): the reference's GPU images get NCCL implicitly from
CUDA wheels and never coordinate across pods. Here, a multi-host slice needs
every pod to know (a) which host it is, (b) who its peers are, and (c) where
the coordinator lives — *before* user code runs, so
``jax.distributed.initialize()`` (driven by ``kubeflow_tpu.parallel.bootstrap``
inside the image) forms the ICI/DCN mesh with zero user configuration.

The reconciler cannot put per-pod values in a shared pod template; admission
can, because each pod CREATE carries its ordinal in the name. This mirrors how
the reference solves per-pod concerns at admission time rather than reconcile
time (PodDefaults, ``admission-webhook/main.go:529-634``).

Injected contract (read by ``parallel/bootstrap.py``):
  TPU_WORKER_ID         ordinal of this host in ITS slice (0..N-1)
  TPU_WORKER_HOSTNAMES  comma-separated stable DNS names of this slice's hosts
  TPU_ACCELERATOR_TYPE  e.g. v4-16
  TPU_TOPOLOGY          e.g. 2x2x2
  JAX_COORDINATOR_ADDRESS  global host0-dns:8476 (slice 0's host 0)
  JAX_NUM_PROCESSES / JAX_PROCESS_ID   GLOBAL across all slices
  TPU_SKIP_MDS_QUERY    skip GCE metadata lookups inside k8s pods

Multislice (``spec.tpu.numSlices`` > 1; SURVEY.md §7 stage 3) adds the
cross-slice DCN contract:
  MEGASCALE_COORDINATOR_ADDRESS  slice 0's host 0 DNS
  MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID
"""
from __future__ import annotations

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import AdmissionDenied, FakeCluster
from kubeflow_tpu.tpu.topology import parse_topology
from kubeflow_tpu.utils.config import ControllerConfig

ACCEL_ANNOTATION = "tpu.kubeflow.org/accelerator"
TOPOLOGY_ANNOTATION = "tpu.kubeflow.org/topology"
NOTEBOOK_ANNOTATION = "tpu.kubeflow.org/notebook"
SLICE_ANNOTATION = "tpu.kubeflow.org/slice-id"
NUM_SLICES_ANNOTATION = "tpu.kubeflow.org/num-slices"


def _ordinal(pod_name: str) -> int | None:
    base, _, tail = pod_name.rpartition("-")
    return int(tail) if base and tail.isdigit() else None


def make_mutator(config: ControllerConfig | None = None):
    cfg = config or ControllerConfig()

    def mutate(pod: dict, cluster: FakeCluster) -> dict:
        anns = ko.annotations(pod)
        accel = anns.get(ACCEL_ANNOTATION)
        topo_str = anns.get(TOPOLOGY_ANNOTATION)
        notebook = anns.get(NOTEBOOK_ANNOTATION)
        if not (accel and topo_str and notebook):
            return pod
        ordinal = _ordinal(ko.name(pod))
        if ordinal is None:
            return pod
        topo = parse_topology(accel, topo_str)
        slice_id = int(anns.get(SLICE_ANNOTATION, "0"))
        num_slices = int(anns.get(NUM_SLICES_ANNOTATION, "1"))
        pod = ko.deep_copy(pod)
        ns = ko.namespace(pod)

        def slice_hostnames(j: int) -> list[str]:
            return topo.worker_hostnames(
                notebook, ns, cfg.cluster_domain,
                slice_id=None if num_slices == 1 else j,
            )

        hostnames = slice_hostnames(slice_id)
        if topo.num_hosts == 1 and num_slices == 1:
            # Single-host single-slice: no coordination; localhost identity.
            hostnames = ["localhost"]
        global_host0 = (
            hostnames[0] if num_slices == 1 else slice_hostnames(0)[0]
        )
        env = {
            "TPU_WORKER_ID": str(ordinal),
            "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
            "TPU_ACCELERATOR_TYPE": topo.slice_name,
            "TPU_TOPOLOGY": topo.topology_str,
            "TPU_CHIPS_PER_HOST_BOUNDS": "x".join(
                map(str, topo.accelerator.host_block)
            ),
            "TPU_SKIP_MDS_QUERY": "true",
            # jax.distributed identity is GLOBAL: every host of every slice
            # is one process; slice 0's host 0 coordinates the whole job.
            "JAX_COORDINATOR_ADDRESS": f"{global_host0}:{cfg.tpu_coordinator_port}",
            "JAX_NUM_PROCESSES": str(topo.num_hosts * num_slices),
            "JAX_PROCESS_ID": str(slice_id * topo.num_hosts + ordinal),
        }
        if num_slices > 1:
            env.update(
                {
                    "MEGASCALE_COORDINATOR_ADDRESS": global_host0,
                    "MEGASCALE_NUM_SLICES": str(num_slices),
                    "MEGASCALE_SLICE_ID": str(slice_id),
                }
            )
        for c in pod.get("spec", {}).get("containers", []):
            if c.get("name") in ("istio-proxy",):
                continue
            existing = c.setdefault("env", [])
            have = {e.get("name") for e in existing}
            for k in sorted(env):
                if k not in have:  # user-set values win (explicit override)
                    existing.append({"name": k, "value": env[k]})
        return pod

    return mutate


def family_label_mutator(nb: dict, cluster) -> dict:
    """Enforce/heal the ``tpu.kubeflow.org/accelerator-family`` label on
    Notebook CREATE **and UPDATE** (the ROADMAP sharding follow-on).

    The label is what lets a sharded scheduler's list/watch select only its
    own families server-side (``runtime/sharding.py``); before this it was
    creation-stamped client-side (``api.notebook``) and healed only by the
    owning shard's reconcile — a kubectl label-strip or spec drift left a
    window where the filtered ingest could not see the gang. Admission
    closes the window: a write that strips or mis-sets the label is
    rewritten to the family ``spec.tpu.accelerator`` proves, and a non-TPU
    notebook sheds a stale label (it is no gang; no shard owns it). The
    label stays an optimization, never the authority — ownership still
    re-derives from spec — but with admission enforcing it the hint can no
    longer silently lie."""
    from kubeflow_tpu.runtime.sharding import FAMILY_LABEL, notebook_family

    fam = notebook_family(nb)
    labels = (nb.get("metadata") or {}).get("labels") or {}
    if labels.get(FAMILY_LABEL) == fam or (
        fam is None and FAMILY_LABEL not in labels
    ):
        return nb
    nb = ko.deep_copy(nb)
    labels = nb.setdefault("metadata", {}).setdefault("labels", {})
    if fam is None:
        labels.pop(FAMILY_LABEL, None)
    else:
        labels[FAMILY_LABEL] = fam
    return nb


def tpu_spec_validator(nb: dict, cluster) -> dict:
    """Admission-deny Notebooks whose ``spec.tpu`` cannot fan out.

    Before this, only the spawner's POST path validated ``spec.tpu``
    (``api.validate_notebook``); a direct create (kubectl, a controllerless
    client) with a topology that doesn't map onto whole hosts sailed into
    the store and surfaced as a reconcile-time ``parse_topology`` crash —
    a runtime failure for an admission-shaped error. This validator is the
    cluster-side guard: topology must parse (including host-divisibility,
    ``tpu/topology.py``) and ``numSlices`` must be a positive integer.

    Scope is ``spec.tpu`` ONLY — container-level validation stays in the
    spawner (tests and internal tooling legitimately create minimal
    Notebook objects with no containers).

    Denials carry ``status = 400``: through the web apps' dispatcher this is
    a typed user-input 400, not admission's generic 403 (the client sent a
    bad spec; nothing about their permissions is wrong).
    """
    tpu = (nb.get("spec") or {}).get("tpu")
    if not tpu:
        return nb
    errors: list[str] = []
    try:
        parse_topology(tpu.get("accelerator", ""), tpu.get("topology", ""))
    except ValueError as e:
        errors.append(f"spec.tpu: {e}")
    raw = tpu.get("numSlices", 1)
    ok = False
    if isinstance(raw, int) and not isinstance(raw, bool):
        ok = raw >= 1
    elif isinstance(raw, str):
        try:
            ok = int(raw) >= 1
        except ValueError:
            ok = False
    if not ok:
        errors.append(
            f"spec.tpu.numSlices: must be an integer >= 1; got {raw!r}"
        )
    if errors:
        exc = AdmissionDenied("; ".join(errors))
        exc.status = 400  # user-input error, not a permission denial
        raise exc
    return nb


def install(cluster: FakeCluster, config: ControllerConfig | None = None) -> None:
    cluster.register_mutator("Pod", make_mutator(config))
    cluster.register_mutator(
        "Notebook", family_label_mutator, operations=("CREATE", "UPDATE")
    )
    cluster.register_mutator(
        "Notebook", tpu_spec_validator, operations=("CREATE", "UPDATE")
    )
