"""CRD multi-version conversion (ConversionReview webhook).

Reference parity: the Notebook CRD serves v1alpha1/v1beta1/v1 and the
controller registers all three in its scheme with conversion between them
(``notebook-controller/api/v1beta1/notebook_conversion.go``,
``main.go:46-54``). In the reference — as here — the versions are
structurally identical, so conversion is the hub-and-spoke boilerplate: the
object is passed through unchanged except for ``apiVersion``, with a
transform table for the day a version actually diverges.

The handler implements the apiextensions.k8s.io/v1 ConversionReview protocol
the API server speaks to conversion webhooks:

    request:  {uid, desiredAPIVersion, objects: [...]}
    response: {uid, result: {status}, convertedObjects: [...]}
"""
from __future__ import annotations

import copy
from typing import Callable

# (kind, from_version, to_version) -> transform(obj) -> obj.
# Versions here are the bare version (e.g. "v1alpha1"), group-agnostic.
# Structural divergence between served versions registers here; identity
# (apiVersion rewrite only) is the default, as in the reference's generated
# ConvertTo/ConvertFrom bodies.
TRANSFORMS: dict[tuple[str, str, str], Callable[[dict], dict]] = {}


def convert_object(obj: dict, desired_api_version: str) -> dict:
    """Convert one object to ``desired_api_version`` (e.g. kubeflow.org/v1)."""
    out = copy.deepcopy(obj)
    current = out.get("apiVersion", "")
    if current == desired_api_version:
        return out
    kind = out.get("kind", "")
    from_v = current.rsplit("/", 1)[-1]
    to_v = desired_api_version.rsplit("/", 1)[-1]
    transform = TRANSFORMS.get((kind, from_v, to_v))
    if transform is not None:
        out = transform(out)
    out["apiVersion"] = desired_api_version
    return out


def convert_review(review: dict) -> dict:
    """Handle a ConversionReview; returns the full response envelope."""
    request = review.get("request", {})
    uid = request.get("uid", "")
    desired = request.get("desiredAPIVersion", "")
    try:
        converted = [
            convert_object(o, desired) for o in request.get("objects", [])
        ]
        response = {
            "uid": uid,
            "result": {"status": "Success"},
            "convertedObjects": converted,
        }
    except Exception as e:  # a failed conversion must be a clean Failure
        response = {
            "uid": uid,
            "result": {"status": "Failure", "message": str(e)},
        }
    return {
        "apiVersion": review.get("apiVersion", "apiextensions.k8s.io/v1"),
        "kind": "ConversionReview",
        "response": response,
    }
