"""Kubernetes-object plumbing shared by the runtime and controllers.

Objects are plain dicts in wire format (what the reference manipulates through
client-go typed structs). Working in wire format keeps the store, admission
patches, and manifests in one representation and mirrors how the reference's
Python web apps already handle resources (``crud_backend/api/*.py``).
"""
from __future__ import annotations

import copy
from typing import Any, Iterable, Mapping

GROUP = "kubeflow.org"
TPU_GROUP = "tpu.kubeflow.org"


def gvk(obj: Mapping) -> tuple[str, str]:
    return obj.get("apiVersion", ""), obj.get("kind", "")


def meta(obj: Mapping) -> dict:
    return obj.setdefault("metadata", {})  # type: ignore[union-attr]


def name(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def labels(obj: Mapping) -> dict:
    return obj.get("metadata", {}).get("labels", {}) or {}


def annotations(obj: Mapping) -> dict:
    return obj.get("metadata", {}).get("annotations", {}) or {}


def set_annotation(obj: Mapping, key: str, value: str) -> None:
    meta(obj).setdefault("annotations", {})[key] = value


def remove_annotation(obj: Mapping, key: str) -> None:
    meta(obj).setdefault("annotations", {}).pop(key, None)


def deep_copy(obj: Any) -> Any:
    """Deep copy for JSON-like K8s object trees.

    Hand-rolled recursion over dict/list/scalars is ~15x faster than the
    generic ``copy.deepcopy`` (no memo table, no type dispatch) — and this
    is the control plane's hottest function: every FakeCluster read path
    copies objects out of the store (measured 93% of a 100-notebook spawn
    loadtest before this). Non-JSON leaves fall back to copy.deepcopy.
    """
    tp = type(obj)
    if tp is dict:
        return {k: deep_copy(v) for k, v in obj.items()}
    if tp is list:
        return [deep_copy(v) for v in obj]
    if tp in (str, int, float, bool, type(None)):
        return obj
    return copy.deepcopy(obj)


def matches_selector(obj: Mapping, selector: Mapping | None) -> bool:
    """LabelSelector match: matchLabels + matchExpressions (In/NotIn/Exists/
    DoesNotExist), the subset the reference's PodDefault filter uses
    (``admission-webhook/main.go:70-95``)."""
    if not selector:
        return True
    obj_labels = labels(obj)
    for k, v in (selector.get("matchLabels") or {}).items():
        if obj_labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op = expr.get("key"), expr.get("operator")
        values = expr.get("values") or []
        present = key in obj_labels
        if op == "Exists" and not present:
            return False
        if op == "DoesNotExist" and present:
            return False
        if op == "In" and (not present or obj_labels[key] not in values):
            return False
        if op == "NotIn" and present and obj_labels[key] in values:
            return False
    return True


def owner_reference(owner: Mapping, *, controller: bool = True) -> dict:
    return {
        "apiVersion": owner.get("apiVersion"),
        "kind": owner.get("kind"),
        "name": name(owner),
        "uid": meta(owner).get("uid"),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def set_controller_reference(obj: Mapping, owner: Mapping) -> None:
    refs = meta(obj).setdefault("ownerReferences", [])
    ref = owner_reference(owner)
    for existing in refs:
        if existing.get("uid") == ref["uid"]:
            existing.update(ref)
            return
    refs.append(ref)


def controller_owner(obj: Mapping) -> dict | None:
    for ref in meta(obj).get("ownerReferences", []) or []:
        if ref.get("controller"):
            return ref
    return None


def strategic_merge(base: Any, patch: Any) -> Any:
    """JSON-merge-patch-style dict merge (``None`` deletes), sufficient for the
    PATCH verbs our web apps expose (reference: ``apps/common/routes/patch.py``)."""
    if not isinstance(patch, Mapping) or not isinstance(base, Mapping):
        return deep_copy(patch)
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = strategic_merge(out.get(k), v)
    return out


def sort_env(env: Iterable[Mapping]) -> list:
    return sorted(env, key=lambda e: e.get("name", ""))
