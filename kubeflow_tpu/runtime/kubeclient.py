"""Real Kubernetes API client, same interface as ``runtime.fake.FakeCluster``.

The controllers and web apps are written against a small client surface
(create/get/list/update/patch/delete/watch + events). In tests that surface is
the in-memory store; in a cluster it is this REST client — direct HTTP to the
API server (the kubernetes python package is not in the image; the API is
plain REST and this keeps the dependency footprint at ``requests``).

In-cluster config discovery matches client-go: service-account token +
namespace + CA from ``/var/run/secrets/kubernetes.io/serviceaccount``,
API server from ``KUBERNETES_SERVICE_HOST/PORT`` (what the reference's Go
controllers get from ``rest.InClusterConfig``).
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Mapping

try:
    import requests
except ImportError:  # pragma: no cover
    requests = None

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import AlreadyExists, Conflict, NotFound

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# a stream that lived at least this long before failing was healthy: its
# failure is routine churn, not a degraded server (tests lower this)
HEALTHY_STREAM_S = 60.0

# transient statuses worth retrying inside one logical request; everything
# else is either a semantic answer (404/409/422) or a caller bug (403)
RETRYABLE_STATUSES = (429, 500, 502, 503, 504)


class RetriesExhausted(Exception):
    """A request kept failing transiently past the client's retry deadline.

    Carries ``attempts`` and ``last_status`` (None when the final failure was
    a connection error) so reconcilers and operators can tell a flaky
    apiserver from a dead one without parsing the message.
    """

    def __init__(self, path: str, attempts: int, last_status: int | None) -> None:
        self.attempts = attempts
        self.last_status = last_status
        super().__init__(
            f"{path}: {attempts} attempts failed, last status {last_status}"
        )


def _pause(backoff: float) -> None:
    """Full-jitter backoff sleep; module-level seam so tests can observe the
    sequence of backoff values without real sleeping."""
    time.sleep(random.uniform(0, backoff))


def _sleep(seconds: float) -> None:
    """Exact sleep (Retry-After honoring); separate seam from the jittered
    ``_pause`` so tests can distinguish the two."""
    time.sleep(seconds)


def _retry_after_seconds(resp) -> float | None:
    """Parse a Retry-After header (seconds form only; HTTP-date is rare from
    apiservers and not worth a date parser here)."""
    value = resp.headers.get("Retry-After")
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None

# kind -> (api prefix, group/version, plural, namespaced)
RESOURCES: dict[str, tuple[str, str, str, bool]] = {
    "Pod": ("api", "v1", "pods", True),
    "Service": ("api", "v1", "services", True),
    "Namespace": ("api", "v1", "namespaces", False),
    "Event": ("api", "v1", "events", True),
    "Secret": ("api", "v1", "secrets", True),
    "ConfigMap": ("api", "v1", "configmaps", True),
    "ServiceAccount": ("api", "v1", "serviceaccounts", True),
    "ResourceQuota": ("api", "v1", "resourcequotas", True),
    "PersistentVolumeClaim": ("api", "v1", "persistentvolumeclaims", True),
    "Node": ("api", "v1", "nodes", False),
    "StatefulSet": ("apis", "apps/v1", "statefulsets", True),
    "Deployment": ("apis", "apps/v1", "deployments", True),
    "RoleBinding": ("apis", "rbac.authorization.k8s.io/v1", "rolebindings", True),
    "Notebook": ("apis", "kubeflow.org/v1beta1", "notebooks", True),
    "Profile": ("apis", "kubeflow.org/v1", "profiles", False),
    "PodDefault": ("apis", "kubeflow.org/v1alpha1", "poddefaults", True),
    "Tensorboard": ("apis", "tensorboard.kubeflow.org/v1alpha1", "tensorboards", True),
    "VirtualService": ("apis", "networking.istio.io/v1alpha3", "virtualservices", True),
    "AuthorizationPolicy": ("apis", "security.istio.io/v1beta1", "authorizationpolicies", True),
    "Route": ("apis", "route.openshift.io/v1", "routes", True),
    "Lease": ("apis", "coordination.k8s.io/v1", "leases", True),
    # create-only review resource: the web apps' authz path posts these
    # (ref crud_backend/authz.py:46-80)
    "SubjectAccessReview": (
        "apis", "authorization.k8s.io/v1", "subjectaccessreviews", False,
    ),
}


_PLURAL_TO_KIND = {plural: kind for kind, (_, _, plural, _) in RESOURCES.items()}


def _path_kind(path: str) -> str:
    """Best-effort kind from an API path (write-span labeling)."""
    parts = [p for p in path.split("/") if p]
    for seg in reversed(parts):
        kind = _PLURAL_TO_KIND.get(seg)
        if kind is not None:
            return kind
    return "?"


def resource_path(
    kind: str,
    namespace: str | None = None,
    name: str | None = None,
    *,
    api_version: str | None = None,
) -> str:
    """API path for a kind (exported for tests). ``api_version`` overrides
    the default group/version — dynamic-client behavior for multi-version
    CRDs (a kubeflow.org/v1 Notebook goes to the v1 endpoint)."""
    prefix, gv, plural, namespaced = RESOURCES[kind]
    if api_version:
        gv = api_version
        prefix = "api" if "/" not in api_version else "apis"
    parts = [prefix, gv]
    if namespaced and namespace:
        parts += ["namespaces", namespace]
    parts.append(plural)
    if name:
        parts.append(name)
    return "/" + "/".join(parts)


class KubeClient:
    """Same call surface the controllers use on FakeCluster."""

    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        ca_cert: str | bool | None = None,
        session=None,
        *,
        retry_deadline_s: float = 15.0,
        retry_backoff_base: float = 0.2,
    ) -> None:
        # Bounded-retry policy: a request that keeps failing transiently
        # (429/5xx/connection reset) is retried with jittered exponential
        # backoff until retry_deadline_s of wall time has elapsed, then
        # surfaces as RetriesExhausted. The deadline (not an attempt count)
        # is what matters operationally: reconcile latency is budgeted in
        # seconds, and an unbounded retry loop inside the client would stall
        # a worker thread forever on a persistently-500ing apiserver while
        # the workqueue believes the key is being processed.
        self.retry_deadline_s = retry_deadline_s
        self.retry_backoff_base = retry_backoff_base
        # observability hooks (obs/): a ControlPlaneMetrics records per-verb
        # request latency + transient-retry counts; a Tracer records every
        # mutating verb as a write span under the current reconcile span; a
        # HealthState hears a beat per handled watch event / stream
        # (re)connect. All optional and settable after construction
        # (cmd/controller.py wires them).
        self.metrics = None
        self.tracer = None
        self.health = None
        if base_url is None:
            # KUBE_API_BASE_URL: out-of-cluster/dev hook (kubeconfig analog)
            # — the deploy-shape smoke points controller processes at the
            # conformance apiserver with it
            base_url = os.environ.get("KUBE_API_BASE_URL")
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        if token is None and os.path.isfile(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        if ca_cert is None:
            ca_cert = f"{SA_DIR}/ca.crt" if os.path.isfile(f"{SA_DIR}/ca.crt") else True
        self.verify = ca_cert
        self.session = session or requests.Session()
        if token:
            self.session.headers["Authorization"] = f"Bearer {token}"
        self._watch_threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # ------------------------------------------------------------------ http

    def _request(
        self,
        method: str,
        path: str,
        *,
        raw: bool = False,
        verb: str | None = None,
        **kw,
    ):
        """One logical request = bounded transient-retry loop.

        429/5xx and connection resets retry with jittered exponential backoff
        (Retry-After honored exactly on 429) until ``retry_deadline_s`` has
        elapsed, then surface as :class:`RetriesExhausted`. Semantic answers
        (404/409) and caller bugs (403/422) never retry.

        ``verb`` labels the request for metrics/tracing (create/get/list/...);
        it defaults to the HTTP method. The whole logical request — retries
        included — is one latency observation and one write span, matching
        what a reconcile actually waited for."""
        if verb is None:
            verb = method.lower()
        if self.metrics is None and self.tracer is None:
            return self._request_inner(method, path, verb, raw=raw, **kw)[0]
        started = time.monotonic()
        # span timestamps must come from the TRACER's clock (epoch/virtual) —
        # mixing a monotonic start with a wall-clock end would yield
        # billion-second durations
        span_start = self.tracer.clock() if self.tracer is not None else 0.0

        def done(status: str, attempts: int) -> None:
            if self.metrics is not None:
                self.metrics.api_latency.observe(
                    time.monotonic() - started, verb=verb
                )
            if self.tracer is not None and method != "GET":
                self.tracer.record_write(
                    verb, kind=_path_kind(path), key=path,
                    start=span_start, status=status,
                    retries=max(0, attempts - 1),
                )

        try:
            out, attempts = self._request_inner(
                method, path, verb, raw=raw, **kw
            )
        except RetriesExhausted as exc:
            done("RetriesExhausted", exc.attempts)
            raise
        except Exception as exc:
            done(type(exc).__name__, 1)
            raise
        done("ok", attempts)
        return out

    def _request_inner(
        self, method: str, path: str, verb: str = "", *, raw: bool = False, **kw
    ):
        deadline = time.monotonic() + self.retry_deadline_s
        backoff = self.retry_backoff_base
        attempts = 0
        last_status: int | None = None
        conn_errors = (
            (requests.RequestException, OSError) if requests else (OSError,)
        )
        while True:
            attempts += 1
            resp = None
            try:
                resp = self.session.request(
                    method, self.base_url + path, verify=self.verify, **kw
                )
            except conn_errors:
                last_status = None
            if resp is not None:
                if resp.status_code == 404:
                    raise NotFound(path)
                if resp.status_code == 409:
                    body = resp.text
                    if "AlreadyExists" in body:
                        raise AlreadyExists(path)
                    raise Conflict(body)
                if resp.status_code not in RETRYABLE_STATUSES:
                    resp.raise_for_status()
                    if raw:  # pod logs: the API returns text, not JSON
                        return resp.text, attempts
                    return (resp.json() if resp.content else {}), attempts
                last_status = resp.status_code
            if time.monotonic() >= deadline:
                raise RetriesExhausted(path, attempts, last_status)
            if self.metrics is not None:
                # counted at retry time (not at completion) so a scrape
                # mid-outage already shows the churn
                self.metrics.api_retries.inc(verb=verb or method.lower())
            retry_after = (
                _retry_after_seconds(resp)
                if resp is not None and resp.status_code == 429
                else None
            )
            if retry_after is not None:
                # the server named its price; cap it at the deadline so a
                # hostile/buggy Retry-After cannot stretch the budget
                _sleep(min(retry_after, max(0.0, deadline - time.monotonic())))
            else:
                _pause(min(backoff, max(0.0, deadline - time.monotonic())))
                backoff = min(backoff * 2, 5.0)

    # ------------------------------------------------------------------ CRUD

    def create(self, obj: Mapping) -> dict:
        kind = obj["kind"]
        return self._request(
            "POST",
            resource_path(
                kind, ko.namespace(obj), api_version=obj.get("apiVersion")
            ),
            verb="create",
            json=dict(obj),
        )

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        return self._request(
            "GET", resource_path(kind, namespace, name), verb="get"
        )

    def try_get(self, kind: str, name: str, namespace: str = "") -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def pod_logs(
        self,
        name: str,
        namespace: str,
        *,
        container: str | None = None,
        tail_lines: int | None = None,
    ) -> str:
        """GET /api/v1/.../pods/<name>/log (ref: read_namespaced_pod_log)."""
        params: dict = {}
        if container:
            params["container"] = container
        if tail_lines is not None:
            params["tailLines"] = tail_lines
        return self._request(
            "GET",
            f"/api/v1/namespaces/{namespace}/pods/{name}/log",
            params=params,
            raw=True,
        )

    def list(self, kind: str, namespace: str | None = None, selector: Mapping | None = None) -> list[dict]:
        params = {}
        if selector and selector.get("matchLabels"):
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in selector["matchLabels"].items()
            )
        out = self._request(
            "GET", resource_path(kind, namespace), verb="list", params=params
        )
        items = out.get("items", [])
        for item in items:  # list items omit kind/apiVersion; restore them
            item.setdefault("kind", kind)
        # client-side matchExpressions (server handles matchLabels)
        if selector and selector.get("matchExpressions"):
            items = [i for i in items if ko.matches_selector(i, selector)]
        return items

    def update(self, obj: Mapping) -> dict:
        kind = obj["kind"]
        return self._request(
            "PUT",
            resource_path(
                kind, ko.namespace(obj), ko.name(obj),
                api_version=obj.get("apiVersion"),
            ),
            verb="update",
            json=dict(obj),
        )

    def update_status(self, obj: Mapping) -> dict:
        """PUT to the /status subresource (the CRDs enable it, so .status on
        the main path would be silently discarded by the API server)."""
        kind = obj["kind"]
        return self._request(
            "PUT",
            resource_path(
                kind, ko.namespace(obj), ko.name(obj),
                api_version=obj.get("apiVersion"),
            ) + "/status",
            verb="update_status",
            json=dict(obj),
        )

    def patch(self, kind: str, name: str, namespace: str, patch: Mapping) -> dict:
        return self._request(
            "PATCH",
            resource_path(kind, namespace, name),
            verb="patch",
            json=dict(patch),
            headers={"Content-Type": "application/merge-patch+json"},
        )

    def strategic_patch(self, kind: str, name: str, namespace: str, patch: Mapping) -> dict:
        """Strategic merge patch: lists with a patchMergeKey merge by key
        (containers/env/volumes/...) instead of replacing wholesale."""
        return self._request(
            "PATCH",
            resource_path(kind, namespace, name),
            verb="patch",
            json=dict(patch),
            headers={"Content-Type": "application/strategic-merge-patch+json"},
        )

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._request(
            "DELETE", resource_path(kind, namespace, name), verb="delete"
        )

    def finalize(self, obj: Mapping) -> None:
        # real API server completes deletes once finalizers empty; nothing to do
        pass

    # ------------------------------------------------------------------ authz

    def subject_access_review(
        self,
        *,
        user: str,
        verb: str,
        resource: str,
        namespace: str = "",
        group: str = "",
        subresource: str = "",
        groups: tuple[str, ...] = (),
    ) -> bool:
        """POST a SubjectAccessReview and return ``status.allowed``.

        This is THE authz primitive on a real cluster: asking the API server
        answers for ClusterRoleBindings, aggregated roles, webhooks — anything
        a local RBAC re-implementation would get wrong
        (ref crud_backend/authz.py:46-80).
        """
        sar = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "spec": {
                "user": user,
                "groups": list(groups),
                "resourceAttributes": {
                    "group": group,
                    "resource": resource,
                    "subresource": subresource,
                    "verb": verb,
                    "namespace": namespace,
                },
            },
        }
        out = self._request(
            "POST", resource_path("SubjectAccessReview"), verb="create",
            json=sar,
        )
        return bool(out.get("status", {}).get("allowed", False))

    # ----------------------------------------------------------------- watch

    def watch(self, kind: str | None, fn: Callable[[str, dict], None]) -> None:
        """Streaming watch with informer-style incremental resume.

        The first connection lists (replaying objects as ADDED — the initial
        cache sync) and then watches from the list's resourceVersion. On
        disconnect it resumes the watch *from the last seen revision* —
        O(changes) per blip, not an O(objects) re-list-and-replay storm —
        falling back to a fresh list only on 410 Gone (revision compacted
        away, signalled either as an HTTP status or as an in-stream ERROR
        event, both of which real apiservers use). Backoff is exponential
        with full jitter so a fleet of severed watchers doesn't reconnect in
        lockstep. This is the resume contract controller-runtime's informers
        give the reference for free (``notebook_controller.go:726-774``).
        """
        if kind is None:
            raise ValueError("KubeClient.watch requires a concrete kind")

        def run():
            rv: str | None = None  # None → (re-)list before watching
            backoff = 0.5
            stream_started = 0.0
            while not self._stop.is_set():
                error_pause = False
                try:
                    if rv is None:
                        listing = self._request(
                            "GET", resource_path(kind), verb="list"
                        )
                        for item in listing.get("items", []):
                            item.setdefault("kind", kind)
                            fn("ADDED", item)
                        # only a fully-replayed list advances rv: if fn raised
                        # mid-replay, rv stays None and the next round re-lists
                        # (level-triggered self-healing, like before)
                        rv = listing.get("metadata", {}).get("resourceVersion", "0")
                    resp = self.session.get(
                        self.base_url + resource_path(kind),
                        params={"watch": "true", "resourceVersion": rv,
                                "allowWatchBookmarks": "true"},
                        stream=True,
                        verify=self.verify,
                        timeout=330,
                    )
                    if resp.status_code == 410:
                        rv = None
                        continue
                    resp.raise_for_status()  # 403 etc. → backoff path, not a busy loop
                    stream_started = time.monotonic()
                    if self.health is not None:
                        # connect counts as freshness: an idle-but-healthy
                        # stream delivers no events to beat on
                        self.health.beat(f"watch:{kind}")
                    for line in resp.iter_lines():
                        if self._stop.is_set():
                            return
                        if not line:
                            continue
                        event = json.loads(line)
                        etype = event.get("type")
                        obj = event.get("object", {})
                        if etype == "ERROR":
                            if obj.get("code") == 410:
                                rv = None  # compacted: full re-list
                            else:
                                error_pause = True  # persistent server error:
                                # reconnect with backoff, not a busy loop
                            break
                        if etype == "BOOKMARK":
                            new_rv = obj.get("metadata", {}).get("resourceVersion")
                            if new_rv:
                                rv = new_rv
                            backoff = 0.5  # bookmark has no handler: healthy
                            continue
                        obj.setdefault("kind", kind)
                        fn(etype or "MODIFIED", obj)
                        if self.health is not None:
                            self.health.beat(f"watch:{kind}")
                        # only a successfully *handled* event proves health —
                        # resetting before fn() would redeliver a poison event
                        # (handler always raises) at 2-4 Hz forever with no
                        # backoff growth
                        backoff = 0.5
                        # advance rv only after the handler succeeded, so an
                        # event whose handler raised is redelivered on resume
                        new_rv = obj.get("metadata", {}).get("resourceVersion")
                        if new_rv:
                            rv = new_rv
                except Exception:
                    error_pause = True
                if error_pause:
                    # an idle-but-healthy stream delivers no events before
                    # the read timeout; if it lived a while, the failure is
                    # routine churn, not a degraded server — start fresh so
                    # sporadic blips can't ratchet backoff to the cap.
                    # Consume stream_started so only the failure *immediately
                    # following* a long-lived stream resets: during a
                    # prolonged outage every retry fails before a stream ever
                    # starts, and backoff must keep escalating.
                    long_lived = (
                        stream_started
                        and time.monotonic() - stream_started > HEALTHY_STREAM_S
                    )
                    stream_started = 0.0
                    if long_lived:
                        backoff = 0.5
                    _pause(backoff)
                    backoff = min(backoff * 2, 30.0)

        t = threading.Thread(target=run, daemon=True, name=f"watch-{kind}")
        self._watch_threads.append(t)
        t.start()

    def stop(self) -> None:
        self._stop.set()

    # ---------------------------------------------------------------- events

    def emit_event(self, involved: Mapping, reason: str, message: str,
                   type_: str = "Normal", count: int = 1) -> dict:
        import uuid

        ns = ko.namespace(involved) or "default"
        return self.create(
            {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {
                    "name": f"{ko.name(involved)}.{uuid.uuid4().hex[:10]}",
                    "namespace": ns,
                },
                "involvedObject": {
                    "kind": involved.get("kind"),
                    "name": ko.name(involved),
                    "namespace": ns,
                    "uid": involved.get("metadata", {}).get("uid"),
                },
                "reason": reason,
                "message": message,
                "type": type_,
                "count": count,
            }
        )

    def events_for(self, involved: Mapping) -> list[dict]:
        ns = ko.namespace(involved)
        uid = involved.get("metadata", {}).get("uid")

        def matches(e: Mapping) -> bool:
            io = e.get("involvedObject", {})
            if io.get("name") != ko.name(involved) or io.get("kind") != involved.get("kind"):
                return False
            # uid-aware (kubectl describe semantics): events from a previous
            # incarnation of a recreated object are not "its" events.
            if uid and io.get("uid") and io["uid"] != uid:
                return False
            return True

        return [e for e in self.list("Event", ns) if matches(e)]
