"""In-memory API server: the platform's envtest.

The reference tests its controllers against a real etcd+apiserver with no
kubelet (``notebook-controller/controllers/suite_test.go:57-66``). We get the
same property — reconcilers exercised against a live object store with watches,
optimistic concurrency, admission, and garbage collection — from a small
in-process store, plus two things envtest lacks (SURVEY.md §4 takeaway):

- a **fake kubelet** (`step_kubelet`) that materializes StatefulSet pods and
  drives them to Ready, so status-mirroring paths run end-to-end;
- a **fake TPU node fixture** (`add_tpu_node_pool`) with topology labels and
  ``google.com/tpu`` capacity, so multi-host scheduling logic is unit-testable
  without TPUs.
"""
from __future__ import annotations

import fnmatch
import hashlib
import itertools
import json
import threading
import uuid
from typing import Callable, Mapping

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.tpu.topology import ACCELERATORS, parse_topology


class Conflict(Exception):
    """Optimistic-concurrency failure (HTTP 409)."""


class NotFound(Exception):
    """HTTP 404."""


class AlreadyExists(Exception):
    """HTTP 409 on create."""


class AdmissionDenied(Exception):
    """A mutating webhook rejected the object (HTTP 403 from admission)."""


class TooManyRequests(Exception):
    """HTTP 429 — the server asked the client to back off. Transient by
    definition; reconcilers must let it propagate into the workqueue's
    rate-limited requeue rather than treating it as fatal."""


class ServerError(Exception):
    """HTTP 5xx — transient apiserver failure. Same retry contract as 429."""


WatchFn = Callable[[str, dict], None]  # (event_type, object) -> None
MutatorFn = Callable[[dict, "FakeCluster"], dict]  # returns mutated object


def _key(obj: Mapping) -> tuple[str, str, str]:
    return (obj.get("kind", ""), ko.namespace(obj), ko.name(obj))


class FakeCluster:
    """Thread-safe object store with the API-server behaviors controllers rely on."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: dict[tuple[str, str, str], dict] = {}
        # owner uid -> keys of owned objects: the GC index. Cascade delete
        # used to scan the whole store per delete — O(objects) per delete
        # is quadratic teardown at fleet scale (10k notebooks completing
        # dominated SCHED_BENCH before this).
        self._owned: dict[str, set[tuple[str, str, str]]] = {}
        # kind -> keys, and (kind, label, value) -> keys: the list/selector
        # indexes (a real apiserver stores per resource type and the
        # sharded control plane's selector-scoped polls hit the label
        # index). Without them every list("Node") walked the whole store —
        # at 10k notebooks, O(store) per list per scheduling cycle.
        # insertion-ordered dicts used as sets: index iteration order must
        # be deterministic or the chaos soaks' seeded fault draws (one draw
        # per read in iteration order) stop reproducing from their seeds
        self._by_kind: dict[str, dict[tuple[str, str, str], None]] = {}
        self._by_label: dict[
            tuple[str, str, str], dict[tuple[str, str, str], None]
        ] = {}
        self._rv = itertools.count(1)
        self._watchers: list[tuple[str | None, WatchFn]] = []
        # kind-pattern -> mutator, the MutatingWebhookConfiguration analog
        self._mutators: list[tuple[str, MutatorFn, tuple[str, ...]]] = []
        # (namespace, pod) -> "[container] line" entries, the kubelet log store
        self._pod_logs: dict[tuple[str, str], list[str]] = {}

    # ------------------------------------------------------------------ CRUD

    def create(self, obj: Mapping, *, skip_admission: bool = False) -> dict:
        obj = ko.deep_copy(dict(obj))
        if not obj.get("kind"):
            raise ValueError("object has no kind")
        with self._lock:
            if not skip_admission:
                obj = self._admit(obj, "CREATE")
            k = _key(obj)
            if k in self._objects:
                raise AlreadyExists(f"{k} already exists")
            m = ko.meta(obj)
            m.setdefault("uid", str(uuid.uuid4()))
            m["resourceVersion"] = str(next(self._rv))
            m.setdefault("creationTimestamp", "1970-01-01T00:00:00Z")
            self._objects[k] = obj
            self._index_owned(k, None, obj)
            self._index_store(k, None, obj)
            stored = ko.deep_copy(obj)
        self._notify("ADDED", stored)
        return stored

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return ko.deep_copy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "") -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def _candidate_keys(
        self, kind: str, selector: Mapping | None
    ) -> "dict[tuple[str, str, str], None] | list[tuple[str, str, str]]":
        """Keys to consider for a (kind, selector) read, off the indexes
        (caller holds the lock). With matchLabels, iterate the smallest
        matching label index and membership-check the rest — deterministic
        insertion order either way (seeded soak draws depend on it)."""
        kind_keys = self._by_kind.get(kind)
        if not kind_keys:
            return {}
        match = (selector or {}).get("matchLabels")
        if not match:
            return kind_keys
        sets = [
            self._by_label.get((kind, lk, lv), {})
            for lk, lv in match.items()
        ]
        sets.sort(key=len)
        smallest, rest = sets[0], sets[1:]
        return [
            k for k in smallest
            if k in kind_keys and all(k in s for s in rest)
        ]

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        selector: Mapping | None = None,
    ) -> list[dict]:
        with self._lock:
            out = [
                ko.deep_copy(self._objects[key])
                for key in self._candidate_keys(kind, selector)
                if (namespace is None or key[1] == namespace)
                and ko.matches_selector(self._objects[key], selector)
            ]
        return sorted(out, key=lambda o: (ko.namespace(o), ko.name(o)))

    def resource_versions(
        self,
        kind: str,
        namespace: str | None = None,
        selector: Mapping | None = None,
    ) -> dict[tuple[str, str], str]:
        """``{(namespace, name): resourceVersion}`` for one kind, with no
        body copies — the poll an informer-style cache diffs against to
        fetch only objects that actually moved (a full ``list`` deep-copies
        every object, which at tens of thousands of objects per cycle is
        the read path's dominant cost). ``selector`` is the label selector
        a real API server applies server-side to a list — what lets a
        scheduler SHARD poll only its own families' notebooks instead of
        the whole fleet (runtime/sharding.py); the label index answers it
        in O(matching), not O(store)."""
        with self._lock:
            return {
                (key[1], key[2]): ko.meta(self._objects[key]).get(
                    "resourceVersion", ""
                )
                for key in self._candidate_keys(kind, selector)
                if (namespace is None or key[1] == namespace)
                and ko.matches_selector(self._objects[key], selector)
            }

    def _admit(self, obj: dict, operation: str) -> dict:
        """Run the registered mutating webhooks for one operation (caller
        holds the lock). Real MutatingWebhookConfigurations name the
        operations they intercept; mutators here default to CREATE-only and
        opt into UPDATE explicitly (``register_mutator(operations=...)``)."""
        for pattern, fn, operations in self._mutators:
            if operation in operations and fnmatch.fnmatch(
                obj["kind"], pattern
            ):
                obj = fn(obj, self)
        return obj

    def update(self, obj: Mapping) -> dict:
        obj = ko.deep_copy(dict(obj))
        k = _key(obj)
        with self._lock:
            current = self._objects.get(k)
            if current is None:
                raise NotFound(f"{k}")
            sent_rv = ko.meta(obj).get("resourceVersion")
            cur_rv = ko.meta(current).get("resourceVersion")
            if sent_rv is not None and sent_rv != cur_rv:
                raise Conflict(f"{k}: resourceVersion {sent_rv} != {cur_rv}")
            obj = self._admit(obj, "UPDATE")
            ko.meta(obj)["uid"] = ko.meta(current).get("uid")
            ko.meta(obj)["resourceVersion"] = str(next(self._rv))
            self._objects[k] = obj
            self._index_owned(k, current, obj)
            self._index_store(k, current, obj)
            stored = ko.deep_copy(obj)
        self._notify("MODIFIED", stored)
        return stored

    def update_status(self, obj: Mapping) -> dict:
        """Status-subresource write: persists ONLY .status (the CRDs declare
        the status subresource, so real API servers ignore .status on the main
        path — controllers must use this method for status)."""
        k = _key(obj)
        with self._lock:
            current = self._objects.get(k)
            if current is None:
                raise NotFound(f"{k}")
            merged = ko.deep_copy(current)
            merged["status"] = ko.deep_copy(obj.get("status", {}))
            merged["metadata"]["resourceVersion"] = str(next(self._rv))
            self._objects[k] = merged
            stored = ko.deep_copy(merged)
        self._notify("MODIFIED", stored)
        return stored

    def patch(self, kind: str, name: str, namespace: str, patch: Mapping) -> dict:
        with self._lock:
            current = self.get(kind, name, namespace)
            merged = ko.strategic_merge(current, dict(patch))
            merged["metadata"]["resourceVersion"] = current["metadata"][
                "resourceVersion"
            ]
        return self.update(merged)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        k = (kind, namespace, name)
        with self._lock:
            obj = self._objects.get(k)
            if obj is None:
                raise NotFound(f"{k}")
            finalizers = ko.meta(obj).get("finalizers") or []
            if finalizers:
                # Finalizer semantics: mark for deletion, keep the object.
                if not ko.meta(obj).get("deletionTimestamp"):
                    obj["metadata"]["deletionTimestamp"] = "1970-01-01T00:00:01Z"
                    obj["metadata"]["resourceVersion"] = str(next(self._rv))
                    stored = ko.deep_copy(obj)
                else:
                    return
            else:
                del self._objects[k]
                self._index_owned(k, obj, None)
                self._index_store(k, obj, None)
                if kind == "Pod":
                    self._pod_logs.pop((namespace, name), None)
                stored = ko.deep_copy(obj)
                self._notify("DELETED", stored)
                self._garbage_collect(stored)
                return
        self._notify("MODIFIED", stored)

    def finalize(self, obj: Mapping) -> None:
        """Called by a controller once its finalizer is removed and the object
        is marked for deletion — completes the delete."""
        k = _key(obj)
        with self._lock:
            current = self._objects.get(k)
            if current is None:
                return
            if current["metadata"].get("finalizers"):
                return
            del self._objects[k]
            self._index_owned(k, current, None)
            self._index_store(k, current, None)
            stored = ko.deep_copy(current)
        self._notify("DELETED", stored)
        self._garbage_collect(stored)

    @staticmethod
    def _owner_uids(obj: Mapping | None) -> tuple[str, ...]:
        if obj is None:
            return ()
        refs = (obj.get("metadata") or {}).get("ownerReferences") or []
        return tuple(r.get("uid") for r in refs if r.get("uid"))

    def _index_store(
        self, k: tuple[str, str, str], old: Mapping | None, new: Mapping | None
    ) -> None:
        """Keep the kind and label indexes in step with one store mutation
        (caller holds the lock). Labels rarely change on update, so the
        common path is one dict compare."""
        kind = k[0]
        if old is None and new is not None:
            self._by_kind.setdefault(kind, {})[k] = None
        elif new is None and old is not None:
            kk = self._by_kind.get(kind)
            if kk is not None:
                kk.pop(k, None)
        old_labels = ko.labels(old) if old is not None else {}
        new_labels = ko.labels(new) if new is not None else {}
        if old_labels == new_labels:
            return
        for lk, lv in old_labels.items():
            if new_labels.get(lk) != lv:
                lkeys = self._by_label.get((kind, lk, lv))
                if lkeys is not None:
                    lkeys.pop(k, None)
        for lk, lv in new_labels.items():
            if old_labels.get(lk) != lv:
                self._by_label.setdefault((kind, lk, lv), {})[k] = None

    def _index_owned(
        self, k: tuple[str, str, str], old: Mapping | None, new: Mapping | None
    ) -> None:
        """Keep the GC's owner→owned index in step with one store mutation
        (caller holds the lock). Owner refs almost never change on update,
        so the common path is a tuple compare."""
        old_uids, new_uids = self._owner_uids(old), self._owner_uids(new)
        if old_uids == new_uids:
            return
        for uid in old_uids:
            owned = self._owned.get(uid)
            if owned is not None:
                owned.discard(k)
                if not owned:
                    del self._owned[uid]
        for uid in new_uids:
            self._owned.setdefault(uid, set()).add(k)

    def _garbage_collect(self, deleted: Mapping) -> None:
        """Cascade-delete objects owned (controller ref) by the deleted
        object — via the owner index, not a store scan (sorted for a
        deterministic cascade order)."""
        uid = ko.meta(dict(deleted)).get("uid")
        with self._lock:
            orphans = sorted(self._owned.get(uid, ()))
        for kind, ns, name_ in orphans:
            try:
                self.delete(kind, name_, ns)
            except NotFound:
                pass

    # ----------------------------------------------------------- watch plane

    def watch(self, kind: str | None, fn: WatchFn) -> None:
        with self._lock:
            self._watchers.append((kind, fn))

    def unwatch(self, fn: WatchFn) -> None:
        """Detach a watch handler (a stopped manager's informer teardown —
        without it, every controller crash-restart in the chaos harness would
        leak a dead subscription that still pays a deep-copy per event)."""
        with self._lock:
            self._watchers = [(k, f) for k, f in self._watchers if f is not fn]

    def dump(self) -> list[dict]:
        """Deep-copied snapshot of every stored object (invariant checking
        and fixed-point fingerprints in testing/chaos.py)."""
        with self._lock:
            return [ko.deep_copy(o) for o in self._objects.values()]

    def _notify(self, event: str, obj: dict) -> None:
        for kind, fn in list(self._watchers):
            if kind is None or kind == obj.get("kind"):
                fn(event, ko.deep_copy(obj))

    # ------------------------------------------------------------- admission

    def register_mutator(
        self,
        kind_pattern: str,
        fn: MutatorFn,
        operations: tuple[str, ...] = ("CREATE",),
    ) -> None:
        """The MutatingWebhookConfiguration analog
        (``admission-webhook/manifests/base/mutating-webhook-configuration.yaml``).
        ``operations`` mirrors the webhook rule's operations list: mutators
        default to CREATE-only (the historical behavior — per-pod env
        injection happens once, at admission of the pod CREATE); a mutator
        that must also heal drift on writes registers with
        ``("CREATE", "UPDATE")`` (the family-label enforcement in
        ``webhooks/tpu_env.py``)."""
        self._mutators.append((kind_pattern, fn, tuple(operations)))

    # --------------------------------------------------- cluster fixtures

    def add_node(
        self,
        name: str,
        labels: Mapping | None = None,
        capacity: Mapping | None = None,
    ) -> dict:
        return self.create(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": name, "labels": dict(labels or {})},
                "status": {
                    "capacity": dict(capacity or {}),
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            }
        )

    def add_tpu_node_pool(self, accelerator: str, topology: str, prefix: str = "tpu-node") -> list[dict]:
        """Fake TPU node fixture: one Ready node per host of the given slice."""
        topo = parse_topology(accelerator, topology)
        accel = ACCELERATORS[accelerator]
        nodes = []
        for i in range(topo.num_hosts):
            nodes.append(
                self.add_node(
                    f"{prefix}-{accelerator}-{topology}-{i}",
                    labels={
                        "cloud.google.com/gke-tpu-accelerator": accel.gke_accelerator,
                        "cloud.google.com/gke-tpu-topology": topology,
                    },
                    capacity={
                        "google.com/tpu": str(topo.chips_per_host),
                        "cpu": "96",
                        "memory": "400Gi",
                    },
                )
            )
        return nodes

    # ------------------------------------------------------- fake kubelet

    @staticmethod
    def _template_hash(owner: Mapping) -> str:
        """Deterministic revision of a workload's pod template — the
        controller-revision-hash analog that lets the kubelet roll pods
        whose spec predates the current template."""
        template = owner.get("spec", {}).get("template", {})
        digest = hashlib.sha256(
            json.dumps(template, sort_keys=True).encode()
        ).hexdigest()
        return digest[:10]

    def _create_workload_pod(self, owner: Mapping, pod_name: str, owner_kind: str) -> dict | None:
        """Materialize one pod from a workload's template, through admission."""
        ns = ko.namespace(owner)
        template = ko.deep_copy(owner["spec"].get("template", {}))
        annotations = dict(template.get("metadata", {}).get("annotations", {}))
        annotations["kubeflow.internal/template-hash"] = self._template_hash(owner)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": ns,
                "labels": dict(template.get("metadata", {}).get("labels", {})),
                "annotations": annotations,
                "ownerReferences": [
                    {
                        "apiVersion": owner["apiVersion"],
                        "kind": owner_kind,
                        "name": ko.name(owner),
                        "uid": owner["metadata"]["uid"],
                        "controller": True,
                    }
                ],
            },
            "spec": ko.deep_copy(template.get("spec", {})),
            "status": {"phase": "Pending", "conditions": []},
        }
        try:
            return self.create(pod)
        except AdmissionDenied:
            return None

    def pod_logs(
        self,
        name: str,
        namespace: str,
        *,
        container: str | None = None,
        tail_lines: int | None = None,
    ) -> str:
        """Pod log text (ref: JWA GET .../pod/<pod>/logs → read_namespaced_pod_log).

        The fake kubelet writes startup lines on promotion; tests and the
        standalone demo append more via ``append_pod_log``.
        """
        self.get("Pod", name, namespace)  # NotFound propagates like the API
        lines = self._pod_logs.get((namespace, name), [])
        if container:
            prefix = f"[{container}] "
            lines = [l[len(prefix):] for l in lines if l.startswith(prefix)]
        else:
            lines = [l.split("] ", 1)[-1] for l in lines]
        if tail_lines is not None:
            lines = lines[-tail_lines:]
        return "\n".join(lines)

    def append_pod_log(
        self, name: str, namespace: str, line: str, container: str = ""
    ) -> None:
        self._pod_logs.setdefault((namespace, name), []).append(
            f"[{container}] {line}"
        )

    def _promote_pod(self, pod: Mapping) -> None:
        """Pending → Running/Ready with container statuses."""
        for c in pod["spec"].get("containers", []):
            cname = c.get("name", "")
            image = c.get("image", "")
            self.append_pod_log(
                ko.name(pod), ko.namespace(pod),
                f"Pulled image {image}", cname,
            )
            self.append_pod_log(
                ko.name(pod), ko.namespace(pod),
                f"Started container {cname}", cname,
            )
        self.patch(
            "Pod",
            ko.name(pod),
            ko.namespace(pod),
            {
                "status": {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "containerStatuses": [
                        {
                            "name": c.get("name", ""),
                            "ready": True,
                            "state": {
                                "running": {"startedAt": "1970-01-01T00:00:02Z"}
                            },
                        }
                        for c in pod["spec"].get("containers", [])
                    ],
                }
            },
        )

    def _drive_workload(self, owner: Mapping, owner_kind: str, pod_name_fn) -> None:
        """Two-tick pod drive shared by StatefulSets and Deployments:
        tick 1 creates missing pods (Pending) and promotes Pending→Running;
        tick 2 counts them Ready into the workload status."""
        ns, base = ko.namespace(owner), ko.name(owner)
        want = owner.get("spec", {}).get("replicas", 1)
        uid = owner["metadata"]["uid"]
        pods = {
            ko.name(p): p
            for p in self.list("Pod", ns)
            if any(r.get("uid") == uid
                   for r in p["metadata"].get("ownerReferences", []))
        }
        wanted_names = {pod_name_fn(i) for i in range(want)}
        # scale down surplus pods (highest ordinals first, like the real
        # StatefulSet controller)
        for pod_name in sorted(set(pods) - wanted_names, reverse=True):
            self.delete("Pod", pod_name, ns)
        ready = 0
        revision = self._template_hash(owner)
        for i in range(want):
            pod_name = pod_name_fn(i)
            pod = pods.get(pod_name)
            if pod is not None and (
                ko.annotations(pod).get("kubeflow.internal/template-hash")
                != revision
            ):
                # rolling update: a pod built from a stale template is
                # deleted and recreated from the current one (the real
                # StatefulSet controller's controller-revision semantics —
                # without this, spec edits never reach running pods)
                self.delete("Pod", pod_name, ns)
                pod = None
            if pod is None:
                pod = self._create_workload_pod(owner, pod_name, owner_kind)
                if pod is None:
                    continue
            if pod["status"].get("phase") != "Running":
                self._promote_pod(pod)
            else:
                ready += 1
        self.patch(
            owner_kind, base, ns,
            {"status": {"replicas": want, "readyReplicas": ready}},
        )

    def step_kubelet(self) -> None:
        """Materialize pods for every StatefulSet/Deployment and drive them
        Ready.

        envtest never runs pods (SURVEY.md §4); this closes that gap so
        controllers' status-mirroring and culling paths are testable
        end-to-end. Pod creation goes through admission, exactly like the real
        flow (workload controller → webhook → kubelet).
        """
        for sts in self.list("StatefulSet"):
            base = ko.name(sts)
            self._drive_workload(sts, "StatefulSet", lambda i: f"{base}-{i}")
        for dep in self.list("Deployment"):
            base = ko.name(dep)
            self._drive_workload(dep, "Deployment", lambda i: f"{base}-rs-{i}")

    def settle(self, manager=None, rounds: int = 6) -> None:
        """Alternate kubelet ticks and reconciles until nothing changes."""
        for _ in range(rounds):
            self.step_kubelet()
            if manager is not None:
                manager.run_until_idle()

    # ------------------------------------------------------------- events

    def emit_event(
        self,
        involved: Mapping,
        reason: str,
        message: str,
        type_: str = "Normal",
        count: int = 1,
    ) -> dict:
        ns = ko.namespace(involved) or "default"
        name = f"{ko.name(involved)}.{uuid.uuid4().hex[:10]}"
        return self.create(
            {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": name, "namespace": ns},
                "involvedObject": {
                    "kind": involved.get("kind"),
                    "name": ko.name(involved),
                    "namespace": ns,
                    "uid": involved.get("metadata", {}).get("uid"),
                },
                "reason": reason,
                "message": message,
                "type": type_,
                "count": count,
            }
        )

    def events_for(self, involved: Mapping) -> list[dict]:
        ns = ko.namespace(involved)
        uid = involved.get("metadata", {}).get("uid")

        def matches(e: Mapping) -> bool:
            io = e.get("involvedObject", {})
            if io.get("name") != ko.name(involved) or io.get("kind") != involved.get("kind"):
                return False
            # uid-aware (kubectl describe semantics): events from a previous
            # incarnation of a recreated object are not "its" events.
            if uid and io.get("uid") and io["uid"] != uid:
                return False
            return True

        return [e for e in self.list("Event", ns) if matches(e)]
