"""Control-plane sharding: partition managers and the scheduler so
throughput scales with shard count.

Everything before this module ran as ONE manager process behind one leader
lease: every watch event, reconcile, and placement funneled through a single
Python loop. This module is the thin coordination plane (the Podracer idiom —
sharded actors, no shared mutable state) that splits the control plane into N
independent shards:

- **Manager shards** partition by *namespace hash*: a Notebook (and every
  namespaced object owned by it) is reconciled by exactly one shard's
  manager. Reconciles are idempotent per object and share no cross-object
  state, so a stable hash is the whole coordination protocol.
- **Scheduler shards** partition by *accelerator family*: node pools belong
  to exactly one family (the ``gke-tpu-accelerator`` label), a gang can only
  ever bind into pools of its own family, and preemptor and victim always
  share a family — so per-family schedulers need no shared free-set and no
  cross-shard locking. No chip is ever visible as free to two shards,
  structurally.

Each shard runs its own :class:`~kubeflow_tpu.runtime.manager.Manager`
(own workqueue, own watch handlers filtered to owned keys) behind its own
leader lease (``runtime/leader.py`` — distinct lease names never interfere),
so shards deploy as independent replicas and their throughput adds.

Cross-shard concerns are handled by an explicit **ownership stamp**
(:data:`SHARD_ANNOTATION`, value ``"<shards>:<shard>"``) written with the
same one-write discipline as the scheduler's bind annotation:

- the scheduler folds the stamp into the admission write (the queued-at
  patch), so a gang is stamped the moment it enters a shard's queue;
- on a shard-count change (resharding), the new owner *adopts* orphans —
  any gang whose stamp names a different generation or shard is re-stamped
  in one write and scheduled by its new owner from the annotations alone
  (placements, queued-at, suspend barriers all replay level-triggered);
- the stamp is an audit trail and adoption signal, not a lock: within one
  generation the family→shard map is deterministic, so exactly one shard
  computes itself as owner. Deployments must not run two *generations*
  (different SHARDS values) concurrently — the per-shard lease names embed
  the shard count (``...-shard-<i>-of-<N>``) precisely so a mixed rollout
  is visible and documented as operator error (docs/architecture.md).

``SHARDS=1`` (the default) constructs no router and stamps nothing: the
single-shard control plane is bit-identical to the pre-sharding one.
"""
from __future__ import annotations

import hashlib
from typing import Mapping

from kubeflow_tpu.tpu.topology import ACCELERATORS

# Ownership stamp: "<shards>:<shard>", e.g. "4:2". Written only when
# shards > 1 — a single-shard control plane must leave no trace (the chaos
# soaks assert SHARDS=1 is bit-identical to the unsharded fixed point).
SHARD_ANNOTATION = "sharding.kubeflow.org/owner"

# The accelerator family as a LABEL, stamped at creation (``api.notebook``)
# and healed by the owning scheduler shard whenever it drifts from
# ``spec.tpu.accelerator``. Labels are what real API servers can filter
# server-side: a scheduler shard's list/watch selects only its families'
# notebooks, so its ingest cost scales with the OWNED slice, not the fleet.
# The label is an optimization, never the authority — ownership decisions
# always re-derive the family from spec, and gangs the filtered index
# cannot see (created without the label, or mid-drift) reach their owner
# through the watch-event hint path (scheduler/controller.py).
FAMILY_LABEL = "tpu.kubeflow.org/accelerator-family"

# claim() verdicts
OWNED = "owned"    # stamp present and names this shard under this count
ADOPT = "adopt"    # this shard owns the key but the stamp is absent/foreign
FOREIGN = "foreign"  # another shard owns the key; leave it alone


def stable_hash(text: str) -> int:
    """Process-independent stable hash (``hash()`` is salted per process —
    two shard replicas would disagree on ownership)."""
    return int.from_bytes(
        hashlib.sha256(text.encode("utf-8", "replace")).digest()[:8], "big"
    )


def parse_owner(raw: str | None) -> tuple[int, int] | None:
    """Decode a stamp into (shards, shard), or None when absent/malformed.
    Malformed reads as absent: the computed owner then adopts rather than
    the whole control plane wedging on kubectl-edited garbage."""
    if not raw:
        return None
    parts = str(raw).split(":")
    if len(parts) != 2:
        return None
    try:
        shards, shard = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if shards < 1 or not (0 <= shard < shards):
        return None
    return (shards, shard)


def owner_of(obj: Mapping) -> tuple[int, int] | None:
    anns = (obj.get("metadata", {}) or {}).get("annotations", {}) or {}
    return parse_owner(anns.get(SHARD_ANNOTATION))


def node_family(node: Mapping) -> str | None:
    """The accelerator family a Node belongs to (via the GKE accelerator
    label), or None for non-TPU nodes."""
    labels = (node.get("metadata", {}) or {}).get("labels", {}) or {}
    gke = labels.get("cloud.google.com/gke-tpu-accelerator")
    if not gke:
        return None
    accel = accelerator_for_gke_label(gke)
    return accel.name if accel is not None else None


def notebook_family(nb: Mapping) -> str | None:
    """The accelerator family a Notebook's gang requests, read straight off
    ``spec.tpu.accelerator`` (no topology parse — this runs on the watch
    ingest path for every Notebook event). None for CPU notebooks and for
    specs naming no known family (the latter are admission's problem; they
    are not gangs and no scheduler shard owns them)."""
    tpu = ((nb.get("spec") or {}).get("tpu")) or {}
    fam = tpu.get("accelerator")
    return fam if fam in ACCELERATORS else None


def shard_enqueue_filter(router: "ShardRouter", shard_id: int):
    """The manager-plane ownership rule, applied at the workqueue's single
    enqueue choke point (``Manager.enqueue_filter``): namespaced keys belong
    to the shard owning their namespace hash; Profiles are cluster-scoped
    but each one IS a namespace, so the name hashes the same way (a
    Profile's shard is the shard of the namespace it manages); the
    scheduler's pseudo-kind passes through — it partitions internally by
    accelerator family, a different axis than namespaces."""

    def owns(rec, namespace: str, name: str) -> bool:
        if rec.kind == "SchedulerCycle":
            return True
        return router.shard_for_namespace(namespace or name) == shard_id

    return owns


class ShardRouter:
    """Stable key → shard-id map, shared by every replica of one generation.

    Namespaces shard by stable hash (the namespace population is large and
    anonymous). Accelerator families shard by their index in the *sorted,
    compiled-in* ``ACCELERATORS`` table — the table is identical across
    replicas of one build, the family count is tiny (a bare hash would
    collide half the time at 4 families / 4 shards), and the index map keeps
    the load balanced by construction. Families beyond the table (a build
    skew during rollout) fall back to the stable hash so ownership is still
    computable, just not balanced.
    """

    def __init__(self, shards: int) -> None:
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.shards = int(shards)
        self._family_shard = {
            fam: i % self.shards for i, fam in enumerate(sorted(ACCELERATORS))
        }

    # ------------------------------------------------------------- mapping

    def shard_for_namespace(self, namespace: str) -> int:
        return stable_hash(f"ns:{namespace}") % self.shards

    def shard_for_family(self, family: str) -> int:
        s = self._family_shard.get(family)
        if s is None:
            s = stable_hash(f"family:{family}") % self.shards
        return s

    def families_for(self, shard_id: int) -> frozenset[str]:
        """Accelerator families a scheduler shard owns (possibly empty —
        scheduler parallelism is bounded by the family count; extra shards
        still carry their namespace slice of the manager plane)."""
        return frozenset(
            fam for fam, s in self._family_shard.items() if s == shard_id
        )

    # ----------------------------------------------------------- ownership

    def stamp(self, shard_id: int) -> str:
        return f"{self.shards}:{shard_id}"

    def claim(self, obj: Mapping, shard_id: int, *, family: str) -> str:
        """This shard's relationship to one gang: :data:`OWNED`,
        :data:`ADOPT` (owner, but the stamp is absent or names another
        generation/shard — re-stamp in one write before scheduling), or
        :data:`FOREIGN`. Ownership is computed from the gang's *current*
        family, so a ``spec.tpu`` family edit moves the gang to its new
        owner the same way a reshard does: the new owner adopts, the old
        owner's filter stops seeing it."""
        if self.shard_for_family(family) != shard_id:
            return FOREIGN
        anns = (obj.get("metadata", {}) or {}).get("annotations", {}) or {}
        if anns.get(SHARD_ANNOTATION) == self.stamp(shard_id):
            return OWNED
        return ADOPT
