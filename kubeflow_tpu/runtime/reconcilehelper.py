"""Create-or-update idiom shared by every controller.

Behavioral equivalent of the reference's ``common/reconcilehelper/util.go:18-219``:
ensure an object exists, and if it does, copy only the fields the controller
owns — never clobbering cluster-managed fields (the reference is careful not to
overwrite ``spec.clusterIP``, ``util.go:182``; here, update functions receive
(existing, desired) and return the merged object or None for "no change").
"""
from __future__ import annotations

from typing import Callable, Mapping

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster

CopyFn = Callable[[dict, dict], dict | None]


def reconcile_object(
    cluster: FakeCluster,
    desired: Mapping,
    owner: Mapping | None = None,
    copy_fields: CopyFn | None = None,
    on_create: Callable[[dict], None] | None = None,
) -> dict:
    """``on_create`` fires only when the object was newly created (not on
    the update path) — the seam event recording hangs off without every
    caller re-reading the store to learn what happened."""
    desired = ko.deep_copy(dict(desired))
    if owner is not None:
        ko.set_controller_reference(desired, owner)
    existing = cluster.try_get(
        desired["kind"], ko.name(desired), ko.namespace(desired)
    )
    if existing is None:
        created = cluster.create(desired)
        if on_create is not None:
            on_create(created)
        return created
    merged = (copy_fields or copy_spec_fields)(existing, desired)
    if merged is None:
        return existing
    return cluster.update(merged)


def subset_matches(desired, existing) -> bool:
    """Is every field the controller *declares* already present in the live
    object? API servers default many fields the controller never set
    (Service sessionAffinity, pod-template defaults, ...); diffing full specs
    against them would make every reconcile dirty and loop update→watch→
    reconcile forever. So dirtiness is judged only on declared fields."""
    if isinstance(desired, dict):
        if not isinstance(existing, dict):
            return False
        return all(subset_matches(v, existing.get(k)) for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(existing, list) or len(desired) != len(existing):
            return False
        return all(subset_matches(d, e) for d, e in zip(desired, existing))
    return desired == existing


def copy_spec_fields(existing: dict, desired: dict) -> dict | None:
    """Default copier: own labels/annotations/spec, keep everything else."""
    changed = False
    out = ko.deep_copy(existing)
    for field in ("labels", "annotations"):
        want = desired.get("metadata", {}).get(field)
        if want is not None and not subset_matches(want, out["metadata"].get(field)):
            out["metadata"][field] = want
            changed = True
    if desired.get("spec") is not None and not subset_matches(
        desired["spec"], out.get("spec")
    ):
        out["spec"] = ko.deep_copy(desired["spec"])
        changed = True
    return out if changed else None


def copy_service_fields(existing: dict, desired: dict) -> dict | None:
    """Service copier: preserve clusterIP and nodePorts the cluster assigned
    (reference: ``CopyServiceFields`` ``util.go:166-195``)."""
    out = copy_spec_fields(existing, desired)
    if out is None:
        return None
    for k in ("clusterIP", "clusterIPs"):
        if k in (existing.get("spec") or {}):
            out["spec"][k] = existing["spec"][k]
    return out


def copy_statefulset_fields(existing: dict, desired: dict) -> dict | None:
    """StatefulSet copier: replicas + template + labels/annotations only
    (reference: ``CopyStatefulSetFields`` ``util.go:107-134`` — volumeClaimTemplates
    are immutable and must not be diffed)."""
    changed = False
    out = ko.deep_copy(existing)
    for field in ("labels", "annotations"):
        want = desired.get("metadata", {}).get(field)
        if want is not None and out["metadata"].get(field) != want:
            out["metadata"][field] = want
            changed = True
    espec, dspec = out.setdefault("spec", {}), desired.get("spec", {})
    for field in ("replicas", "template"):
        if field in dspec and not subset_matches(dspec[field], espec.get(field)):
            espec[field] = ko.deep_copy(dspec[field])
            changed = True
    return out if changed else None
