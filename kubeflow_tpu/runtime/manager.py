"""Reconciler manager: watch wiring over the native workqueue.

The controller-runtime analog (reference: ``notebook-controller/main.go:84-131``
builds a manager; ``SetupWithManager`` at
``controllers/notebook_controller.go:726-774`` wires For/Owns/Watches sources).
Same model here: each reconciler owns a primary kind; secondary watches map
events back to primary keys; the deduplicating workqueue
(``native/workqueue.cc`` via ``runtime/workqueue.py``) guarantees one
reconcile per key at a time — the structural concurrency-safety argument the
reference relies on (SURVEY.md §5 "race detection"). Failed reconciles back
off exponentially per key; successful ones reset the counter, exactly the
client-go rate-limiter contract.

Two execution modes share the code path:

- deterministic (tests, the platform's envtest): virtual clock, ``advance()``
  fires requeue timers, ``run_until_idle`` drains synchronously;
- production (``cmd/controller.py``): an external wall clock synced on every
  ``tick()``, or ``run_workers()`` fanning N threads over the blocking queue.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Iterable

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.runtime.workqueue import make_workqueue

log = logging.getLogger(__name__)

MapFn = Callable[[dict], Iterable[tuple[str, str]]]  # obj -> (ns, name) keys

_SEP = "\x1f"  # key separator; never appears in k8s names

# the dedup queue coalesces events; keep at most this many trace ids pending
# per key (the span records "N events funneled here", not an unbounded list)
_MAX_TRACES_PER_KEY = 8


@dataclasses.dataclass
class Result:
    requeue_after: float | None = None  # seconds


class Reconciler:
    """Base class. Subclasses set ``kind`` and implement ``reconcile``."""

    kind: str = ""
    # False for reconcilers whose primary kind is a pseudo-kind (no such
    # object ever exists — e.g. the fleet scheduler's global cycle): the
    # manager then installs only the secondary watches(). Against the real
    # apiserver a primary watch on a made-up kind is not even resolvable.
    watch_primary: bool = True

    def reconcile(self, cluster: FakeCluster, namespace: str, name: str) -> Result | None:
        raise NotImplementedError

    # Secondary sources: list of (kind, map_fn). Default maps an owned object
    # back to its controller owner of our kind (the Owns() idiom).
    def watches(self) -> list[tuple[str, MapFn]]:
        return []

    def owns(self, kind: str) -> tuple[str, MapFn]:
        def map_owner(obj: dict) -> Iterable[tuple[str, str]]:
            ref = ko.controller_owner(obj)
            if ref and ref.get("kind") == self.kind:
                yield (ko.namespace(obj), ref["name"])

        return (kind, map_owner)


class Manager:
    """Runs reconcilers against a cluster on the shared workqueue."""

    def __init__(
        self,
        cluster: FakeCluster,
        *,
        clock: Callable[[], float] | None = None,
        # controller-runtime's per-item rate limiter starts at 5 ms
        # (workqueue.DefaultItemBasedRateLimiter); a 1 s base turned every
        # optimistic-concurrency conflict into a ~1 s latency cliff under
        # churn (loadtest/churn.py found it: create p50 1.5 s at n=20)
        error_backoff_base: float = 0.005,
        error_backoff_max: float = 64.0,
        tracer=None,
        metrics=None,
        enqueue_filter: Callable[[Reconciler, str, str], bool] | None = None,
    ) -> None:
        self.cluster = cluster
        # Control-plane sharding (runtime/sharding.py): a sharded manager
        # drops keys it does not own at the single enqueue choke point —
        # watch handlers, the initial cache-sync replay, and direct enqueues
        # all pass through here, so an unowned key can never reach a worker.
        # None (the default) accepts everything: the unsharded manager.
        self.enqueue_filter = enqueue_filter
        # reconcile tracing (obs/tracing.py): reconcilers see the traced
        # client surface so every write they issue lands as a child span of
        # the reconcile that caused it; the manager's own watch/list plumbing
        # keeps the raw client (reads are untraced by design)
        self.tracer = tracer
        if tracer is not None:
            from kubeflow_tpu.obs.tracing import TracingCluster

            self._rec_cluster = TracingCluster(cluster, tracer)
        else:
            self._rec_cluster = cluster
        # ControlPlaneMetrics (utils/metrics.py): reconcile duration/outcome
        # per kind + workqueue queue-wait/retries — controller-runtime's
        # standard families
        self.metrics = metrics
        self._pending_traces: dict[str, list[str]] = {}
        self._enqueued_at: dict[str, float] = {}
        self._trace_lock = threading.Lock()
        self._reconcilers: list[Reconciler] = []
        self.error_backoff_max = error_backoff_max
        self._wq = make_workqueue(
            virtual_clock=True,
            backoff_base=error_backoff_base,
            backoff_max=error_backoff_max,
        )
        self._clock = clock
        self._epoch = clock() if clock else 0.0
        self._sync_lock = threading.Lock()
        self._watches_started = False
        self._installed_watches: list = []
        # one-worker-per-key runtime guard: keys currently inside reconcile.
        # The workqueue makes a violation structurally impossible; counting
        # (instead of trusting) is what lets the chaos soak assert it.
        self._active_keys: set[str] = set()
        self._active_lock = threading.Lock()
        self.concurrency_violations = 0

    # ------------------------------------------------------------- wiring

    def register(self, rec: Reconciler) -> None:
        """Record a reconciler. Watches install when execution starts
        (``start_watches``), NOT here: controller-runtime starts informers
        only when the manager starts — under leader election a STANDBY
        replica must not stream events into a queue no worker drains
        (unbounded growth, and its scraped depth would read as a live
        backlog; the multiproc churn loadtest hit exactly that)."""
        self._reconcilers.append(rec)

    def start_watches(self) -> None:
        """Install watches + initial sync (idempotent). The initial pass
        enqueues every existing object as ADDED — the informer cache-sync
        contract — so objects created before the manager started still
        reconcile (KubeClient.watch replays its own initial list; the
        in-memory FakeCluster delivers only live events, so the replay here
        covers both).

        All-or-nothing: a fault during installation (a flaky initial list)
        rolls back the watches already attached and re-raises, so the next
        call retries from scratch — a half-wired manager would silently
        never reconcile the kinds past the failure point (controller-runtime
        fails manager start on cache-sync failure for the same reason).
        """
        if self._watches_started:
            return
        installed: list = []
        try:
            for rec in self._reconcilers:
                if rec.watch_primary:
                    primary = self._primary_handler(rec)
                    self.cluster.watch(rec.kind, primary)
                    installed.append(primary)
                    for obj in self.cluster.list(rec.kind):
                        primary("ADDED", obj)
                for kind, map_fn in rec.watches():
                    secondary = self._secondary_handler(rec, map_fn)
                    self.cluster.watch(kind, secondary)
                    installed.append(secondary)
                    for obj in self.cluster.list(kind):
                        secondary("ADDED", obj)
        except Exception:
            unwatch = getattr(self.cluster, "unwatch", None)
            if unwatch is not None:
                for handler in installed:
                    unwatch(handler)
            raise
        self._installed_watches = installed
        self._watches_started = True

    def shutdown(self) -> None:
        """Tear the manager down: detach its watch handlers (when the cluster
        supports it) and shut the workqueue so blocked workers drain out.
        The chaos harness uses this to model a controller process dying.

        Must be a clean no-op on a manager that never started — a sharded
        standby that never won its lease (so never installed watches, never
        ran a worker) is still shut down on process exit, and the teardown
        path dying on it would mask the real exit reason. Idempotent for the
        same reason: crash-restart loops shut down whatever they hold."""
        unwatch = getattr(self.cluster, "unwatch", None)
        if unwatch is not None:
            for handler in self._installed_watches:
                unwatch(handler)
        self._installed_watches = []
        self._watches_started = False
        self._wq.shutdown()

    @property
    def watches_started(self) -> bool:
        """Public view of watch installation (readiness probes read this)."""
        return self._watches_started

    def reconciler_for(self, kind: str) -> Reconciler | None:
        """The registered reconciler for a primary kind (process wiring —
        e.g. the labels-file watcher needs the ProfileReconciler)."""
        for rec in self._reconcilers:
            if rec.kind == kind:
                return rec
        return None

    def _event_trace(self, event: str, obj: dict) -> str | None:
        """Stamp a trace id on one delivered watch event (tracing's origin
        point: everything downstream — queue wait, reconcile, writes — links
        back to this id)."""
        if self.tracer is None:
            return None
        return self.tracer.new_trace(
            f"watch:{obj.get('kind', '?')}:{event} "
            f"{ko.namespace(obj)}/{ko.name(obj)}"
        )

    def _primary_handler(self, rec: Reconciler):
        def handle(event: str, obj: dict) -> None:
            trace_id = self._event_trace(event, obj)
            self.enqueue(rec, ko.namespace(obj), ko.name(obj), trace_id)

        return handle

    def _secondary_handler(self, rec: Reconciler, map_fn: MapFn):
        def handle(event: str, obj: dict) -> None:
            trace_id = None
            for ns, name in map_fn(obj):
                if trace_id is None:  # one event = one trace, N mapped keys
                    trace_id = self._event_trace(event, obj)
                self.enqueue(rec, ns, name, trace_id)

        return handle

    # -------------------------------------------------------------- queue

    def _key(self, rec: Reconciler, namespace: str, name: str) -> str:
        return f"{self._reconcilers.index(rec)}{_SEP}{namespace}{_SEP}{name}"

    def _unkey(self, key: str) -> tuple[Reconciler, str, str]:
        idx, ns, name = key.split(_SEP, 2)
        return self._reconcilers[int(idx)], ns, name

    def enqueue(
        self,
        rec: Reconciler,
        namespace: str,
        name: str,
        trace_id: str | None = None,
    ) -> None:
        if self.enqueue_filter is not None and not self.enqueue_filter(
            rec, namespace, name
        ):
            return
        key = self._key(rec, namespace, name)
        if self.tracer is not None or self.metrics is not None:
            with self._trace_lock:
                if trace_id is not None:
                    pending = self._pending_traces.setdefault(key, [])
                    if len(pending) < _MAX_TRACES_PER_KEY:
                        pending.append(trace_id)
                # queue-wait clock starts at the FIRST add of this round;
                # re-adds while queued are dedup'd and must not reset it
                self._enqueued_at.setdefault(key, self.now())
        self._wq.add(key)

    def now(self) -> float:
        if self._clock is not None:
            return self._clock()
        return self._wq.now()

    def advance(self, seconds: float) -> None:
        """Advance the virtual clock and fire due requeue timers."""
        if self._clock is not None:
            raise RuntimeError(
                "advance() requires the built-in virtual clock; this manager "
                "was constructed with an external clock"
            )
        self._wq.advance(seconds)

    def _sync_external_clock(self) -> None:
        if self._clock is None:
            return
        with self._sync_lock:
            delta = (self._clock() - self._epoch) - self._wq.now()
            if delta > 0:
                self._wq.advance(delta)

    def queue_metrics(self) -> dict:
        """Workqueue counters (depth/adds/requeues/backoff), for /metrics.
        ``depth`` is the LIVE queue length — the raw counters don't carry
        it, and both the ops gauge and the churn loadtest's stuck-key gate
        were silently reading 0 without it."""
        return {"depth": len(self._wq), **self._wq.metrics()}

    def next_requeue_in(self) -> float | None:
        """Seconds until the earliest pending timer fires, or None. The chaos
        soak's backoff invariant reads this: no requeue may ever be scheduled
        further out than max(error_backoff_max, largest legitimate
        requeue_after a reconciler returns)."""
        deadline = self._wq.next_deadline()
        if deadline is None:
            return None
        return deadline - self._wq.now()

    # ----------------------------------------------------------- execution

    def _execute(self, key: str) -> None:
        rec, ns, name = self._unkey(key)
        with self._active_lock:
            if key in self._active_keys:
                self.concurrency_violations += 1
                log.error("one-worker-per-key violated for %s", key)
            self._active_keys.add(key)
        trace_ids: tuple[str, ...] = ()
        if self.tracer is not None or self.metrics is not None:
            with self._trace_lock:
                trace_ids = tuple(self._pending_traces.pop(key, ()))
                queued_at = self._enqueued_at.pop(key, None)
            if self.metrics is not None and queued_at is not None:
                self.metrics.observe_queue_wait(
                    max(0.0, self.now() - queued_at)
                )
        span = (
            self.tracer.start_reconcile(rec.kind, f"{ns}/{name}", trace_ids)
            if self.tracer is not None
            else None
        )
        started = self.now()
        try:
            result = rec.reconcile(self._rec_cluster, ns, name)
        except Exception:
            log.exception("reconcile %s %s/%s failed", rec.kind, ns, name)
            result = None
            failed = True
        else:
            failed = False
        finally:
            # leave _active_keys strictly BEFORE done(): once done() runs,
            # another worker may legitimately re-acquire the key, and finding
            # it still marked active would be a false concurrency violation
            with self._active_lock:
                self._active_keys.discard(key)
        if failed:
            outcome = "error"
        elif result and result.requeue_after is not None:
            outcome = "requeue"
        else:
            outcome = "success"
        if span is not None:
            self.tracer.end_reconcile(span, outcome)
        if self.metrics is not None:
            # duration on the injected clock, like the tracer's spans: real
            # wall time in production, the injected latency (not host
            # jitter) under the soaks' virtual clock
            self.metrics.observe_reconcile(
                rec.kind, max(0.0, self.now() - started), outcome
            )
        if failed:
            self._wq.done(key)
            self._wq.add_rate_limited(key)  # per-key exponential backoff
            if self.metrics is not None:
                self.metrics.queue_retries.inc()
            return
        self._wq.forget(key)
        self._wq.done(key)
        if result and result.requeue_after is not None:
            self._wq.add_after(key, result.requeue_after)

    def tick(self) -> int:
        """One production control-loop turn: sync the wall clock (firing due
        requeue timers), then drain the queue."""
        self._sync_external_clock()
        return self.run_until_idle()

    def run_until_idle(self, max_iterations: int = 1000) -> int:
        """Drain the workqueue; returns number of reconciles executed."""
        self.start_watches()
        executed = 0
        for _ in range(max_iterations):
            self._sync_external_clock()
            key = self._wq.get(0)
            if key is None:
                return executed
            self._execute(key)
            executed += 1
        raise RuntimeError("reconcile loop did not settle (hot loop?)")

    def run_workers(
        self, n_workers: int, stop: threading.Event, *, poll_interval: float = 0.2
    ) -> list[threading.Thread]:
        """Long-running mode: N threads block on the queue; a pacer thread
        syncs the external clock so ``add_after`` requeues fire."""
        self.start_watches()

        def worker():
            while not stop.is_set():
                key = self._wq.get(poll_interval)
                if key is None:
                    continue
                self._execute(key)

        def pacer():
            while not stop.is_set():
                self._sync_external_clock()
                stop.wait(poll_interval)

        threads = [
            threading.Thread(target=worker, daemon=True, name=f"reconcile-{i}")
            for i in range(n_workers)
        ]
        threads.append(threading.Thread(target=pacer, daemon=True, name="clock-pacer"))
        for t in threads:
            t.start()
        return threads
