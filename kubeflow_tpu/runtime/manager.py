"""Reconciler manager: watch wiring + deduplicating workqueue.

The controller-runtime analog (reference: ``notebook-controller/main.go:84-131``
builds a manager; ``SetupWithManager`` at
``controllers/notebook_controller.go:726-774`` wires For/Owns/Watches sources).
Same model here: each reconciler owns a primary kind; secondary watches map
events back to primary keys; a queue deduplicates keys; one reconcile runs per
key at a time (the structural concurrency-safety argument the reference relies
on, SURVEY.md §5 "race detection").
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Iterable

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster

log = logging.getLogger(__name__)

MapFn = Callable[[dict], Iterable[tuple[str, str]]]  # obj -> (ns, name) keys


@dataclasses.dataclass
class Result:
    requeue_after: float | None = None  # seconds


class Reconciler:
    """Base class. Subclasses set ``kind`` and implement ``reconcile``."""

    kind: str = ""

    def reconcile(self, cluster: FakeCluster, namespace: str, name: str) -> Result | None:
        raise NotImplementedError

    # Secondary sources: list of (kind, map_fn). Default maps an owned object
    # back to its controller owner of our kind (the Owns() idiom).
    def watches(self) -> list[tuple[str, MapFn]]:
        return []

    def owns(self, kind: str) -> tuple[str, MapFn]:
        def map_owner(obj: dict) -> Iterable[tuple[str, str]]:
            ref = ko.controller_owner(obj)
            if ref and ref.get("kind") == self.kind:
                yield (ko.namespace(obj), ref["name"])

        return (kind, map_owner)


class Manager:
    """Runs reconcilers against a cluster.

    Test-mode execution model: watch events enqueue keys synchronously;
    ``run_until_idle`` drains the queue, honoring ``requeue_after`` via a
    virtual clock (``advance``) so culling-period behavior is testable without
    sleeping (the reference's envtest suites poll with Eventually; we get
    determinism instead).
    """

    def __init__(self, cluster: FakeCluster, *, clock: Callable[[], float] | None = None) -> None:
        self.cluster = cluster
        self._reconcilers: list[Reconciler] = []
        self._queue: list[tuple[Reconciler, str, str]] = []
        self._queued: set[tuple[int, str, str]] = set()
        self._timers: list[tuple[float, int, Reconciler, str, str]] = []
        self._timer_seq = 0
        self._lock = threading.RLock()
        self._now = 0.0
        self._clock = clock

    # ------------------------------------------------------------- wiring

    def register(self, rec: Reconciler) -> None:
        self._reconcilers.append(rec)
        self.cluster.watch(rec.kind, self._primary_handler(rec))
        for kind, map_fn in rec.watches():
            self.cluster.watch(kind, self._secondary_handler(rec, map_fn))

    def _primary_handler(self, rec: Reconciler):
        def handle(event: str, obj: dict) -> None:
            self.enqueue(rec, ko.namespace(obj), ko.name(obj))

        return handle

    def _secondary_handler(self, rec: Reconciler, map_fn: MapFn):
        def handle(event: str, obj: dict) -> None:
            for ns, name in map_fn(obj):
                self.enqueue(rec, ns, name)

        return handle

    # -------------------------------------------------------------- queue

    def enqueue(self, rec: Reconciler, namespace: str, name: str) -> None:
        with self._lock:
            key = (id(rec), namespace, name)
            if key in self._queued:
                return
            self._queued.add(key)
            self._queue.append((rec, namespace, name))

    def now(self) -> float:
        return self._clock() if self._clock else self._now

    def advance(self, seconds: float) -> None:
        """Advance the virtual clock and fire due requeue timers."""
        if self._clock is not None:
            raise RuntimeError(
                "advance() requires the built-in virtual clock; this manager "
                "was constructed with an external clock"
            )
        self._now += seconds
        self._fire_due_timers()

    def _fire_due_timers(self) -> None:
        with self._lock:
            due = [t for t in self._timers if t[0] <= self.now()]
            self._timers = [t for t in self._timers if t[0] > self.now()]
        for _, _, rec, ns, name in due:
            self.enqueue(rec, ns, name)

    def tick(self) -> int:
        """One production control-loop turn: fire due requeue timers, then
        drain the queue. The public idiom for long-running entrypoints."""
        self._fire_due_timers()
        return self.run_until_idle()

    def run_until_idle(self, max_iterations: int = 1000) -> int:
        """Drain the workqueue; returns number of reconciles executed."""
        executed = 0
        for _ in range(max_iterations):
            with self._lock:
                if not self._queue:
                    break
                rec, ns, name = self._queue.pop(0)
                self._queued.discard((id(rec), ns, name))
            try:
                result = rec.reconcile(self.cluster, ns, name)
            except Exception:  # reconcile errors requeue, like controller-runtime
                log.exception("reconcile %s %s/%s failed", rec.kind, ns, name)
                result = Result(requeue_after=1.0)
            executed += 1
            if result and result.requeue_after is not None:
                with self._lock:
                    self._timer_seq += 1
                    self._timers.append(
                        (
                            self.now() + result.requeue_after,
                            self._timer_seq,
                            rec,
                            ns,
                            name,
                        )
                    )
        else:
            raise RuntimeError("reconcile loop did not settle (hot loop?)")
        return executed
