"""Deduplicating, rate-limited workqueue — binding to the native core.

Every controller in the reference rides on client-go's workqueue via
controller-runtime (``notebook-controller/main.go:84-131``); its guarantees —
one worker per key at a time, re-adds during processing deferred to Done,
delayed requeues, per-key exponential backoff — are what make level-triggered
reconciliation safe without locks in the reconcilers (SURVEY.md §5 "race
detection"). Here that core is native C++ (``native/workqueue.cc``) loaded via
ctypes, with :class:`PyWorkQueue` as a drop-in pure-Python fallback so the
platform runs (and tests run) on machines without the compiled library.

Both implementations share the contract:

- ``add(key)``: enqueue with dedup; if ``key`` is mid-processing it is marked
  dirty and re-enqueued when ``done(key)`` is called.
- ``get(timeout)``: block for the next key, move it to the processing set.
- ``done(key)``: finish processing (fires the deferred re-add if dirty).
- ``add_after(key, delay)``: timer-driven enqueue (the culling requeue,
  ref ``notebook_controller.go:279-281``).
- ``add_rate_limited(key)`` / ``forget(key)``: per-key exponential backoff,
  ``base * 2^failures`` capped at ``maximum``.
- virtual-clock mode + ``advance(seconds)`` for deterministic tests.
"""
from __future__ import annotations

import ctypes
import heapq
import math
import os
import subprocess
import threading
import time
from typing import Optional

_MAX_KEY = 4096

_lib = None
_lib_err: Optional[str] = None


def _load_library():
    """Load (building if necessary) the native runtime library."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    here = os.path.dirname(os.path.abspath(__file__))
    so_path = os.path.join(here, "libkfruntime.so")
    native_dir = os.path.join(here, os.pardir, os.pardir, "native")
    makefile = os.path.join(native_dir, "Makefile")
    if os.path.exists(makefile):
        # Always invoke make: it no-ops when the .so is fresh and rebuilds
        # when native/*.cc changed (a stale binary would silently win
        # otherwise).
        try:
            subprocess.run(
                ["make", "-C", native_dir],
                capture_output=True,
                timeout=120,
                check=True,
            )
        except Exception as exc:  # toolchain absent: fall back to Python
            if not os.path.exists(so_path):
                _lib_err = f"native build failed: {exc}"
                return None
    if not os.path.exists(so_path):
        _lib_err = "libkfruntime.so not found"
        return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError as exc:  # pragma: no cover
        _lib_err = str(exc)
        return None
    lib.wq_new.restype = ctypes.c_void_p
    lib.wq_new.argtypes = [ctypes.c_int, ctypes.c_double, ctypes.c_double]
    lib.wq_free.argtypes = [ctypes.c_void_p]
    lib.wq_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_add_after.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
    lib.wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_failures.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_failures.restype = ctypes.c_int
    lib.wq_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_double,
    ]
    lib.wq_get.restype = ctypes.c_int
    lib.wq_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.wq_advance.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.wq_now.argtypes = [ctypes.c_void_p]
    lib.wq_now.restype = ctypes.c_double
    lib.wq_next_deadline.argtypes = [ctypes.c_void_p]
    lib.wq_next_deadline.restype = ctypes.c_double
    lib.wq_len.argtypes = [ctypes.c_void_p]
    lib.wq_len.restype = ctypes.c_int
    lib.wq_timer_count.argtypes = [ctypes.c_void_p]
    lib.wq_timer_count.restype = ctypes.c_int
    lib.wq_shutdown.argtypes = [ctypes.c_void_p]
    lib.wq_metrics.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load_library() is not None


class NativeWorkQueue:
    """ctypes wrapper over ``native/workqueue.cc``."""

    def __init__(
        self,
        *,
        virtual_clock: bool = False,
        backoff_base: float = 0.005,
        backoff_max: float = 1000.0,
    ) -> None:
        lib = _load_library()
        if lib is None:
            raise RuntimeError(f"native workqueue unavailable: {_lib_err}")
        self._lib = lib
        self._q = lib.wq_new(
            1 if virtual_clock else 0,
            ctypes.c_double(backoff_base),
            ctypes.c_double(backoff_max),
        )

    def __del__(self):  # pragma: no cover
        try:
            if getattr(self, "_q", None):
                self._lib.wq_free(self._q)
                self._q = None
        except Exception:
            pass

    def add(self, key: str) -> None:
        self._lib.wq_add(self._q, key.encode())

    def add_after(self, key: str, delay: float) -> None:
        self._lib.wq_add_after(self._q, key.encode(), ctypes.c_double(delay))

    def add_rate_limited(self, key: str) -> None:
        self._lib.wq_add_rate_limited(self._q, key.encode())

    def forget(self, key: str) -> None:
        self._lib.wq_forget(self._q, key.encode())

    def failures(self, key: str) -> int:
        return self._lib.wq_failures(self._q, key.encode())

    def get(self, timeout: float | None = 0.0) -> str | None:
        """Next key, or None on timeout / shutdown-drained."""
        t = -1.0 if timeout is None else float(timeout)
        # get() can block; a separate buffer per call keeps it thread-safe.
        buf = ctypes.create_string_buffer(_MAX_KEY)
        rc = self._lib.wq_get(self._q, buf, _MAX_KEY, ctypes.c_double(t))
        if rc != 1:
            return None
        return buf.value.decode()

    def done(self, key: str) -> None:
        self._lib.wq_done(self._q, key.encode())

    def advance(self, seconds: float) -> None:
        self._lib.wq_advance(self._q, ctypes.c_double(seconds))

    def now(self) -> float:
        return self._lib.wq_now(self._q)

    def next_deadline(self) -> float | None:
        d = self._lib.wq_next_deadline(self._q)
        return None if d < 0 else d

    def __len__(self) -> int:
        return self._lib.wq_len(self._q)

    def timer_count(self) -> int:
        return self._lib.wq_timer_count(self._q)

    def shutdown(self) -> None:
        self._lib.wq_shutdown(self._q)

    def metrics(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.wq_metrics(self._q, out)
        return {
            "adds": out[0],
            "gets": out[1],
            "requeues": out[2],
            "rate_limited": out[3],
            "timer_fires": out[4],
            "max_depth": out[5],
        }


class PyWorkQueue:
    """Pure-Python fallback with identical semantics."""

    def __init__(
        self,
        *,
        virtual_clock: bool = False,
        backoff_base: float = 0.005,
        backoff_max: float = 1000.0,
    ) -> None:
        self._virtual = virtual_clock
        self._base = backoff_base
        self._max = backoff_max
        self._vnow = 0.0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[str] = []
        self._dirty: set[str] = set()
        self._processing: set[str] = set()
        self._timers: list[tuple[float, int, str]] = []
        self._seq = 0
        self._failures: dict[str, int] = {}
        self._shutdown = False
        self._m = {
            "adds": 0, "gets": 0, "requeues": 0,
            "rate_limited": 0, "timer_fires": 0, "max_depth": 0,
        }

    def _now(self) -> float:
        return self._vnow if self._virtual else time.monotonic()  # tpulint: disable=TPU001 — this IS the virtual/real clock seam: the real branch is the injected default

    def _add_locked(self, key: str) -> None:
        if self._shutdown:
            return
        self._m["adds"] += 1
        if key in self._dirty:
            return
        self._dirty.add(key)
        if key in self._processing:
            return
        self._queue.append(key)
        self._m["max_depth"] = max(self._m["max_depth"], len(self._queue))

    def _fire_due_locked(self) -> None:
        now = self._now()
        while self._timers and self._timers[0][0] <= now:
            _, _, key = heapq.heappop(self._timers)
            self._m["timer_fires"] += 1
            self._add_locked(key)

    def add(self, key: str) -> None:
        with self._cv:
            self._add_locked(key)
            self._cv.notify()

    def add_after(self, key: str, delay: float) -> None:
        with self._cv:
            if self._shutdown:
                return
            if delay <= 0:
                self._add_locked(key)
            else:
                self._seq += 1
                heapq.heappush(
                    self._timers, (self._now() + delay, self._seq, key)
                )
            self._cv.notify()

    def add_rate_limited(self, key: str) -> None:
        with self._cv:
            if self._shutdown:
                return
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
            delay = min(self._base * math.pow(2.0, n), self._max)
            self._m["rate_limited"] += 1
            self._seq += 1
            heapq.heappush(self._timers, (self._now() + delay, self._seq, key))
            self._cv.notify()

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def get(self, timeout: float | None = 0.0) -> str | None:
        deadline = None if timeout is None else time.monotonic() + timeout  # tpulint: disable=TPU001 — blocking production get(): real threads wait on a real clock; soaks use the virtual branch
        with self._cv:
            while True:
                self._fire_due_locked()
                if self._queue:
                    key = self._queue.pop(0)
                    self._dirty.discard(key)
                    self._processing.add(key)
                    self._m["gets"] += 1
                    return key
                if self._shutdown:
                    return None
                waits = []
                if deadline is not None:
                    remain = deadline - time.monotonic()  # tpulint: disable=TPU001 — production blocking wait (see deadline above)
                    if remain <= 0:
                        return None
                    waits.append(remain)
                if not self._virtual and self._timers:
                    until = self._timers[0][0] - self._now()
                    if until > 0:
                        waits.append(until)
                self._cv.wait(min(waits) if waits else None)

    def done(self, key: str) -> None:
        with self._cv:
            self._processing.discard(key)
            if key in self._dirty:
                # Key stays dirty across the re-add (dirty == queued-or-
                # pending); clearing it would let a later add() enqueue a
                # duplicate and break one-worker-per-key.
                self._queue.append(key)
                self._m["requeues"] += 1
                self._m["max_depth"] = max(
                    self._m["max_depth"], len(self._queue)
                )
                self._cv.notify()

    def advance(self, seconds: float) -> None:
        with self._cv:
            self._vnow += seconds
            self._fire_due_locked()
            self._cv.notify_all()

    def now(self) -> float:
        with self._lock:
            return self._now()

    def next_deadline(self) -> float | None:
        with self._lock:
            return self._timers[0][0] if self._timers else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def timer_count(self) -> int:
        with self._lock:
            return len(self._timers)

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()

    def metrics(self) -> dict:
        with self._lock:
            return dict(self._m)


def make_workqueue(**kwargs):
    """Native queue when the library loads, Python fallback otherwise."""
    if native_available():
        return NativeWorkQueue(**kwargs)
    return PyWorkQueue(**kwargs)
