"""TPU-native notebook platform."""
