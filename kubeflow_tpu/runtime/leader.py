"""Lease-based leader election for controller replicas.

Reference parity: the Go controllers enable controller-runtime leader election
(``notebook-controller/main.go:84-91``) so only one replica reconciles. Same
protocol here: a ``coordination.k8s.io/v1 Lease`` object is the lock — the
holder renews it, challengers take over when ``renewTime`` is older than the
lease duration. Works against both the in-memory cluster (tests) and the real
API server (optimistic-concurrency conflicts on update mean we lost a race).
"""
from __future__ import annotations

import datetime
import math
import logging
import os
import socket
import threading
import time
import uuid
from typing import Callable

from kubeflow_tpu.runtime.fake import AlreadyExists, Conflict, NotFound

log = logging.getLogger("leader")

_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def _format(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc
    ).strftime(_FMT)


def _parse(s: str) -> float:
    return (
        datetime.datetime.strptime(s, _FMT)
        .replace(tzinfo=datetime.timezone.utc)
        .timestamp()
    )


def default_identity() -> str:
    return f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    """Acquire/renew loop over one Lease; callbacks mirror controller-runtime's
    ``OnStartedLeading``/``OnStoppedLeading``."""

    def __init__(
        self,
        cluster,
        *,
        name: str,
        namespace: str = "kubeflow-system",
        identity: str | None = None,
        lease_duration: float = 15.0,
        renew_deadline: float | None = None,
        retry_period: float = 2.0,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.cluster = cluster
        self.name = name
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_duration = lease_duration
        # client-go discipline (main.go:84-91 uses its defaults 15s/10s/2s):
        # a leader that hasn't successfully renewed within renew_deadline
        # stands down, strictly before the lease can expire for challengers —
        # the gap absorbs clock skew and the retry-period detection lag.
        self.renew_deadline = (
            renew_deadline
            if renew_deadline is not None
            else lease_duration * (2.0 / 3.0)
        )
        if not (0 < self.renew_deadline < lease_duration):
            raise ValueError(
                f"renew_deadline ({self.renew_deadline}) must be positive and "
                f"strictly less than lease_duration ({lease_duration})"
            )
        self.retry_period = retry_period
        self.clock = clock
        self.is_leader = False

    # ---------------------------------------------------------------- step

    def try_acquire_or_renew(self) -> bool:
        """One election step; updates ``is_leader`` and returns it."""
        now = self.clock()
        try:
            lease = self.cluster.get("Lease", self.name, self.namespace)
        except NotFound:
            lease = self._new_lease(now)
            try:
                self.cluster.create(lease)
                self.is_leader = True
                log.info("%s acquired lease %s (created)", self.identity, self.name)
                return True
            except (AlreadyExists, Conflict):
                self.is_leader = False
                return False

        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity")
        renew = _parse(spec["renewTime"]) if spec.get("renewTime") else 0.0

        if holder == self.identity:
            spec["renewTime"] = _format(now)
            # client-go writes LeaseDurationSeconds on every acquire/renew —
            # a lease inherited from a differently-configured replica must
            # not advertise a shorter expiry than our renew_deadline ordering
            # was validated against.
            spec["leaseDurationSeconds"] = math.ceil(self.lease_duration)
            try:
                self.cluster.update(lease)
                self.is_leader = True
                return True
            except NotFound:
                self.is_leader = False
                return False
            # A 409 on our OWN renew is ambiguous — a transient apiserver
            # blip or a write that raced ours — and must not stand a healthy
            # leader down instantly (run() would then return for good).
            # Propagate into run()'s renew-deadline grace: the next step
            # re-reads, so a genuine takeover shows an unexpired foreign
            # holder (definitive stand-down, the branch below) while a blip
            # just renews late. Safe because a legitimate takeover requires
            # our renewTime to age past lease_duration, and the grace
            # expires earlier, at renew_deadline < lease_duration.

        if now < renew + float(spec.get("leaseDurationSeconds", self.lease_duration)):
            self.is_leader = False  # healthy holder elsewhere
            return False

        # Expired — challenge.
        spec["holderIdentity"] = self.identity
        spec["leaseDurationSeconds"] = math.ceil(self.lease_duration)
        spec["acquireTime"] = _format(now)
        spec["renewTime"] = _format(now)
        spec["leaseTransitions"] = int(spec.get("leaseTransitions", 0)) + 1
        try:
            self.cluster.update(lease)
            log.info(
                "%s took over lease %s from %s", self.identity, self.name, holder
            )
            self.is_leader = True
            return True
        except (Conflict, NotFound):
            self.is_leader = False
            return False

    def _new_lease(self, now: float) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                # ceil: the advertised (integer) duration must never undercut
                # the float the renew_deadline ordering was validated against
                "leaseDurationSeconds": math.ceil(self.lease_duration),
                "acquireTime": _format(now),
                "renewTime": _format(now),
                "leaseTransitions": 0,
            },
        }

    # ---------------------------------------------------------------- loop

    def run(
        self,
        on_started_leading: Callable[[], None],
        *,
        on_stopped_leading: Callable[[], None] | None = None,
        stop: threading.Event | None = None,
    ) -> None:
        """Block until leadership, fire the callback, keep renewing; on loss
        fire ``on_stopped_leading`` (default: hard exit, the controller-runtime
        behavior — a stale leader must not keep reconciling).

        ``run`` RETURNS after a stand-down (client-go's ``LeaderElector.Run``
        contract): the loop must not keep renewing with workers stopped —
        re-acquiring its own still-unexpired lease seconds after standing down
        would fire ``on_started_leading`` into a half-torn-down process. The
        exactly-once guarantee on ``on_stopped_leading`` is structural: the
        callback is immediately followed by the return."""
        stop = stop or threading.Event()
        was_leader = False
        last_renew_ok = self.clock()
        while not stop.is_set():
            # Stamp BEFORE the API call: the lease's renewTime is also taken
            # before the call, so the stand-down clock and the challengers'
            # expiry clock start from the same instant.
            t_step = self.clock()
            try:
                leading = self.try_acquire_or_renew()
                if leading:
                    last_renew_ok = t_step
            except Exception:
                # Transient API error (connection blip, 5xx, renew 409):
                # keep retrying —
                # dying here while workers run would be silent split-brain.
                # A leader that can't renew within renew_deadline must stand
                # down while the lease is still unexpired for challengers
                # (renew_deadline < lease_duration guarantees the ordering).
                log.exception("election step failed for %s", self.name)
                leading = was_leader and (
                    self.clock() - last_renew_ok < self.renew_deadline
                )
                self.is_leader = leading
            if leading and not was_leader:
                on_started_leading()
            elif was_leader and not leading:
                log.error("%s lost lease %s", self.identity, self.name)
                self.is_leader = False
                if on_stopped_leading is not None:
                    on_stopped_leading()
                else:  # pragma: no cover - process exit
                    os._exit(1)
                return
            was_leader = leading
            stop.wait(self.retry_period)
