"""Shared web-app backend library (the crud_backend analog, SURVEY.md L5).

Everything the reference's ``crud-web-apps/common/backend/kubeflow/kubeflow/
crud_backend`` package provides, on Werkzeug instead of Flask (which isn't in
the TPU image): header authn (``authn.py``), per-verb authz
(``authz.py:25-132``), CSRF double-submit cookie (``csrf.py:57-90``),
success/error JSON envelope, liveness/readiness probes (``probes.py:8-17``),
Prometheus text metrics, and SPA serving with a no-cache index
(``serving.py:18-31``).

Apps are plain WSGI callables — servable by any WSGI server and testable with
``werkzeug.test.Client`` (no socket needed).
"""
from __future__ import annotations

import gzip as gzip_mod
import json
import logging
import secrets
import time
from typing import Any, Callable

from werkzeug.exceptions import HTTPException, NotFound
from werkzeug.routing import Map, RequestRedirect, Rule
from werkzeug.wrappers import Request, Response

from kubeflow_tpu.auth.rbac import AuthError, Authorizer, User, authenticate
from kubeflow_tpu.runtime.fake import AdmissionDenied, AlreadyExists, Conflict
from kubeflow_tpu.runtime.fake import NotFound as ClusterNotFound
from kubeflow_tpu.utils.metrics import Registry, WebAppMetrics

log = logging.getLogger("webapps")

CSRF_COOKIE = "XSRF-TOKEN"
CSRF_HEADER = "X-XSRF-TOKEN"
SAFE_METHODS = {"GET", "HEAD", "OPTIONS"}

# Request-trace propagation (obs/timeline.py origin point): every request
# gets an id — the caller's, if it sent one, else freshly minted — echoed
# on the response and available to handlers via request_id(). The spawner
# stamps it on the Notebook CR it creates, linking reconcile spans,
# scheduler bind writes, and session-barrier writes back to the click.
REQUEST_ID_HEADER = "X-Request-Id"
_REQUEST_ID_ENV = "kubeflow_tpu.request_id"
# bound + charset-restricted: the id lands in log lines, response headers,
# and CR annotations — a hostile header must not smuggle content into any
_REQUEST_ID_MAX = 64


def request_id(request: Request) -> str:
    """The request's trace id (middleware-assigned; '' outside an App)."""
    return request.environ.get(_REQUEST_ID_ENV, "")


def _assign_request_id(request: Request) -> str:
    rid = request.environ.get(_REQUEST_ID_ENV)
    if rid:
        return rid
    raw = (request.headers.get(REQUEST_ID_HEADER) or "")[:_REQUEST_ID_MAX]
    rid = "".join(c for c in raw if c.isalnum() or c in "-._")
    if not rid:
        rid = f"req-{secrets.token_hex(8)}"
    request.environ[_REQUEST_ID_ENV] = rid
    return rid


# responses below this many bytes aren't worth the gzip round trip
_GZIP_MIN_BYTES = 512
_GZIP_MIMES = ("application/json", "text/plain", "text/html", "text/css",
               "application/javascript")


def not_modified(request: Request, etag: str | None) -> Response | None:
    """HTTP revalidation: a request whose If-None-Match covers ``etag``
    gets a 304 with no body and no serialization work. ``etag`` is the
    ReadCache signature (None = unserviceable → always render fully)."""
    if not etag:
        return None
    inm = request.headers.get("If-None-Match", "")
    candidates = {t.strip().strip('"') for t in inm.split(",") if t.strip()}
    if etag in candidates or "*" in candidates:
        resp = Response(status=304)
        resp.headers["ETag"] = f'"{etag}"'
        return resp
    return None


def set_etag(resp: Response, etag: str | None) -> Response:
    if etag:
        resp.headers["ETag"] = f'"{etag}"'
    return resp


def maybe_gzip(request: Request, response: Response) -> bool:
    """Compress a sizable compressible 200 for a gzip-accepting client.
    Returns True when the body was compressed."""
    if response.status_code != 200:
        return False
    if response.headers.get("Content-Encoding"):
        return False
    if "gzip" not in request.headers.get("Accept-Encoding", "").lower():
        return False
    if response.mimetype not in _GZIP_MIMES:
        return False
    body = response.get_data()
    if len(body) < _GZIP_MIN_BYTES:
        return False
    # level 1: the point is wire bytes at the UI's poll cadence, not
    # archive ratios — higher levels just burn serve-path CPU
    response.set_data(gzip_mod.compress(body, compresslevel=1))
    response.headers["Content-Encoding"] = "gzip"
    response.headers["Vary"] = "Accept-Encoding"
    return True


def success(key: str | None = None, value: Any = None, **extra) -> Response:
    """The crud_backend success envelope (``api.success_response``)."""
    body = {"success": True, "status": 200}
    if key is not None:
        body[key] = value
    body.update(extra)
    return Response(json.dumps(body), mimetype="application/json")


def error(status: int, log_text: str) -> Response:
    body = {"success": False, "status": status, "log": log_text}
    return Response(json.dumps(body), status=status, mimetype="application/json")


class App:
    """Minimal routed WSGI app with the platform's auth/CSRF/probe plumbing."""

    def __init__(
        self,
        name: str,
        *,
        authorizer: Authorizer | None = None,
        userid_header: str = "kubeflow-userid",
        userid_prefix: str = "",
        csrf_protect: bool = True,
        metrics_registry: Registry | None = None,
        metrics_public: bool = False,
        count_requests: bool = True,
    ) -> None:
        self.name = name
        self.authorizer = authorizer
        self.userid_header = userid_header
        self.userid_prefix = userid_prefix
        self.csrf_protect = csrf_protect
        # every app exposes /metrics with request/error counters, like the
        # reference's per-service prometheus wiring (kfam/monitoring.go:24-45,
        # profile-controller monitoring.go:25-60); domain registries
        # (NotebookMetrics) plug in via metrics_registry
        if metrics_registry is None:
            metrics_registry = Registry()
        self.metrics_registry = metrics_registry
        self.count_requests = count_requests
        self._requests_total = metrics_registry.counter(
            "http_requests_total", "HTTP requests served, by method and code"
        )
        # read-path observability (docs/observability.md): per-route latency
        # histogram + revalidation/gzip counters; the ReadCache families ride
        # the same instance when a cache is attached to this app
        self.web_metrics = WebAppMetrics(metrics_registry)
        self.url_map = Map()
        self.endpoints: dict[str, Callable] = {}
        # probes (ref probes.py:8-17)
        self.route("/healthz/liveness")(lambda req: success("message", "alive"))
        self.route("/healthz/readiness")(lambda req: success("message", "ready"))
        # closes over self, not the constructor local: swapping
        # app.metrics_registry later would otherwise silently diverge from
        # what /metrics serves. On the user-facing port the route requires an
        # authenticated caller (ADVICE r3: counters and any domain registry
        # must not be readable by anonymous clients); unauthenticated scrape
        # belongs on the dedicated ops port (ops_app), like the reference's
        # separate metrics bind address (main.go:56).
        def metrics_view(req):
            if not metrics_public:
                self.current_user(req)
            return Response(
                self.metrics_registry.expose(), mimetype="text/plain"
            )

        self.route("/metrics")(metrics_view)
        self._on_close: list[Callable[[], None]] = []

    def on_close(self, fn: Callable[[], None]) -> None:
        """Register teardown (background samplers, watchers). WSGI has no
        lifecycle of its own; embedders that create apps repeatedly (tests,
        hot-reloading servers) call close() or the resources accumulate."""
        self._on_close.append(fn)

    def close(self) -> None:
        for fn in self._on_close:
            try:
                fn()
            except Exception:
                pass

    def ops_app(self) -> "App":
        """A sibling app for the ops port: same registry, /metrics served
        without authentication (Prometheus scrapes don't carry the gateway's
        userid header), probes included. Mirrors the controller's serve_ops."""
        # count_requests=False: scrape and probe hits on the ops port are
        # self-monitoring traffic and must not skew the user-facing app's
        # request-rate/error-ratio series (promhttp doesn't self-instrument
        # either)
        return App(
            f"{self.name}-ops",
            csrf_protect=False,
            metrics_registry=self.metrics_registry,
            metrics_public=True,
            count_requests=False,
        )

    def route(self, rule: str, methods: tuple[str, ...] = ("GET",)):
        def deco(fn):
            endpoint = f"{fn.__name__}:{rule}:{','.join(methods)}"
            self.url_map.add(Rule(rule, endpoint=endpoint, methods=list(methods)))
            self.endpoints[endpoint] = fn
            return fn

        return deco

    # ----------------------------------------------------------------- auth

    def current_user(self, request: Request) -> User:
        return authenticate(
            request.headers,
            userid_header=self.userid_header,
            userid_prefix=self.userid_prefix,
        )

    def ensure(self, request: Request, verb: str, resource: str, namespace: str) -> User:
        """authn + authz in one call (the reference's @needs_authorization)."""
        user = self.current_user(request)
        if self.authorizer is not None:
            self.authorizer.ensure(user, verb, resource, namespace)
        return user

    # ----------------------------------------------------------------- wsgi

    def _check_csrf(self, request: Request) -> Response | None:
        """Double-submit cookie (ref csrf.py:57-90): mutating requests must
        echo the cookie token in the header."""
        if not self.csrf_protect or request.method in SAFE_METHODS:
            return None
        cookie = request.cookies.get(CSRF_COOKIE)
        header = request.headers.get(CSRF_HEADER)
        # Missing cookie is a Forbidden, like the reference (csrf.py:96-98):
        # a browser that never loaded the app must not be able to mutate.
        if not cookie or header != cookie:
            return error(403, "CSRF token missing or incorrect")
        return None

    def attach_frontend(self, app_dir_name: str) -> None:
        """Serve the app's SPA: shared assets under /static/, the app's
        index.html at /, index served no-cache (ref serving.py:18-31 — a stale
        index must never pin old bundles)."""
        import mimetypes
        import os

        static_root = os.path.join(os.path.dirname(__file__), "static")

        def send(target: str, *, index: bool = False) -> Response:
            real = os.path.realpath(target)
            root = os.path.realpath(static_root)
            # trailing-sep containment: 'static_dev' must not pass as 'static'
            if not real.startswith(root + os.sep) or not os.path.isfile(real):
                return error(404, "not found")
            with open(real, "rb") as f:
                data = f.read()
            mime = mimetypes.guess_type(real)[0] or "application/octet-stream"
            resp = Response(data, mimetype=mime)
            resp.headers["Cache-Control"] = (
                "no-store, must-revalidate" if index else "max-age=300"
            )
            return resp

        index_path = os.path.join(static_root, app_dir_name, "index.html")
        self.route("/")(lambda request: send(index_path, index=True))
        # app-local pages (e.g. the notebook detail page) next to index.html
        self.route("/<page>.html")(
            lambda request, page: send(
                os.path.join(static_root, app_dir_name, f"{page}.html"),
                index=True,
            )
        )
        self.route("/static/<path:path>")(
            lambda request, path: send(os.path.join(static_root, path))
        )

    def __call__(self, environ, start_response):
        request = Request(environ)
        rid = _assign_request_id(request)
        adapter = self.url_map.bind_to_environ(environ)
        started = time.perf_counter()
        route = "<unmatched>"
        try:
            csrf_fail = self._check_csrf(request)
            if csrf_fail is not None:
                # count before the early return: CSRF rejections are an
                # attack-indicating error class /metrics must surface
                if self.count_requests:
                    self._requests_total.inc(
                        method=request.method, code=str(csrf_fail.status_code)
                    )
                csrf_fail.headers[REQUEST_ID_HEADER] = rid
                return csrf_fail(environ, start_response)
            endpoint, args = adapter.match()
            # endpoint is "fn:rule:methods" — the rule pattern is the
            # bounded-cardinality route label (never the raw path: object
            # names would explode the series space)
            route = endpoint.split(":", 2)[1] if ":" in endpoint else endpoint
            response = self.endpoints[endpoint](request, **args)
            if isinstance(response, dict):
                response = success(**response)
        except RequestRedirect as e:
            response = e.get_response(environ)  # URL normalization redirect
        except AuthError as e:
            response = error(getattr(e, "status", 401), str(e))
        except (ClusterNotFound, NotFound) as e:
            response = error(404, str(e))
        except (AlreadyExists, Conflict) as e:
            response = error(409, str(e))
        except AdmissionDenied as e:
            # admission denials default to 403; a validator that rejected
            # user INPUT (bad spec.tpu, webhooks/tpu_env.tpu_spec_validator)
            # tags itself 400 so clients see a typed input error
            response = error(getattr(e, "status", 403), str(e))
        except ValueError as e:
            response = error(400, str(e))
        except HTTPException as e:
            response = error(e.code or 500, e.description or str(e))
        except Exception:
            # the traceback is server-side material: frames leak code
            # paths, line numbers, and internal values to any client that
            # can trigger a 500. Log it keyed by the request trace id and
            # hand the client only that opaque id to quote at support.
            log.exception(
                "%s: unhandled error serving %s %s (request id %s)",
                self.name, request.method, request.path, rid,
            )
            response = error(
                500, f"Internal server error (request id {rid})"
            )
        response.headers[REQUEST_ID_HEADER] = rid
        if maybe_gzip(request, response) and self.count_requests:
            self.web_metrics.gzipped.inc()
        if self.count_requests:
            self._requests_total.inc(
                method=request.method, code=str(response.status_code)
            )
            self.web_metrics.observe_request(
                route, response.status_code, time.perf_counter() - started
            )
            if response.status_code == 304:
                self.web_metrics.not_modified.inc(route=route)
        # seed the CSRF cookie on safe responses (double-submit bootstrap)
        if (
            self.csrf_protect
            and request.method in SAFE_METHODS
            and CSRF_COOKIE not in request.cookies
        ):
            response.set_cookie(
                CSRF_COOKIE, secrets.token_urlsafe(16), samesite="Strict"
            )
        return response(environ, start_response)


def add_namespaces_route(app: "App", cluster) -> None:
    """GET /api/namespaces for the shared namespace-select component: names
    the authenticated user may pick from. The reference's child apps get this
    from the dashboard via iframe messaging; standalone pages need a backend
    source (same authenticated-only policy as the dashboard's route)."""

    @app.route("/api/namespaces")
    def list_namespaces(request):
        app.current_user(request)
        names = sorted(
            ns.get("metadata", {}).get("name", "")
            for ns in cluster.list("Namespace")
        )
        return success("namespaces", [n for n in names if n])


def apply_edited_cr(
    cluster,
    kind: str,
    name: str,
    namespace: str,
    body: dict,
    *,
    validate: Callable[[dict], list] | None = None,
    dry_run: bool = False,
) -> dict:
    """Server-side apply for the editable-YAML flow (the kubeflow-common-lib
    ``editor`` module's save path): the full edited CR replaces the stored
    one.

    - Path identity wins: a body whose metadata.name/namespace disagrees
      with the URL is rejected (no silent renames), and kind must match.
    - ``.status`` is carried over from the stored object — main-path updates
      cannot write the status subresource (apiserver semantics the fake
      doesn't enforce on ``update``).
    - A body without resourceVersion applies over the current revision; a
      stale revision surfaces as 409 via the cluster client.
    - ``dry_run`` runs every check and returns the would-be object without
      persisting (the all-or-nothing UX of the POST path).
    """
    if body.get("kind") not in (None, kind):
        raise ValueError(f"kind must be {kind}")
    meta = body.setdefault("metadata", {})
    if meta.get("name", name) != name or meta.get("namespace", namespace) != namespace:
        raise ValueError("metadata.name/namespace must match the URL")
    current = cluster.get(kind, name, namespace)
    body["kind"] = kind
    body.setdefault("apiVersion", current.get("apiVersion"))
    meta["name"], meta["namespace"] = name, namespace
    meta.setdefault("resourceVersion", current["metadata"].get("resourceVersion"))
    if "status" in current:
        body["status"] = current["status"]
    else:
        body.pop("status", None)
    if validate is not None:
        errors = validate(body)
        if errors:
            raise ValueError("; ".join(errors))
    if dry_run:
        return body
    return cluster.update(body)


def handle_cr_put(
    request: Request, cluster, kind: str, name: str, namespace: str,
    *, validate: Callable[[dict], list] | None = None,
    cache=None, principal: str | None = None,
) -> Response:
    """The PUT-handler body every editable CR shares: parse the JSON body,
    honor ?dryRun, apply via ``apply_edited_cr``. Callers do authz first.
    With a ReadCache attached, the committed object writes through and pins
    the principal (read-your-writes for the editor's immediate re-get)."""
    body = get_json(request)
    dry = request.args.get("dryRun", "").lower() in ("1", "true", "all")
    stored = apply_edited_cr(
        cluster, kind, name, namespace, body, validate=validate, dry_run=dry
    )
    if cache is not None and not dry:
        cache.note_write(stored, principal=principal)
    return success("message", "Valid (dry run)." if dry else f"{kind} updated")


def get_json(request: Request, *required: str) -> dict:
    """request_is_json_type + required_body_params (ref decorators.py)."""
    if not request.is_json:
        raise ValueError("Request must be application/json")
    body = request.get_json()
    missing = [p for p in required if p not in body]
    if missing:
        raise ValueError(f"Missing required body params: {missing}")
    return body
