/* kubeflow-common-lib analog: shared frontend runtime for every app
 * (reference: crud-web-apps/common/frontend/kubeflow-common-lib — resource
 * table, status icons, namespace selector, polling service, snack bar,
 * confirm dialog). No framework: custom elements + fetch, so the platform
 * images need no node toolchain. */
(function () {
  "use strict";

  // ---- api client with CSRF double-submit echo ---------------------------
  function csrfToken() {
    const m = document.cookie.match(/(?:^|;\s*)XSRF-TOKEN=([^;]*)/);
    return m ? decodeURIComponent(m[1]) : null;
  }

  async function api(method, url, body) {
    const headers = { "Content-Type": "application/json" };
    const token = csrfToken();
    if (token) headers["X-XSRF-TOKEN"] = token;
    const resp = await fetch(url, {
      method: method,
      headers: headers,
      body: body === undefined ? undefined : JSON.stringify(body),
      credentials: "same-origin",
    });
    const data = await resp.json().catch(() => ({}));
    if (!resp.ok || data.success === false) {
      throw new Error(data.log || resp.statusText);
    }
    return data;
  }

  // ---- snack bar (kubeflow-common-lib snack-bar module) ------------------
  function snack(message, isError) {
    let el = document.getElementById("kf-snack");
    if (!el) {
      el = document.createElement("div");
      el.id = "kf-snack";
      document.body.appendChild(el);
    }
    el.textContent = message;
    el.className = "show" + (isError ? " error" : "");
    setTimeout(() => (el.className = ""), 4000);
  }

  // ---- status icon (status-icon module) ----------------------------------
  const STATUS_ICONS = {
    ready: "✔",
    running: "✔",
    waiting: "⏳",
    warning: "⚠",
    stopped: "⏹",
    terminating: "…",
  };
  function statusIcon(phase) {
    const span = document.createElement("span");
    span.className = "status status-" + phase;
    span.textContent = (STATUS_ICONS[phase] || "•") + " " + phase;
    return span;
  }

  // ---- resource table (resource-table module) ----------------------------
  // columns: [{key, label, render?(row) -> Node|string}]
  function renderTable(container, columns, rows, actions) {
    container.textContent = "";
    const table = document.createElement("table");
    table.className = "kf-table";
    const thead = table.createTHead();
    const hr = thead.insertRow();
    columns.forEach((c) => {
      const th = document.createElement("th");
      th.textContent = c.label;
      hr.appendChild(th);
    });
    if (actions) hr.appendChild(document.createElement("th"));
    const tbody = table.createTBody();
    rows.forEach((row) => {
      const tr = tbody.insertRow();
      columns.forEach((c) => {
        const td = tr.insertCell();
        const v = c.render ? c.render(row) : row[c.key];
        if (v instanceof Node) td.appendChild(v);
        else td.textContent = v == null ? "" : String(v);
      });
      if (actions) {
        const td = tr.insertCell();
        actions(row).forEach((btn) => td.appendChild(btn));
      }
    });
    container.appendChild(table);
  }

  function button(label, onClick, danger) {
    const b = document.createElement("button");
    b.textContent = label;
    b.className = "kf-btn" + (danger ? " danger" : "");
    b.addEventListener("click", onClick);
    return b;
  }

  // ---- confirm dialog (confirm-dialog module) ----------------------------
  function confirmDialog(message) {
    return Promise.resolve(window.confirm(message));
  }

  // ---- namespace selector (namespace-select module) ----------------------
  function currentNamespace() {
    return (
      new URLSearchParams(location.search).get("ns") ||
      localStorage.getItem("kf-namespace") ||
      ""
    );
  }
  function setNamespace(ns) {
    localStorage.setItem("kf-namespace", ns);
  }

  // ---- polling service (poller module) -----------------------------------
  function poll(fn, intervalMs) {
    fn();
    const id = setInterval(fn, intervalMs || 10000);
    return () => clearInterval(id);
  }

  window.kf = {
    api: api,
    snack: snack,
    statusIcon: statusIcon,
    renderTable: renderTable,
    button: button,
    confirmDialog: confirmDialog,
    currentNamespace: currentNamespace,
    setNamespace: setNamespace,
    poll: poll,
  };
})();
