/* kubeflow-common-lib analog: shared frontend runtime for every app
 * (reference: crud-web-apps/common/frontend/kubeflow-common-lib — resource
 * table, status icons, namespace selector, polling service, snack bar,
 * confirm dialog). No framework: custom elements + fetch, so the platform
 * images need no node toolchain. */
(function () {
  "use strict";

  // ---- api client with CSRF double-submit echo ---------------------------
  function csrfToken() {
    const m = document.cookie.match(/(?:^|;\s*)XSRF-TOKEN=([^;]*)/);
    return m ? decodeURIComponent(m[1]) : null;
  }

  async function api(method, url, body) {
    const headers = { "Content-Type": "application/json" };
    const token = csrfToken();
    if (token) headers["X-XSRF-TOKEN"] = token;
    const resp = await fetch(url, {
      method: method,
      headers: headers,
      body: body === undefined ? undefined : JSON.stringify(body),
      credentials: "same-origin",
    });
    const data = await resp.json().catch(() => ({}));
    if (!resp.ok || data.success === false) {
      const err = new Error(data.log || resp.statusText);
      err.status = resp.status;  // callers distinguish 404 from transient
      throw err;
    }
    return data;
  }

  // ---- snack bar (kubeflow-common-lib snack-bar module) ------------------
  function snack(message, isError) {
    let el = document.getElementById("kf-snack");
    if (!el) {
      el = document.createElement("div");
      el.id = "kf-snack";
      document.body.appendChild(el);
    }
    el.textContent = message;
    el.className = "show" + (isError ? " error" : "");
    setTimeout(() => (el.className = ""), 4000);
  }

  // ---- status icon (status-icon module) ----------------------------------
  const STATUS_ICONS = {
    ready: "✔",
    running: "✔",
    waiting: "⏳",
    warning: "⚠",
    stopped: "⏹",
    terminating: "…",
  };
  function statusIcon(phase) {
    const span = document.createElement("span");
    span.className = "status status-" + phase;
    span.textContent = (STATUS_ICONS[phase] || "•") + " " + phase;
    return span;
  }

  // ---- resource table (resource-table module) ----------------------------
  // columns: [{key, label, render?(row) -> Node|string}]
  function renderTable(container, columns, rows, actions) {
    container.textContent = "";
    const table = document.createElement("table");
    table.className = "kf-table";
    const thead = table.createTHead();
    const hr = thead.insertRow();
    columns.forEach((c) => {
      const th = document.createElement("th");
      th.textContent = c.label;
      hr.appendChild(th);
    });
    if (actions) hr.appendChild(document.createElement("th"));
    const tbody = table.createTBody();
    rows.forEach((row) => {
      const tr = tbody.insertRow();
      columns.forEach((c) => {
        const td = tr.insertCell();
        const v = c.render ? c.render(row) : row[c.key];
        if (v instanceof Node) td.appendChild(v);
        else td.textContent = v == null ? "" : String(v);
      });
      if (actions) {
        const td = tr.insertCell();
        actions(row).forEach((btn) => td.appendChild(btn));
      }
    });
    container.appendChild(table);
  }

  function button(label, onClick, danger) {
    const b = document.createElement("button");
    b.textContent = label;
    b.className = "kf-btn" + (danger ? " danger" : "");
    b.addEventListener("click", onClick);
    return b;
  }

  // ---- confirm dialog (confirm-dialog module) ----------------------------
  // A real DOM modal (kubeflow-common-lib confirm-dialog analog), not
  // window.confirm: styleable, keyboard-dismissable, testable.
  function confirmDialog(message, opts) {
    opts = opts || {};
    return new Promise((resolve) => {
      const overlay = document.createElement("div");
      overlay.className = "kf-modal-overlay";
      const box = document.createElement("div");
      box.className = "kf-modal";
      const text = document.createElement("p");
      text.textContent = message;
      const row = document.createElement("div");
      row.className = "kf-modal-actions";
      const cancel = button("Cancel", () => done(false));
      const ok = button(opts.okLabel || "Confirm", () => done(true), opts.danger);
      ok.classList.add("kf-modal-ok");
      cancel.classList.add("kf-modal-cancel");
      row.appendChild(cancel);
      row.appendChild(ok);
      box.appendChild(text);
      box.appendChild(row);
      overlay.appendChild(box);
      function done(result) {
        document.removeEventListener("keydown", onKey);
        overlay.remove();
        resolve(result);
      }
      function onKey(ev) {
        if (ev.key === "Escape") done(false);
      }
      document.addEventListener("keydown", onKey);
      overlay.addEventListener("click", (ev) => {
        if (ev.target === overlay) done(false);
      });
      document.body.appendChild(overlay);
      ok.focus();
    });
  }

  // ---- tabs (the notebook-page tab strip) --------------------------------
  // tabs(container, [{id, label, render(panel) -> cleanup?}]) -> {select(id)}
  // A render may return a cleanup function; it runs before the next tab
  // renders (so pollers like the logs viewer stop when their tab hides).
  function tabs(container, defs) {
    container.textContent = "";
    const bar = document.createElement("nav");
    bar.className = "kf-tabs";
    const panel = document.createElement("div");
    panel.className = "kf-tab-panel";
    const buttons = {};
    let cleanup = null;
    defs.forEach((def) => {
      const b = document.createElement("button");
      b.textContent = def.label;
      b.className = "kf-tab";
      b.dataset.tab = def.id;
      b.addEventListener("click", () => select(def.id));
      buttons[def.id] = b;
      bar.appendChild(b);
    });
    function select(id) {
      if (cleanup) {
        try { cleanup(); } catch (e) {}
        cleanup = null;
      }
      defs.forEach((d) => buttons[d.id].classList.toggle("active", d.id === id));
      panel.textContent = "";
      const out = defs.find((d) => d.id === id).render(panel);
      if (typeof out === "function") cleanup = out;
    }
    container.appendChild(bar);
    container.appendChild(panel);
    if (defs.length) select(defs[0].id);
    return { select: select };
  }

  // ---- logs viewer (kubeflow-common-lib logs-viewer analog) --------------
  // logsViewer(container, fetchLines: () -> Promise<string[]>)
  function logsViewer(container, fetchLines) {
    const bar = document.createElement("div");
    bar.className = "kf-logs-bar";
    const follow = document.createElement("label");
    const followBox = document.createElement("input");
    followBox.type = "checkbox";
    followBox.checked = true;
    follow.appendChild(followBox);
    follow.appendChild(document.createTextNode(" follow"));
    const pre = document.createElement("pre");
    pre.className = "kf-logs";
    async function refresh() {
      try {
        const lines = await fetchLines();
        pre.textContent = lines.join("\n");
        if (followBox.checked) pre.scrollTop = pre.scrollHeight;
      } catch (e) {
        pre.textContent = "(logs unavailable: " + e.message + ")";
      }
    }
    bar.appendChild(button("Refresh", refresh));
    bar.appendChild(follow);
    container.appendChild(bar);
    container.appendChild(pre);
    const stop = poll(refresh, 5000);
    return { refresh: refresh, stop: stop };
  }

  // ---- events table (notebook-page events tab) ---------------------------
  function eventsTable(container, events) {
    renderTable(
      container,
      [
        {
          key: "type",
          label: "Type",
          render: (e) =>
            statusIcon(e.type === "Warning" ? "warning" : "ready"),
        },
        { key: "reason", label: "Reason" },
        { key: "message", label: "Message" },
      ],
      events
    );
  }

  // ---- link helper -------------------------------------------------------
  function link(text, href) {
    const a = document.createElement("a");
    a.textContent = text;
    a.href = href;
    return a;
  }

  // ---- namespace selector (namespace-select module) ----------------------
  function currentNamespace() {
    return (
      new URLSearchParams(location.search).get("ns") ||
      localStorage.getItem("kf-namespace") ||
      ""
    );
  }
  function setNamespace(ns) {
    localStorage.setItem("kf-namespace", ns);
  }

  // ---- polling service (poller module) -----------------------------------
  function poll(fn, intervalMs) {
    fn();
    const id = setInterval(fn, intervalMs || 10000);
    return () => clearInterval(id);
  }

  window.kf = {
    api: api,
    snack: snack,
    statusIcon: statusIcon,
    renderTable: renderTable,
    button: button,
    confirmDialog: confirmDialog,
    tabs: tabs,
    logsViewer: logsViewer,
    eventsTable: eventsTable,
    link: link,
    currentNamespace: currentNamespace,
    setNamespace: setNamespace,
    poll: poll,
  };
})();
