/* kubeflow-common-lib analog: shared frontend runtime for every app
 * (reference: crud-web-apps/common/frontend/kubeflow-common-lib — resource
 * table, status icons, namespace selector, polling service, snack bar,
 * confirm dialog). No framework: custom elements + fetch, so the platform
 * images need no node toolchain. */
(function () {
  "use strict";

  // ---- api client with CSRF double-submit echo ---------------------------
  function csrfToken() {
    const m = document.cookie.match(/(?:^|;\s*)XSRF-TOKEN=([^;]*)/);
    return m ? decodeURIComponent(m[1]) : null;
  }

  async function api(method, url, body) {
    const headers = { "Content-Type": "application/json" };
    const token = csrfToken();
    if (token) headers["X-XSRF-TOKEN"] = token;
    const resp = await fetch(url, {
      method: method,
      headers: headers,
      body: body === undefined ? undefined : JSON.stringify(body),
      credentials: "same-origin",
    });
    const data = await resp.json().catch(() => ({}));
    if (!resp.ok || data.success === false) {
      const err = new Error(data.log || resp.statusText);
      err.status = resp.status;  // callers distinguish 404 from transient
      throw err;
    }
    return data;
  }

  // ---- snack bar (kubeflow-common-lib snack-bar module) ------------------
  function snack(message, isError) {
    let el = document.getElementById("kf-snack");
    if (!el) {
      el = document.createElement("div");
      el.id = "kf-snack";
      document.body.appendChild(el);
    }
    el.textContent = message;
    el.className = "show" + (isError ? " error" : "");
    setTimeout(() => (el.className = ""), 4000);
  }

  // ---- status icon (status-icon module) ----------------------------------
  const STATUS_ICONS = {
    ready: "✔",
    running: "✔",
    waiting: "⏳",
    warning: "⚠",
    stopped: "⏹",
    suspended: "⏸",
    resuming: "↻",
    terminating: "…",
  };
  function statusIcon(phase) {
    const span = document.createElement("span");
    span.className = "status status-" + phase;
    span.textContent = (STATUS_ICONS[phase] || "•") + " " + phase;
    return span;
  }

  // ---- resource table (resource-table module) ----------------------------
  // columns: [{key, label, render?(row) -> Node|string}]
  function renderTable(container, columns, rows, actions) {
    container.textContent = "";
    const table = document.createElement("table");
    table.className = "kf-table";
    const thead = table.createTHead();
    const hr = thead.insertRow();
    columns.forEach((c) => {
      const th = document.createElement("th");
      th.textContent = c.label;
      hr.appendChild(th);
    });
    if (actions) hr.appendChild(document.createElement("th"));
    const tbody = table.createTBody();
    rows.forEach((row) => {
      const tr = tbody.insertRow();
      columns.forEach((c) => {
        const td = tr.insertCell();
        const v = c.render ? c.render(row) : row[c.key];
        if (v instanceof Node) td.appendChild(v);
        else td.textContent = v == null ? "" : String(v);
      });
      if (actions) {
        const td = tr.insertCell();
        actions(row).forEach((btn) => td.appendChild(btn));
      }
    });
    container.appendChild(table);
  }

  function button(label, onClick, danger) {
    const b = document.createElement("button");
    b.textContent = label;
    b.className = "kf-btn" + (danger ? " danger" : "");
    b.addEventListener("click", onClick);
    return b;
  }

  // ---- confirm dialog (confirm-dialog module) ----------------------------
  // A real DOM modal (kubeflow-common-lib confirm-dialog analog), not
  // window.confirm: styleable, keyboard-dismissable, testable.
  function confirmDialog(message, opts) {
    opts = opts || {};
    return new Promise((resolve) => {
      const overlay = document.createElement("div");
      overlay.className = "kf-modal-overlay";
      const box = document.createElement("div");
      box.className = "kf-modal";
      const text = document.createElement("p");
      text.textContent = message;
      const row = document.createElement("div");
      row.className = "kf-modal-actions";
      const cancel = button("Cancel", () => done(false));
      const ok = button(opts.okLabel || "Confirm", () => done(true), opts.danger);
      ok.classList.add("kf-modal-ok");
      cancel.classList.add("kf-modal-cancel");
      row.appendChild(cancel);
      row.appendChild(ok);
      box.appendChild(text);
      box.appendChild(row);
      overlay.appendChild(box);
      function done(result) {
        document.removeEventListener("keydown", onKey);
        overlay.remove();
        resolve(result);
      }
      function onKey(ev) {
        if (ev.key === "Escape") done(false);
      }
      document.addEventListener("keydown", onKey);
      overlay.addEventListener("click", (ev) => {
        if (ev.target === overlay) done(false);
      });
      document.body.appendChild(overlay);
      ok.focus();
    });
  }

  // ---- tabs (the notebook-page tab strip) --------------------------------
  // tabs(container, [{id, label, render(panel) -> cleanup?}]) -> {select(id)}
  // A render may return a cleanup function; it runs before the next tab
  // renders (so pollers like the logs viewer stop when their tab hides).
  function tabs(container, defs) {
    container.textContent = "";
    const bar = document.createElement("nav");
    bar.className = "kf-tabs";
    const panel = document.createElement("div");
    panel.className = "kf-tab-panel";
    const buttons = {};
    let cleanup = null;
    defs.forEach((def) => {
      const b = document.createElement("button");
      b.textContent = def.label;
      b.className = "kf-tab";
      b.dataset.tab = def.id;
      b.addEventListener("click", () => select(def.id));
      buttons[def.id] = b;
      bar.appendChild(b);
    });
    function select(id) {
      if (cleanup) {
        try { cleanup(); } catch (e) {}
        cleanup = null;
      }
      defs.forEach((d) => buttons[d.id].classList.toggle("active", d.id === id));
      panel.textContent = "";
      const out = defs.find((d) => d.id === id).render(panel);
      if (typeof out === "function") cleanup = out;
    }
    container.appendChild(bar);
    container.appendChild(panel);
    if (defs.length) select(defs[0].id);
    return { select: select };
  }

  // ---- logs viewer (kubeflow-common-lib logs-viewer analog) --------------
  // logsViewer(container, fetchLines: () -> Promise<string[]>)
  function logsViewer(container, fetchLines) {
    const bar = document.createElement("div");
    bar.className = "kf-logs-bar";
    const follow = document.createElement("label");
    const followBox = document.createElement("input");
    followBox.type = "checkbox";
    followBox.checked = true;
    follow.appendChild(followBox);
    follow.appendChild(document.createTextNode(" follow"));
    const pre = document.createElement("pre");
    pre.className = "kf-logs";
    async function refresh() {
      try {
        const lines = await fetchLines();
        pre.textContent = lines.join("\n");
        if (followBox.checked) pre.scrollTop = pre.scrollHeight;
      } catch (e) {
        pre.textContent = "(logs unavailable: " + e.message + ")";
      }
    }
    bar.appendChild(button("Refresh", refresh));
    bar.appendChild(follow);
    container.appendChild(bar);
    container.appendChild(pre);
    const stop = poll(refresh, 5000);
    return { refresh: refresh, stop: stop };
  }

  // ---- events table (notebook-page events tab) ---------------------------
  function eventsTable(container, events) {
    renderTable(
      container,
      [
        {
          key: "type",
          label: "Type",
          render: (e) =>
            statusIcon(e.type === "Warning" ? "warning" : "ready"),
        },
        { key: "reason", label: "Reason" },
        { key: "message", label: "Message" },
      ],
      events
    );
  }

  // ---- link helper -------------------------------------------------------
  function link(text, href) {
    const a = document.createElement("a");
    a.textContent = text;
    a.href = href;
    return a;
  }

  // ---- namespace selector (namespace-select module) ----------------------
  function currentNamespace() {
    return (
      new URLSearchParams(location.search).get("ns") ||
      localStorage.getItem("kf-namespace") ||
      ""
    );
  }
  function setNamespace(ns) {
    localStorage.setItem("kf-namespace", ns);
  }

  // ---- polling service (poller module) -----------------------------------
  function poll(fn, intervalMs) {
    fn();
    const id = setInterval(fn, intervalMs || 10000);
    return () => clearInterval(id);
  }

  // ---- date-time module --------------------------------------------------
  // age("2026-01-02T03:04:05Z") -> "3d" (list-page Age columns)
  function age(timestamp) {
    if (!timestamp) return "—";
    const ms = Date.now() - new Date(timestamp).getTime();
    if (isNaN(ms) || ms < 0) return "—";
    const s = Math.floor(ms / 1000);
    if (s < 60) return s + "s";
    if (s < 3600) return Math.floor(s / 60) + "m";
    if (s < 86400) return Math.floor(s / 3600) + "h";
    return Math.floor(s / 86400) + "d";
  }

  // ---- form validation (the Angular form-control validators) -------------
  // RFC 1123 DNS label, the rule the apiserver enforces on metadata.name.
  // Returns an error string, or null when valid.
  function validateK8sName(name) {
    if (!name) return "Name is required.";
    if (name.length > 63) return "Name must be at most 63 characters.";
    if (!/^[a-z0-9]([-a-z0-9]*[a-z0-9])?$/.test(name))
      return "Name must consist of lowercase letters, digits and '-', " +
             "starting and ending with a letter or digit.";
    return null;
  }

  // fieldError(input, msg|null): inline per-field error line (mat-error)
  function fieldError(input, msg) {
    let el = input.parentElement.querySelector(".kf-field-error");
    if (!msg) {
      if (el) el.remove();
      input.classList.remove("invalid");
      return;
    }
    if (!el) {
      el = document.createElement("div");
      el.className = "kf-field-error";
      input.parentElement.appendChild(el);
    }
    el.textContent = msg;
    input.classList.add("invalid");
  }

  // ---- details-list module (detail-page key/value overview) --------------
  // rows: [{label, value: string|Node}]
  function detailsList(container, rows) {
    container.textContent = "";
    const dl = document.createElement("dl");
    dl.className = "kf-details";
    rows.forEach((r) => {
      const dt = document.createElement("dt");
      dt.textContent = r.label;
      const dd = document.createElement("dd");
      if (r.value instanceof Node) dd.appendChild(r.value);
      else dd.textContent = r.value == null ? "—" : String(r.value);
      dl.appendChild(dt);
      dl.appendChild(dd);
    });
    container.appendChild(dl);
  }

  // ---- conditions-table module (CR status.conditions) --------------------
  function conditionsTable(container, conditions) {
    renderTable(
      container,
      [
        {
          key: "status", label: "Status",
          render: (c) => statusIcon(c.status === "True" ? "ready" : "warning"),
        },
        { key: "type", label: "Type" },
        { key: "reason", label: "Reason" },
        { key: "message", label: "Message" },
        { key: "lastTransitionTime", label: "Last transition",
          render: (c) => age(c.lastTransitionTime) },
      ],
      conditions || []
    );
  }

  // ---- editor module (read-only YAML view of the live resource) ----------
  function toYaml(value, indent) {
    indent = indent || "";
    if (value === null || value === undefined) return "null";
    if (typeof value === "string") {
      // quote when YAML would reinterpret the scalar
      if (value === "" || /[:#\[\]{}&*!|>'"%@`,\n]/.test(value) ||
          /^[\s\-?]/.test(value) || /\s$/.test(value) ||
          /^(true|false|null|~|yes|no|on|off)$/i.test(value) ||
          /^[\d.+-]/.test(value))
        return JSON.stringify(value);
      return value;
    }
    if (typeof value !== "object") return String(value);
    if (Array.isArray(value)) {
      if (!value.length) return "[]";
      return value
        .map((v) => {
          const isComposite = typeof v === "object" && v !== null &&
            (Array.isArray(v) ? v.length : Object.keys(v).length);
          if (isComposite) {
            // render at indent+2, then turn the first line's indentation
            // into "- ": continuation lines already align under the first
            // key (block-sequence element indentation)
            const rendered = toYaml(v, indent + "  ");
            return indent + "- " + rendered.slice(indent.length + 2);
          }
          return indent + "- " + toYaml(v, indent);
        })
        .join("\n");
    }
    const keys = Object.keys(value);
    if (!keys.length) return "{}";
    return keys
      .map((k) => {
        const v = value[k];
        const isComposite = typeof v === "object" && v !== null &&
          (Array.isArray(v) ? v.length : Object.keys(v).length);
        if (isComposite)
          return indent + k + ":\n" + toYaml(v, indent + "  ");
        return indent + k + ": " + toYaml(v, indent);
      })
      .join("\n");
  }

  function yamlView(container, obj) {
    container.textContent = "";
    const pre = document.createElement("pre");
    pre.className = "kf-yaml";
    pre.textContent = toYaml(obj);
    container.appendChild(pre);
  }

  // ---- YAML parser (editor module, the toYaml inverse) -------------------
  // Block maps, block sequences, quoted/plain scalars, comments, flow []/{}
  // — the subset toYaml emits plus what humans type into the editor.
  // Throws Error with a 1-based line number on malformed input.
  function fromYaml(text) {
    const rawLines = text.split("\n");
    const lines = [];  // {indent, body, num}
    rawLines.forEach((raw, i) => {
      // strip comments: full-line, or trailing outside quotes
      let line = raw.replace(/\t/g, "  ");
      let inS = null, cut = -1;
      for (let j = 0; j < line.length; j++) {
        const ch = line[j];
        if (inS) { if (ch === inS && line[j - 1] !== "\\") inS = null; }
        else if (ch === '"' || ch === "'") inS = ch;
        else if (ch === "#" && (j === 0 || line[j - 1] === " ")) { cut = j; break; }
      }
      if (cut >= 0) line = line.slice(0, cut);
      if (!line.trim()) return;
      lines.push({
        indent: line.length - line.trimStart().length,
        body: line.trim(),
        num: i + 1,
      });
    });

    function primitive(s, num) {
      if (s === "" || s === "~" || s === "null") return null;
      if (s === "true") return true;
      if (s === "false") return false;
      if (s[0] === '"' || s[0] === "'") {
        try {
          return s[0] === '"'
            ? JSON.parse(s)
            : s.slice(1, -1).replace(/''/g, "'");
        } catch (e) {
          throw new Error("line " + num + ": bad quoted string " + s);
        }
      }
      if (/^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?$/.test(s)) return Number(s);
      return s;
    }

    // flow collections ([a, b] / {k: v}, the k8s-manifest inline style):
    // parsed for real — falling through to a string would silently corrupt
    // an edited CR (e.g. a container command) instead of rejecting it
    function parseFlow(str, num) {
      let i = 0;
      function ws() { while (i < str.length && /\s/.test(str[i])) i++; }
      function fail(what) {
        throw new Error("line " + num + ": " + what + " in flow value " + str);
      }
      function quoted() {
        const q = str[i];
        let j = i + 1;
        while (j < str.length && (str[j] !== q || (q === "'" && str[j + 1] === "'"))) {
          if (q === '"' && str[j] === "\\") j++;
          if (q === "'" && str[j] === "'" && str[j + 1] === "'") j++;
          j++;
        }
        if (j >= str.length) fail("unterminated string");
        const out = primitive(str.slice(i, j + 1), num);
        i = j + 1;
        return out;
      }
      function bare(stop) {
        const start = i;
        while (i < str.length && stop.indexOf(str[i]) === -1) i++;
        return str.slice(start, i).trim();
      }
      function value(stop) {
        ws();
        if (str[i] === "[") return arr();
        if (str[i] === "{") return map();
        if (str[i] === '"' || str[i] === "'") return quoted();
        return primitive(bare(stop), num);
      }
      function arr() {
        i++;  // [
        const out = [];
        ws();
        if (str[i] === "]") { i++; return out; }
        for (;;) {
          out.push(value(",]"));
          ws();
          if (str[i] === ",") { i++; continue; }
          if (str[i] === "]") { i++; return out; }
          fail("expected ',' or ']'");
        }
      }
      function map() {
        i++;  // {
        const out = {};
        ws();
        if (str[i] === "}") { i++; return out; }
        for (;;) {
          ws();
          let key;
          if (str[i] === '"' || str[i] === "'") key = quoted();
          else key = bare(":,}");
          ws();
          if (str[i] !== ":") fail("expected ':'");
          i++;
          out[key] = value(",}");
          ws();
          if (str[i] === ",") { i++; continue; }
          if (str[i] === "}") { i++; return out; }
          fail("expected ',' or '}'");
        }
      }
      const out = value("");
      ws();
      if (i < str.length) fail("trailing content");
      return out;
    }

    function scalar(s, num) {
      s = s.trim();
      if (s[0] === "[" || s[0] === "{") return parseFlow(s, num);
      return primitive(s, num);
    }

    let pos = 0;
    function parseBlock(indent) {
      if (pos >= lines.length) return null;
      const first = lines[pos];
      if (first.indent < indent) return null;
      if (first.body.startsWith("- ") || first.body === "-") {
        const arr = [];
        while (pos < lines.length && lines[pos].indent === first.indent &&
               (lines[pos].body.startsWith("- ") || lines[pos].body === "-")) {
          const ln = lines[pos];
          const rest = ln.body === "-" ? "" : ln.body.slice(2);
          if (!rest) {  // nested block on following lines
            pos++;
            const v = parseBlock(first.indent + 1);
            arr.push(v === null && (pos >= lines.length ||
              lines[pos].indent <= first.indent) ? null : v);
          } else if (rest === "-" || rest.startsWith("- ")) {
            // "- - x": nested sequence inline (what toYaml emits for
            // list-of-lists) — reparse the tail as a sequence item at the
            // virtual indent
            lines[pos] = { indent: ln.indent + 2, body: rest, num: ln.num };
            arr.push(parseBlock(ln.indent + 2));
          } else if (/^[^"':\s][^:]*:(\s|$)/.test(rest) || /^"[^"]*":(\s|$)/.test(rest)) {
            // "- key: value": the item is a map whose first entry is inline;
            // rewrite this line as the map entry at the virtual indent
            lines[pos] = { indent: ln.indent + 2, body: rest, num: ln.num };
            arr.push(parseBlock(ln.indent + 2));
          } else {
            arr.push(scalar(rest, ln.num));
            pos++;
          }
        }
        return arr;
      }
      const obj = {};
      let any = false;
      while (pos < lines.length && lines[pos].indent === first.indent) {
        const ln = lines[pos];
        if (ln.body.startsWith("- ")) break;
        let key, rest;
        const qm = ln.body.match(/^"((?:[^"\\]|\\.)*)"\s*:\s*(.*)$/);
        if (qm) {
          key = JSON.parse('"' + qm[1] + '"');
          rest = qm[2];
        } else {
          const m = ln.body.match(/^([^:]+?)\s*:\s*(.*)$/);
          if (!m) throw new Error("line " + ln.num + ": expected 'key: value'");
          key = m[1];
          rest = m[2];
        }
        pos++;
        if (rest) {
          obj[key] = scalar(rest, ln.num);
        } else {
          const v = parseBlock(ln.indent + 1);
          obj[key] = v === null ? null : v;
        }
        any = true;
      }
      if (!any) {
        throw new Error("line " + first.num + ": unexpected indentation");
      }
      return obj;
    }

    if (!lines.length) return null;
    const out = parseBlock(lines[0].indent);
    if (pos < lines.length) {
      throw new Error("line " + lines[pos].num + ": unexpected content");
    }
    return out;
  }

  // ---- editable editor (kubeflow-common-lib `editor` module) -------------
  // yamlEditor(container, obj, onApply?): read view with an Edit button;
  // Edit swaps in a textarea + Apply/Cancel. Apply parses the YAML and
  // resolves onApply(parsed) (async; typically a PUT) before re-rendering.
  // Without onApply the editor is read-only (the old yamlView behavior).
  function yamlEditor(container, obj, onApply) {
    container.textContent = "";
    const bar = document.createElement("div");
    bar.className = "kf-editor-bar";
    const body = document.createElement("div");
    container.appendChild(bar);
    container.appendChild(body);
    let version = 0;  // bumped by update(): detects refresh during Apply

    function view() {
      bar.textContent = "";
      body.textContent = "";
      if (onApply) bar.appendChild(button("Edit", edit));
      const pre = document.createElement("pre");
      pre.className = "kf-yaml";
      pre.textContent = toYaml(obj);
      body.appendChild(pre);
    }

    // the user-editable surface: everything the PUT honors (status and
    // server-set metadata are carried over server-side, base.py
    // apply_edited_cr) — used to tell status-only refreshes apart from a
    // concurrent edit of what the user is editing
    function editableFingerprint(o) {
      const md = o.metadata || {};
      return JSON.stringify({
        spec: o.spec || null,
        labels: md.labels || null,
        annotations: md.annotations || null,
      });
    }

    function edit() {
      bar.textContent = "";
      body.textContent = "";
      const ta = document.createElement("textarea");
      ta.className = "kf-yaml-edit";
      ta.value = toYaml(obj);
      ta.rows = Math.min(40, ta.value.split("\n").length + 2);
      ta.spellcheck = false;
      const seedPrint = editableFingerprint(obj);
      const err = document.createElement("div");
      err.className = "kf-field-error";
      bar.appendChild(
        button("Apply", async () => {
          let parsed;
          try {
            parsed = fromYaml(ta.value);
          } catch (e) {
            err.textContent = e.message;
            return;
          }
          // polls kept `obj` fresh during the edit. Status-only updates
          // (controller/kubelet) bump resourceVersion without touching
          // anything this editor can change — carry the live rv so a
          // spec-only edit of a Running resource doesn't 409 against its
          // own status churn. A live change to spec/labels/annotations is
          // a REAL concurrent edit: refuse, keep the 409 semantics.
          if (editableFingerprint(obj) !== seedPrint) {
            err.textContent =
              "resource was modified while editing — Cancel to reload";
            return;
          }
          if (parsed && parsed.metadata && obj.metadata &&
              obj.metadata.resourceVersion !== undefined) {
            parsed.metadata.resourceVersion = obj.metadata.resourceVersion;
          }
          err.textContent = "";
          const seen = version;
          try {
            await onApply(parsed);
            // onApply typically reloads and calls update() with the fresh
            // object (new resourceVersion); only fall back to the parsed
            // text when no refresh happened, else the next Apply would
            // carry the stale revision and 409
            if (version === seen) obj = parsed;
            view();
          } catch (e) {
            err.textContent = e.message;  // server rejection: stay editing
          }
        })
      );
      bar.appendChild(button("Cancel", view));
      bar.appendChild(err);
      body.appendChild(ta);
      ta.focus();
    }

    view();
    return {
      update: (next) => {
        obj = next;
        version++;
        // don't clobber an in-progress edit with poll refreshes
        if (!body.querySelector("textarea")) view();
      },
    };
  }

  // ---- loading spinner (loading-spinner module) --------------------------
  function loadingSpinner(container) {
    const el = document.createElement("div");
    el.className = "kf-spinner";
    el.setAttribute("role", "progressbar");
    container.appendChild(el);
    return () => el.remove();
  }

  // ---- help popover (help-popover module) --------------------------------
  function helpPopover(text) {
    const wrap = document.createElement("span");
    wrap.className = "kf-help";
    const btn = document.createElement("button");
    btn.type = "button";
    btn.className = "kf-help-btn";
    btn.textContent = "?";
    btn.setAttribute("aria-label", "help");
    const bubble = document.createElement("span");
    bubble.className = "kf-help-bubble";
    bubble.textContent = text;
    bubble.hidden = true;
    btn.addEventListener("click", () => (bubble.hidden = !bubble.hidden));
    btn.addEventListener("blur", () => (bubble.hidden = true));
    wrap.appendChild(btn);
    wrap.appendChild(bubble);
    return wrap;
  }

  // ---- panel (collapsible section; panel module) -------------------------
  function panel(container, title, renderContent, opts) {
    opts = opts || {};
    const det = document.createElement("details");
    det.className = "kf-panel";
    det.open = opts.open !== false;
    const sum = document.createElement("summary");
    sum.textContent = title;
    det.appendChild(sum);
    const content = document.createElement("div");
    det.appendChild(content);
    renderContent(content);
    container.appendChild(det);
    return det;
  }

  // ---- resource table v2 (sort / filter / pagination) --------------------
  // resourceTable(container, columns, rows, opts):
  //   columns: [{key, label, render?, sortValue?(row)}] — sortValue defaults
  //   to row[key]; opts: {actions?, filter: true, pageSize: 10}
  function resourceTable(container, columns, rows, opts) {
    opts = opts || {};
    const state = {
      sortKey: null,
      asc: true,
      page: 0,
      query: "",
      pageSize: opts.pageSize || 10,
    };

    function sortValue(col, row) {
      if (col.sortValue) return col.sortValue(row);
      const v = row[col.key];
      return v == null ? "" : v;
    }

    function visibleRows() {
      let out = rows;
      if (state.query) {
        const q = state.query.toLowerCase();
        out = out.filter((row) =>
          columns.some((c) =>
            String(sortValue(c, row)).toLowerCase().includes(q)
          )
        );
      }
      if (state.sortKey) {
        const col = columns.find((c) => c.key === state.sortKey);
        out = out.slice().sort((a, b) => {
          const va = sortValue(col, a), vb = sortValue(col, b);
          const cmp = typeof va === "number" && typeof vb === "number"
            ? va - vb
            : String(va).localeCompare(String(vb));
          return state.asc ? cmp : -cmp;
        });
      }
      return out;
    }

    function render() {
      // rebuilding wipes the filter input; if the user is typing in it when
      // a poll-driven update() fires, restore focus and caret or every
      // refresh tick steals the keyboard mid-word
      const prevFilter = container.querySelector(".kf-table-filter");
      const hadFocus = prevFilter && document.activeElement === prevFilter;
      const caret = hadFocus ? prevFilter.selectionStart : null;
      container.textContent = "";
      if (opts.filter) {
        const box = document.createElement("input");
        box.type = "search";
        box.className = "kf-table-filter";
        box.placeholder = "Filter…";
        box.value = state.query;
        box.addEventListener("input", () => {
          state.query = box.value;
          state.page = 0;
          render();
        });
        container.appendChild(box);
        if (hadFocus) {
          box.focus();
          box.setSelectionRange(caret, caret);
        }
      }
      const all = visibleRows();
      // clamp: deletions/refreshes can shrink the list under the current
      // page, which would strand the user on an empty page with no pager
      const maxPage = Math.max(0, Math.ceil(all.length / state.pageSize) - 1);
      state.page = Math.min(state.page, maxPage);
      const start = state.page * state.pageSize;
      const pageRows = all.slice(start, start + state.pageSize);

      const table = document.createElement("table");
      table.className = "kf-table";
      const hr = table.createTHead().insertRow();
      columns.forEach((c) => {
        const th = document.createElement("th");
        th.className = "sortable";
        th.textContent = c.label;
        if (state.sortKey === c.key)
          th.textContent += state.asc ? " ▲" : " ▼";
        th.addEventListener("click", () => {
          state.asc = state.sortKey === c.key ? !state.asc : true;
          state.sortKey = c.key;
          render();
        });
        hr.appendChild(th);
      });
      if (opts.actions) hr.appendChild(document.createElement("th"));
      const tbody = table.createTBody();
      pageRows.forEach((row) => {
        const tr = tbody.insertRow();
        columns.forEach((c) => {
          const td = tr.insertCell();
          const v = c.render ? c.render(row) : row[c.key];
          if (v instanceof Node) td.appendChild(v);
          else td.textContent = v == null ? "" : String(v);
        });
        if (opts.actions) {
          const td = tr.insertCell();
          opts.actions(row).forEach((btn) => td.appendChild(btn));
        }
      });
      container.appendChild(table);

      if (all.length > state.pageSize) {
        const pager = document.createElement("div");
        pager.className = "kf-pager";
        const pages = Math.ceil(all.length / state.pageSize);
        const prev = button("‹", () => { state.page--; render(); });
        prev.disabled = state.page === 0;
        const next = button("›", () => { state.page++; render(); });
        next.disabled = state.page >= pages - 1;
        const label = document.createElement("span");
        label.textContent =
          (start + 1) + "–" + Math.min(start + state.pageSize, all.length) +
          " of " + all.length;
        pager.appendChild(prev);
        pager.appendChild(label);
        pager.appendChild(next);
        container.appendChild(pager);
      }
    }

    render();
    return {
      update: (next) => { rows = next; render(); },
    };
  }

  // ---- sparkline (dashboard metrics chart; resource-charts analog) -------
  // values: number[]; renders an inline SVG polyline
  function sparkline(container, values, opts) {
    opts = opts || {};
    const w = opts.width || 120, h = opts.height || 28;
    const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
    svg.setAttribute("width", w);
    svg.setAttribute("height", h);
    svg.setAttribute("class", "kf-sparkline");
    if (values && values.length > 1) {
      const max = Math.max.apply(null, values.concat([1]));
      const min = Math.min.apply(null, values.concat([0]));
      const span = max - min || 1;
      const pts = values.map((v, i) => {
        const x = (i / (values.length - 1)) * (w - 2) + 1;
        const y = h - 2 - ((v - min) / span) * (h - 4);
        return x.toFixed(1) + "," + y.toFixed(1);
      });
      const line = document.createElementNS("http://www.w3.org/2000/svg", "polyline");
      line.setAttribute("points", pts.join(" "));
      line.setAttribute("fill", "none");
      line.setAttribute("stroke", opts.stroke || "#1a73e8");
      line.setAttribute("stroke-width", "1.5");
      svg.appendChild(line);
    }
    container.textContent = "";
    container.appendChild(svg);
  }

  // ---- namespace selector (namespace-select module, shared) --------------
  // Replaces each page's ad-hoc header label. Inside the dashboard iframe
  // the namespace comes from ?ns= (the dashboard owns the picker, like the
  // reference); standalone pages get a live <select> fed by fetchNamespaces.
  function namespaceSelector(container, opts) {
    opts = opts || {};
    const ns = currentNamespace() || opts.fallback || "default";
    if (window.parent !== window || opts.static) {
      const span = document.createElement("span");
      span.id = "ns-label";
      span.textContent = "namespace: " + ns;
      container.appendChild(span);
      return ns;
    }
    const sel = document.createElement("select");
    sel.id = "ns-select";
    (opts.fetchNamespaces
      ? opts.fetchNamespaces()
      : api("GET", "api/namespaces").then((d) => d.namespaces)
    )
      .then((names) => {
        names.forEach((n) => {
          const name = typeof n === "string" ? n : n.namespace;
          const o = document.createElement("option");
          o.value = name;
          o.textContent = typeof n === "string" ? name : name + " (" + n.role + ")";
          sel.appendChild(o);
        });
        if (names.length) {
          sel.value = ns;
          if (!sel.value) sel.value = sel.options[0].value;
          setNamespace(sel.value);
          if (sel.value !== ns && opts.onChange) opts.onChange(sel.value);
        }
      })
      .catch(() => {
        const o = document.createElement("option");
        o.value = o.textContent = ns;
        sel.appendChild(o);
      });
    sel.addEventListener("change", () => {
      setNamespace(sel.value);
      if (opts.onChange) opts.onChange(sel.value);
    });
    container.appendChild(sel);
    return ns;
  }

  // ---- i18n (reference: crud-web-apps/*/frontend/i18n catalogs) ----------
  // Keys live on elements as data-i18n (textContent) / data-i18n-placeholder
  // (input placeholder); catalogs are flat JSON at static/common/i18n/<lang>
  // .json. English is the source language: with no catalog (or a missing
  // key) the markup's own text stands, so pages never blank out on a fetch
  // failure — same fallback contract as the reference's missing-translation
  // behavior.
  let i18nCatalog = {};
  let i18nLang = "en";

  function t(key, fallback) {
    return Object.prototype.hasOwnProperty.call(i18nCatalog, key)
      ? i18nCatalog[key]
      : (fallback !== undefined ? fallback : key);
  }

  function applyI18n(root) {
    (root || document).querySelectorAll("[data-i18n]").forEach((el) => {
      el.textContent = t(el.dataset.i18n, el.textContent);
    });
    (root || document)
      .querySelectorAll("[data-i18n-placeholder]")
      .forEach((el) => {
        el.placeholder = t(el.dataset.i18nPlaceholder, el.placeholder);
      });
  }

  async function initI18n() {
    // explicit choice (persisted) wins over the browser locale
    const lang = (
      localStorage.getItem("kf.lang") || navigator.language || "en"
    ).slice(0, 2).toLowerCase();
    i18nLang = lang;
    if (lang !== "en") {
      try {
        const resp = await fetch("static/common/i18n/" + lang + ".json", {
          credentials: "same-origin",
        });
        if (resp.ok) i18nCatalog = await resp.json();
      } catch (e) { /* missing catalog -> English */ }
    }
    applyI18n();
    return i18nLang;
  }

  function setLang(lang) {
    localStorage.setItem("kf.lang", lang);
    location.reload();
  }

  window.kf = {
    t: t,
    applyI18n: applyI18n,
    initI18n: initI18n,
    setLang: setLang,
    api: api,
    snack: snack,
    statusIcon: statusIcon,
    renderTable: renderTable,
    button: button,
    confirmDialog: confirmDialog,
    tabs: tabs,
    logsViewer: logsViewer,
    eventsTable: eventsTable,
    link: link,
    currentNamespace: currentNamespace,
    setNamespace: setNamespace,
    poll: poll,
    age: age,
    validateK8sName: validateK8sName,
    fieldError: fieldError,
    detailsList: detailsList,
    conditionsTable: conditionsTable,
    toYaml: toYaml,
    fromYaml: fromYaml,
    yamlView: yamlView,
    yamlEditor: yamlEditor,
    loadingSpinner: loadingSpinner,
    helpPopover: helpPopover,
    panel: panel,
    resourceTable: resourceTable,
    sparkline: sparkline,
    namespaceSelector: namespaceSelector,
  };
})();
