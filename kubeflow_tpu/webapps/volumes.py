"""Volumes web app (VWA) backend: PVC CRUD + used-by view.

Parity with ``crud-web-apps/volumes/backend/apps/default/routes`` — list PVCs
with the pods mounting them (the "used by" column), create from a simple form
(``apps/common/form.py pvc_from_dict``), delete with in-use protection.
"""
from __future__ import annotations

from kubeflow_tpu.auth.rbac import Authorizer
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.webapps import base
from kubeflow_tpu.webapps.base import App, get_json, success


def pods_using_pvc(cluster: FakeCluster, namespace: str, claim: str) -> list[str]:
    out = []
    for pod in cluster.list("Pod", namespace):
        for vol in pod.get("spec", {}).get("volumes", []):
            if vol.get("persistentVolumeClaim", {}).get("claimName") == claim:
                out.append(ko.name(pod))
    return out


def create_app(cluster: FakeCluster, *, authorizer: Authorizer | None = None) -> App:
    app = App("volumes-web-app", authorizer=authorizer or Authorizer(cluster))

    app.attach_frontend("volumes")
    base.add_namespaces_route(app, cluster)

    @app.route("/api/namespaces/<namespace>/pvcs")
    def list_pvcs(request, namespace):
        app.ensure(request, "list", "persistentvolumeclaims", namespace)
        out = []
        for pvc in cluster.list("PersistentVolumeClaim", namespace):
            out.append(
                {
                    "name": ko.name(pvc),
                    "namespace": namespace,
                    "capacity": pvc.get("spec", {})
                    .get("resources", {})
                    .get("requests", {})
                    .get("storage"),
                    "modes": pvc.get("spec", {}).get("accessModes", []),
                    "class": pvc.get("spec", {}).get("storageClassName"),
                    "usedBy": pods_using_pvc(cluster, namespace, ko.name(pvc)),
                    "status": pvc.get("status", {}).get("phase", "Bound"),
                }
            )
        return success("pvcs", out)

    @app.route("/api/namespaces/<namespace>/pvcs", methods=("POST",))
    def post_pvc(request, namespace):
        app.ensure(request, "create", "persistentvolumeclaims", namespace)
        body = get_json(request, "name", "size", "mode")
        pvc = {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": {"name": body["name"], "namespace": namespace},
            "spec": {
                "accessModes": [body["mode"]],
                "resources": {"requests": {"storage": body["size"]}},
            },
        }
        if body.get("class"):
            pvc["spec"]["storageClassName"] = body["class"]
        cluster.create(pvc)
        return success("message", "PVC created successfully.")

    @app.route("/api/namespaces/<namespace>/pvcs/<name>", methods=("DELETE",))
    def delete_pvc(request, namespace, name):
        app.ensure(request, "delete", "persistentvolumeclaims", namespace)
        users = pods_using_pvc(cluster, namespace, name)
        if users:
            raise ValueError(
                f"PVC {name} is in use by pods: {', '.join(users)}"
            )
        cluster.delete("PersistentVolumeClaim", name, namespace)
        return success("message", "PVC deleted")

    return app
