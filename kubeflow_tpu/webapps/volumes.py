"""Volumes web app (VWA) backend: PVC CRUD + used-by view.

Parity with ``crud-web-apps/volumes/backend/apps/default/routes`` — list PVCs
with the pods mounting them (the "used by" column), create from a simple form
(``apps/common/form.py pvc_from_dict``), delete with in-use protection.
"""
from __future__ import annotations

from kubeflow_tpu.auth.rbac import Authorizer
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.webapps import base
from kubeflow_tpu.webapps.base import App, get_json, success
from kubeflow_tpu.webapps.cache import ReadCache

VWA_KINDS = ("PersistentVolumeClaim", "Pod")


def pods_using_pvc(cluster: FakeCluster, namespace: str, claim: str) -> list[str]:
    out = []
    for pod in cluster.list("Pod", namespace):
        for vol in pod.get("spec", {}).get("volumes", []):
            if vol.get("persistentVolumeClaim", {}).get("claimName") == claim:
                out.append(ko.name(pod))
    return out


def create_app(
    cluster: FakeCluster,
    *,
    authorizer: Authorizer | None = None,
    cache: ReadCache | None = None,
    use_cache: bool = True,
) -> App:
    app = App("volumes-web-app", authorizer=authorizer or Authorizer(cluster))
    if cache is not None:
        cache.ensure_kinds(VWA_KINDS)
    elif use_cache:
        cache = ReadCache(
            cluster, VWA_KINDS, metrics=app.web_metrics
        ).start()
        app.on_close(cache.close)

    def _used_by(namespace: str, claim: str) -> list[str]:
        # pods-by-claim index: the "used by" column without an
        # O(pvcs x pods) scan per render
        if cache is not None:
            return cache.pods_using_claim(namespace, claim)
        return pods_using_pvc(cluster, namespace, claim)

    app.attach_frontend("volumes")
    base.add_namespaces_route(app, cluster)

    @app.route("/api/namespaces/<namespace>/pvcs")
    def list_pvcs(request, namespace):
        user = app.ensure(request, "list", "persistentvolumeclaims", namespace)
        etag = (
            cache.etag(
                ("PersistentVolumeClaim", namespace), ("Pod", namespace),
                principal=user.name,
            )
            if cache is not None else None
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        pvcs = (
            cache.list(
                "PersistentVolumeClaim", namespace,
                principal=user.name, copy=False,
            )
            if cache is not None
            else cluster.list("PersistentVolumeClaim", namespace)
        )
        out = []
        for pvc in pvcs:
            out.append(
                {
                    "name": ko.name(pvc),
                    "namespace": namespace,
                    "capacity": pvc.get("spec", {})
                    .get("resources", {})
                    .get("requests", {})
                    .get("storage"),
                    "modes": pvc.get("spec", {}).get("accessModes", []),
                    "class": pvc.get("spec", {}).get("storageClassName"),
                    "usedBy": _used_by(namespace, ko.name(pvc)),
                    "status": pvc.get("status", {}).get("phase", "Bound"),
                }
            )
        return base.set_etag(success("pvcs", out), etag)

    @app.route("/api/namespaces/<namespace>/pvcs", methods=("POST",))
    def post_pvc(request, namespace):
        user = app.ensure(request, "create", "persistentvolumeclaims", namespace)
        body = get_json(request, "name", "size", "mode")
        pvc = {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": {"name": body["name"], "namespace": namespace},
            "spec": {
                "accessModes": [body["mode"]],
                "resources": {"requests": {"storage": body["size"]}},
            },
        }
        if body.get("class"):
            pvc["spec"]["storageClassName"] = body["class"]
        stored = cluster.create(pvc)
        if cache is not None:
            cache.note_write(stored, principal=user.name)
        return success("message", "PVC created successfully.")

    @app.route("/api/namespaces/<namespace>/pvcs/<name>", methods=("DELETE",))
    def delete_pvc(request, namespace, name):
        user = app.ensure(request, "delete", "persistentvolumeclaims", namespace)
        # in-use protection reads the authoritative store, not the cache: a
        # pod bound seconds ago must block the delete even mid-staleness
        users = pods_using_pvc(cluster, namespace, name)
        if users:
            raise ValueError(
                f"PVC {name} is in use by pods: {', '.join(users)}"
            )
        cluster.delete("PersistentVolumeClaim", name, namespace)
        if cache is not None:
            cache.note_delete(
                "PersistentVolumeClaim", name, namespace, principal=user.name
            )
        return success("message", "PVC deleted")

    return app
