"""Central dashboard backend: platform aggregation API.

Parity with the reference's Express server endpoints
(``centraldashboard/app/api.ts:31-95`` and ``api_workgroup.ts:254-388``):

  GET  /api/workgroup/env-info      namespaces + platform + user + registration
  GET  /api/workgroup/exists        has the user a profile?
  POST /api/workgroup/create        self-serve registration
  GET  /api/namespaces              all namespaces
  GET  /api/activities/<namespace>  recent events (ref activities endpoint)
  GET  /api/dashboard-links         configurable menu/link set
  GET  /api/metrics/<type>          cluster metrics; the reference only ships a
       Stackdriver impl (metrics_service_factory.ts:24) — here the default
       impl reads the platform's own Prometheus registries (TPU-first:
       chips-in-use is a first-class series)
"""
from __future__ import annotations

import json
import os

from kubeflow_tpu.api import types as api
from kubeflow_tpu.auth.kfam import BindingClient, ProfileClient
from kubeflow_tpu.auth.rbac import Authorizer, Forbidden
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.utils.metrics import NotebookMetrics
from kubeflow_tpu.webapps import base
from kubeflow_tpu.webapps.base import App, get_json, success
from kubeflow_tpu.webapps.cache import ReadCache
from kubeflow_tpu.webapps.metrics_source import (
    MetricsSource,
    RegistrySource,
    metrics_source_from_env,
)

DEFAULT_LINKS = {
    "menuLinks": [
        {"type": "item", "link": "/jupyter/", "text": "Notebooks", "icon": "book"},
        {"type": "item", "link": "/tensorboards/", "text": "TensorBoards", "icon": "assessment"},
        {"type": "item", "link": "/volumes/", "text": "Volumes", "icon": "device:storage"},
    ],
    "externalLinks": [],
    "documentationItems": [
        {
            "text": "TPU Notebook Platform",
            "desc": "Run JAX/XLA notebooks on TPU pod slices",
            "link": "/docs/",
        }
    ],
}

# ref api.ts:88-101 serves whatever JSON the ConfigMap's "settings" key
# holds; these are the platform defaults overlaid under it
DEFAULT_SETTINGS = {
    "DASHBOARD_FORCE_IFRAME": True,
}


def create_app(
    cluster: FakeCluster,
    *,
    userid_header: str = "kubeflow-userid",
    userid_prefix: str = "",
    cluster_admins: set[str] | None = None,
    metrics: NotebookMetrics | None = None,
    metrics_source: MetricsSource | None = None,
    links: dict | None = None,
    telemetry=None,
    gang=None,
    profiler=None,
    slo=None,
    scheduler=None,
    ledger=None,
    capacity=None,
    cache: ReadCache | None = None,
    use_cache: bool = True,
) -> App:
    metrics = metrics or NotebookMetrics()

    def _gauge_total(gauge):
        return lambda: sum(s["value"] for s in gauge.samples())

    # the cluster walk runs ONCE per sample (pre_sample below), not once
    # per reader — the readers are then pure gauge sums
    readers = {
        "notebooks": _gauge_total(metrics.running),
        "tpus": _gauge_total(metrics.tpu_chips_in_use),
    }
    if telemetry is not None:
        # data-plane series (telemetry/collector.py): burned utilization
        # next to the allocation counts above — memory reads off the
        # collector's last pass, so the dashboard ticker never scrapes
        readers["duty_cycle"] = telemetry.fleet_duty_cycle
        readers["hbm"] = telemetry.fleet_hbm_utilization
    if gang is not None:
        # gang step series (telemetry/gang.py): fleet p99 step time and the
        # worst straggler ratio — "is any gang being dragged" next to the
        # duty cycle's "are the chips busy". Memory reads off the
        # aggregator's last pass.
        readers["step_p99"] = gang.fleet_step_p99
        readers["straggler_ratio"] = gang.fleet_straggler_ratio
        # compile telemetry (telemetry/agent.py compile families rolled up
        # per gang): cumulative XLA compile seconds across the fleet — a
        # rising slope after warm-up is the recompilation-storm signature
        # the aggregator's detector names per host
        readers["compile_seconds"] = _gauge_total(gang.metrics.compile_seconds)
    if profiler is not None:
        # finding-triggered captures (obs/profiler.py): how many traces the
        # platform captured, by outcome (stored/failed/rate_limited) — the
        # proof the capture loop is alive and its rate bounds are biting
        readers["capture_count"] = _gauge_total(profiler.metrics.captures)
    if slo is not None:
        # startup SLO series (obs/slo.py): click-to-ready p99 off the real
        # histogram and the fast-window error-budget burn rate — the two
        # numbers the NotebookOS argument says the platform is judged on
        readers["startup_p99"] = slo.startup_p99
        readers["startup_burn_rate"] = slo.fast_burn
    if scheduler is not None:
        # placement series (scheduler/explain.py): queue depth summed
        # across shards, and the fleet fragmentation index — the worst
        # pool's largest-free-cuboid ÷ free-chips ratio, the "would more
        # chips even help" signal next to the capacity counts above. Pure
        # gauge reads: the scheduler's own cycle keeps them current.
        readers["queue_depth"] = scheduler.total_queue_depth
        readers["fragmentation"] = scheduler.fleet_fragmentation_index
    if ledger is not None:
        # efficiency-ledger series (obs/ledger.py): the economics row —
        # busy ÷ allocated, waste ÷ capacity, and live unmet demand in
        # chips. Pure memory reads off the same registry families that
        # /debug/ledger and the JWA efficiency field serve, so every
        # surface tells one story.
        readers["efficiency"] = ledger.fleet_efficiency
        readers["waste"] = ledger.fleet_waste_fraction
        readers["unmet_demand"] = ledger.unmet_demand_chips
    if capacity is not None:
        # elastic-capacity series (capacity/): the time-to-first-chip SLO
        # p50 next to the startup p99 above — the two latencies the
        # platform's L1 contract is judged on — and the chips currently
        # being provisioned (the autoscaler acting on unmet_demand)
        cap_metrics = getattr(capacity, "metrics", None)
        if cap_metrics is not None:
            readers["first_chip_p50"] = cap_metrics.ttfc_p50
            readers["pending_chips"] = _gauge_total(
                cap_metrics.pending_chips
            )
    owned_source = None
    if metrics_source is None:
        if os.environ.get("METRICS_SOURCE"):
            metrics_source = metrics_source_from_env(
                readers, os.environ,
                pre_sample=lambda: metrics.observe_notebooks(cluster),
            )
        else:
            metrics_source = RegistrySource(
                readers,
                pre_sample=lambda: metrics.observe_notebooks(cluster),
            )
        # history accumulates while nobody is looking; an injected source
        # (tests, embedding apps) controls its own ticker. The app owns
        # this one: registered on app.close() below, or every create_app
        # call leaks a polling thread holding the cluster alive
        metrics_source.start_background()
        owned_source = metrics_source
    # the domain gauges are scraped live (reference collector pattern,
    # metrics.go:82-99): refresh them on every expose so the ops-port scrape
    # serves current values, not whatever the last /api/metrics UI hit left
    metrics.registry.pre_expose(lambda: metrics.observe_notebooks(cluster))
    app = App(
        "centraldashboard",
        userid_header=userid_header,
        userid_prefix=userid_prefix,
        authorizer=Authorizer(cluster, cluster_admins=cluster_admins),
        metrics_registry=metrics.registry,
    )
    if owned_source is not None:
        app.on_close(owned_source.stop_background)
    if cache is not None:
        cache.ensure_kinds(("Event",))
    elif use_cache:
        # the activity feed is the dashboard's poll loop; Events come from
        # the watch-backed store instead of a per-request namespace list
        cache = ReadCache(cluster, ("Event",), metrics=app.web_metrics).start()
        app.on_close(cache.close)
    bindings = BindingClient(cluster)
    profiles = ProfileClient(cluster, cluster_admins=cluster_admins)

    app.attach_frontend("dashboard")

    @app.route("/api/workgroup/env-info")
    def env_info(request):
        user = app.current_user(request)
        namespaces = profiles.namespaces_for_user(user.name, bindings)
        return success(
            "user", user.name,
            platform={"kind": "tpu-native", "provider": "gke"},
            namespaces=[
                {"namespace": ns, "role": "owner" if _owns(ns, user.name) else "contributor"}
                for ns in namespaces
            ],
            hasWorkgroup=any(_owns(ns, user.name) for ns in namespaces),
            isClusterAdmin=profiles.is_cluster_admin(user.name),
        )

    def _owns(ns: str, user: str) -> bool:
        prof = cluster.try_get("Profile", ns)
        return bool(
            prof and prof.get("spec", {}).get("owner", {}).get("name") == user
        )

    @app.route("/api/workgroup/exists")
    def exists(request):
        user = app.current_user(request)
        owned = [
            p for p in cluster.list("Profile")
            if p.get("spec", {}).get("owner", {}).get("name") == user.name
        ]
        return success("hasAuth", True, hasWorkgroup=bool(owned), user=user.name)

    @app.route("/api/workgroup/create", methods=("POST",))
    def create_workgroup(request):
        user = app.current_user(request)
        body = request.get_json(silent=True) or {}
        name = body.get("namespace") or user.name.split("@")[0]
        cluster.create(api.profile(name, user.name))
        return success("message", f"Profile {name} created")

    @app.route("/api/workgroup/nuke-self", methods=("DELETE",))
    def nuke_self(request):
        # ref api_workgroup.ts:254-388 "nuke-self": self-serve teardown of the
        # user's PRIMARY profile only (namespace == username, ts:329), via
        # DELETE only. A user who owns additional shared namespaces keeps
        # them — destroying every owned namespace in one call is not what
        # "remove my workgroup" means. An explicit ?namespace= targets one
        # other owned profile.
        user = app.current_user(request)
        body = request.get_json(silent=True) or {}
        target = request.args.get("namespace") or body.get("namespace")
        from werkzeug.exceptions import Conflict, Forbidden, NotFound

        if not target:
            # primary = the username-derived name IF the user owns it; if
            # they registered under a custom namespace (create_workgroup
            # accepts one) and own exactly one profile, that one is
            # unambiguous. Several owned profiles with no explicit target is
            # a 409, never a delete-them-all.
            target = user.name.split("@")[0]
            primary = cluster.try_get("Profile", target)
            primary_owned = bool(
                primary
                and primary.get("spec", {}).get("owner", {}).get("name")
                == user.name
            )
            if not primary_owned:
                owned = [
                    p for p in cluster.list("Profile")
                    if p.get("spec", {}).get("owner", {}).get("name")
                    == user.name
                ]
                if len(owned) == 1:
                    target = ko.name(owned[0])
                elif len(owned) > 1:
                    raise Conflict(
                        f"{user.name} owns several profiles; pass "
                        "?namespace= to pick one."
                    )
        profile = cluster.try_get("Profile", target)
        if profile is None:
            raise NotFound(f"{user.name} has no profile {target} to delete.")
        if profile.get("spec", {}).get("owner", {}).get("name") != user.name:
            raise Forbidden(f"{user.name} does not own profile {target}.")
        for b in bindings.list(namespaces=[target]):
            if b["user"].get("name") == user.name:
                # the owner RoleBinding is the profile controller's (its
                # own naming scheme) and dies with the profile below
                continue
            bindings.delete(b["user"], target, b["roleRef"]["name"])
        profiles.delete(target)
        return success("message", f"Deleted profile {target} for {user.name}")

    # -- contributor management (api_workgroup.ts:254-388: the dashboard
    # backend fronts kfam so the SPA never crosses the app mount) ----------
    def _ensure_can_manage(user, namespace: str) -> None:
        if profiles.is_cluster_admin(user.name) or _owns(namespace, user.name):
            return
        raise Forbidden(
            f"User '{user.name}' may not manage contributors in '{namespace}'"
        )

    @app.route("/api/workgroup/contributors/<namespace>")
    def list_contributors(request, namespace):
        user = app.current_user(request)
        _ensure_can_manage(user, namespace)
        return success(
            "contributors",
            [
                {"user": b["user"], "roleRef": b["roleRef"]}
                for b in bindings.list(namespaces=[namespace])
            ],
        )

    def _contributor_subject(body) -> tuple[dict, str]:
        subject = body["user"]
        if isinstance(subject, str):
            subject = {"kind": "User", "name": subject}
        if not isinstance(subject, dict) or not subject.get("name"):
            raise ValueError(
                "user must be an email string or a subject with a 'name'"
            )
        return subject, (body.get("roleRef") or {}).get("name", "edit")

    @app.route("/api/workgroup/contributors/<namespace>", methods=("POST",))
    def add_contributor(request, namespace):
        user = app.current_user(request)
        _ensure_can_manage(user, namespace)
        subject, role = _contributor_subject(get_json(request, "user"))
        bindings.create(subject, namespace, role)
        return success("message", f"Added {subject['name']} to {namespace}")

    @app.route(
        "/api/workgroup/contributors/<namespace>", methods=("DELETE",)
    )
    def remove_contributor(request, namespace):
        user = app.current_user(request)
        _ensure_can_manage(user, namespace)
        subject, role = _contributor_subject(get_json(request, "user"))
        bindings.delete(subject, namespace, role)
        return success("message", f"Removed {subject['name']} from {namespace}")

    # /api/namespaces comes from the shared helper (one implementation for
    # every app, serving the namespace-select component)
    base.add_namespaces_route(app, cluster)

    @app.route("/api/activities/<namespace>")
    def activities(request, namespace):
        # per-namespace authz: events leak tenant activity (object names,
        # failure messages) — same guard as JWA's events endpoint
        user = app.ensure(request, "list", "events", namespace)
        etag = (
            cache.etag(("Event", namespace), principal=user.name)
            if cache is not None else None
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        events = (
            cache.events_in(namespace, principal=user.name)
            if cache is not None
            else cluster.list("Event", namespace)
        )
        return base.set_etag(success(
            "activities",
            [
                {
                    "event": e.get("reason"),
                    "message": e.get("message"),
                    "type": e.get("type"),
                    "involved": e.get("involvedObject", {}).get("name"),
                }
                for e in events[-50:]
            ],
        ), etag)

    @app.route("/api/dashboard-links")
    def dashboard_links(request):
        return success(None, **(links or DEFAULT_LINKS))

    @app.route("/api/dashboard-settings")
    def dashboard_settings(request):
        """Operator-tunable UI settings (ref api.ts:88-101: JSON under the
        'settings' key of the dashboard ConfigMap). Absent ConfigMap/key →
        defaults; malformed-or-non-object JSON → controlled 500, like the
        reference's invalid_settings error."""
        app.current_user(request)
        cm = cluster.try_get(
            "ConfigMap", "centraldashboard-config",
            os.environ.get("POD_NAMESPACE", "kubeflow"),
        )
        raw = ((cm or {}).get("data") or {}).get("settings")
        if raw is None:
            return success(None, DASHBOARD_SETTINGS=dict(DEFAULT_SETTINGS))
        try:
            settings = json.loads(raw)
            if not isinstance(settings, dict):
                raise ValueError("settings must be a JSON object")
        except ValueError:
            raise RuntimeError("Cannot load dashboard settings")
        return success(None, DASHBOARD_SETTINGS={
            **DEFAULT_SETTINGS, **settings
        })

    @app.route("/api/metrics/<metric_type>")
    def cluster_metrics(request, metric_type):
        """Current per-label values PLUS the server-held series (reference
        api.ts:31-59 serves MetricsService time series; round-3's client-side
        sparkline accumulation vanished on reload and diverged across
        replicas — the history now lives in the MetricsSource store)."""
        app.current_user(request)
        metrics.observe_notebooks(cluster)
        if metric_type == "notebooks":
            values = metrics.running.samples()
        elif metric_type == "tpus":
            values = metrics.tpu_chips_in_use.samples()
        elif telemetry is not None and metric_type == "duty_cycle":
            values = telemetry.metrics.session_duty_cycle.samples()
        elif telemetry is not None and metric_type == "hbm":
            values = telemetry.metrics.session_hbm_used.samples()
        elif gang is not None and metric_type == "step_p99":
            # per-gang p99 step time as the labeled values; the fleet p99
            # is the series
            values = gang.per_gang_p99_samples()
        elif gang is not None and metric_type == "straggler_ratio":
            # per-gang straggler index as the labeled values; the worst
            # gang's ratio is the series
            values = gang.metrics.straggler_ratio.samples()
        elif gang is not None and metric_type == "compile_seconds":
            # per-gang cumulative compile seconds as the labeled values;
            # the fleet total is the series
            values = gang.metrics.compile_seconds.samples()
        elif profiler is not None and metric_type == "capture_count":
            # per-outcome capture counts as the labeled values; the total
            # is the series
            values = profiler.metrics.captures.samples()
        elif slo is not None and metric_type == "startup_p99":
            values = [{"labels": {}, "value": slo.startup_p99()}]
        elif slo is not None and metric_type == "startup_burn_rate":
            slo.refresh()
            values = slo.burn_rate.samples()
        elif scheduler is not None and metric_type == "queue_depth":
            # per-family (and per-shard, when sharded) breakdown as the
            # labeled values; the fleet total is the series
            values = scheduler.family_queue_depth.samples()
        elif scheduler is not None and metric_type == "fragmentation":
            # per-pool fragmentation indices as the labeled values
            values = scheduler.pool_fragmentation.samples()
        elif ledger is not None and metric_type == "efficiency":
            values = [{"labels": {}, "value": ledger.fleet_efficiency()}]
        elif ledger is not None and metric_type == "waste":
            # per-pool/bucket chip-second breakdown as the labeled values;
            # the fleet waste fraction is the series
            values = ledger.metrics.pool_chip_seconds.samples()
        elif ledger is not None and metric_type == "unmet_demand":
            # per-family queued chip-seconds as the labeled values
            values = ledger.metrics.queued_chip_seconds.samples()
        else:
            raise ValueError(f"unknown metric type {metric_type!r}")
        try:
            window = float(request.args.get("window", 900))
        except ValueError:
            raise ValueError("window must be a number of seconds")
        try:
            series = metrics_source.series(metric_type, window)
        except KeyError:
            # a custom source (e.g. prometheus with a trimmed families map)
            # may cover fewer types than the gauges do: misconfiguration,
            # not a server fault
            raise ValueError(
                f"metric type {metric_type!r} not served by the configured "
                f"metrics source (has: {metrics_source.types()})"
            )
        return success(
            "values", values,
            series=series,
            source=getattr(metrics_source, "kind", "registry"),
            interval=metrics_source.interval_s,
        )

    return app
