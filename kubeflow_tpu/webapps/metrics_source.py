"""Dashboard metrics sources with server-held history.

Reference parity: centraldashboard defines a ``MetricsService`` interface
(``centraldashboard/app/metrics_service.ts:11-21``) whose only shipped
implementation queries an external TSDB (Stackdriver), selected by a factory
(``metrics_service_factory.ts:24``); ``api.ts:31-59`` serves the resulting
series to the dashboard charts.

Here the analog is ``MetricsSource``:

- ``RegistrySource`` (default) samples the platform's own in-process gauges
  into a server-held ring buffer — history survives page reloads, unlike the
  round-3 client-side accumulation the verdict called out.
- ``PrometheusSource`` polls an external Prometheus scrape endpoint (text
  exposition) into the same store. Several dashboard replicas pointed at the
  same endpoint converge on the same series because samples are taken on a
  shared wall-clock grid (one sample per ``interval_s`` tick, timestamped at
  the tick) — replica agreement is a contract, not luck.

The factory (``metrics_source_from_env``) mirrors the reference's: the
``METRICS_SOURCE`` env var picks the implementation the way the reference's
``METRICS_SERVICE`` flag picks Stackdriver.
"""
from __future__ import annotations

import abc
import re
import threading
import time
import urllib.request
from typing import Callable, Mapping

DEFAULT_INTERVAL_S = 15.0
DEFAULT_MAXLEN = 720  # 3 h of 15 s ticks


class SeriesStore:
    """Thread-safe per-type ring buffer of (timestamp, value) samples."""

    def __init__(self, maxlen: int = DEFAULT_MAXLEN) -> None:
        self._maxlen = maxlen
        self._series: dict[str, list[tuple[float, float]]] = {}
        self._lock = threading.Lock()

    def append(self, metric_type: str, ts: float, value: float) -> None:
        with self._lock:
            pts = self._series.setdefault(metric_type, [])
            if pts and pts[-1][0] == ts:
                pts[-1] = (ts, value)  # re-sample of the same tick wins
            else:
                pts.append((ts, value))
            if len(pts) > self._maxlen:
                del pts[: len(pts) - self._maxlen]

    def window(
        self, metric_type: str, window_s: float, now: float
    ) -> list[dict]:
        cutoff = now - window_s
        with self._lock:
            pts = self._series.get(metric_type, [])
            return [
                {"timestamp": ts, "value": v} for ts, v in pts if ts >= cutoff
            ]


class MetricsSource(abc.ABC):
    """The series contract every implementation honors (the reference's
    ``MetricsService`` interface, metrics_service_ts:11-21):

    ``series(type, window_s)`` → ordered ``[{"timestamp", "value"}, ...]``
    covering at most the last ``window_s`` seconds, sampled on the source's
    tick grid. Unknown types raise ``KeyError``.

    Samples are taken on read AND by a background ticker
    (``start_background()``, called by the dashboard app): sample-on-read
    alone would leave the store empty between visits — a user returning
    after an hour would see a one-point "history", exactly the failure
    server-held history exists to prevent.
    """

    interval_s: float = DEFAULT_INTERVAL_S
    _ticker: threading.Thread | None = None
    _ticker_stop: threading.Event | None = None

    @abc.abstractmethod
    def types(self) -> list[str]: ...

    @abc.abstractmethod
    def sample(self) -> None: ...

    @abc.abstractmethod
    def series(
        self, metric_type: str, window_s: float = 900.0
    ) -> list[dict]: ...

    def start_background(self) -> None:
        """Sample every tick even with no readers (idempotent)."""
        if self._ticker is not None:
            return
        self._ticker_stop = threading.Event()
        stop = self._ticker_stop

        def loop() -> None:
            while not stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception:
                    pass  # next tick retries; readers still sample-on-read

        self._ticker = threading.Thread(
            target=loop, daemon=True, name="metrics-source-ticker"
        )
        self._ticker.start()

    def stop_background(self) -> None:
        if self._ticker_stop is not None:
            self._ticker_stop.set()
        self._ticker = None
        self._ticker_stop = None


class _TickSampler:
    """Shared sample-on-read scheduling: at most one sample per wall-clock
    tick (``floor(now / interval) * interval``), timestamped AT the tick so
    independent replicas sampling the same ground truth produce identical
    series."""

    def __init__(self, interval_s: float, clock: Callable[[], float]) -> None:
        self.interval_s = interval_s
        self._clock = clock
        self._last_tick = float("-inf")
        self._lock = threading.Lock()

    def due(self) -> float | None:
        """Return the current tick if it still needs sampling, else None."""
        now = self._clock()
        tick = now - (now % self.interval_s)
        with self._lock:
            if tick <= self._last_tick:
                return None
            self._last_tick = tick
            return tick

    def now(self) -> float:
        return self._clock()


class RegistrySource(MetricsSource):
    """Samples in-process reader callables into the server-held store.

    ``readers`` maps metric type → zero-arg callable returning the current
    scalar (e.g. a gauge sum scraped live from the cluster).
    """

    def __init__(
        self,
        readers: Mapping[str, Callable[[], float]],
        *,
        interval_s: float = DEFAULT_INTERVAL_S,
        maxlen: int = DEFAULT_MAXLEN,
        clock: Callable[[], float] = time.time,
        pre_sample: Callable[[], None] | None = None,
    ) -> None:
        self.kind = "registry"
        self.interval_s = interval_s
        self._readers = dict(readers)
        self._store = SeriesStore(maxlen)
        self._sampler = _TickSampler(interval_s, clock)
        self._pre_sample = pre_sample

    def types(self) -> list[str]:
        return sorted(self._readers)

    def sample(self) -> None:
        """Take a sample if the current tick hasn't been taken yet."""
        tick = self._sampler.due()
        if tick is None:
            return
        if self._pre_sample is not None:
            # shared refresh (e.g. one cluster walk feeding every gauge) —
            # without it each reader would redo the walk per sample
            try:
                self._pre_sample()
            except Exception:
                pass
        for mtype, read in self._readers.items():
            try:
                self._store.append(mtype, tick, float(read()))
            except Exception:
                pass  # one broken reader must not starve the others

    def series(self, metric_type: str, window_s: float = 900.0) -> list[dict]:
        if metric_type not in self._readers:
            raise KeyError(metric_type)
        self.sample()
        return self._store.window(metric_type, window_s, self._sampler.now())


# The label block is NOT "anything up to the first }": label values are
# quoted strings with \\ \" \n escapes (utils/metrics.py emits them), so a
# value may legally contain both `}` and escaped quotes. Outside quotes we
# accept anything but a brace or quote; inside, any escaped char or any
# non-quote — the same grammar the exposition writer produces.
_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?:[^{}"]|"(?:\\.|[^"\\])*")*\})?'
    r"\s+(?P<value>[^\s]+)"
)


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Per-family totals from Prometheus text exposition: all samples of a
    family (across label sets) are summed — the dashboard charts cluster
    totals, the per-label breakdown stays on the scrape endpoint. Label
    values containing escaped quotes or `}` (legal since the registry's
    exposition escaping landed) parse correctly instead of truncating the
    sample line mid-label."""
    totals: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        try:
            v = float(m.group("value"))
        except ValueError:
            continue
        totals[m.group("name")] = totals.get(m.group("name"), 0.0) + v
    return totals


class PrometheusSource(MetricsSource):
    """Polls an external Prometheus scrape endpoint into the store.

    ``families`` maps metric type → exposition family name (e.g.
    ``{"notebooks": "notebook_running"}``). ``fetch`` is injectable for
    tests; the default does a GET with a short timeout.
    """

    def __init__(
        self,
        url: str,
        families: Mapping[str, str],
        *,
        interval_s: float = DEFAULT_INTERVAL_S,
        maxlen: int = DEFAULT_MAXLEN,
        clock: Callable[[], float] = time.time,
        fetch: Callable[[str], str] | None = None,
    ) -> None:
        self.kind = "prometheus"
        self.url = url
        self.interval_s = interval_s
        self._families = dict(families)
        self._store = SeriesStore(maxlen)
        self._sampler = _TickSampler(interval_s, clock)
        self._fetch = fetch or self._http_fetch

    @staticmethod
    def _http_fetch(url: str) -> str:
        with urllib.request.urlopen(url, timeout=5) as resp:  # noqa: S310
            return resp.read().decode("utf-8", "replace")

    def types(self) -> list[str]:
        return sorted(self._families)

    def sample(self) -> None:
        tick = self._sampler.due()
        if tick is None:
            return
        try:
            totals = parse_prometheus_text(self._fetch(self.url))
        except Exception:
            return  # endpoint down: the series simply has a gap, like Prom
        for mtype, family in self._families.items():
            if family in totals:
                self._store.append(mtype, tick, totals[family])

    def series(self, metric_type: str, window_s: float = 900.0) -> list[dict]:
        if metric_type not in self._families:
            raise KeyError(metric_type)
        self.sample()
        return self._store.window(metric_type, window_s, self._sampler.now())


def metrics_source_from_env(
    readers: Mapping[str, Callable[[], float]],
    env: Mapping[str, str],
    pre_sample: Callable[[], None] | None = None,
) -> MetricsSource:
    """The reference's metrics_service_factory.ts:24 analog: pick the
    implementation from configuration, defaulting to the in-process source.

    ``METRICS_SOURCE=prometheus`` + ``METRICS_PROMETHEUS_URL=...`` selects
    the external-endpoint source; families map through
    ``METRICS_PROMETHEUS_FAMILIES`` (``type=family,type=family``, default
    the platform's notebook series).
    """
    kind = env.get("METRICS_SOURCE", "registry")
    if kind == "prometheus":
        url = env.get("METRICS_PROMETHEUS_URL")
        if not url:
            raise ValueError(
                "METRICS_SOURCE=prometheus requires METRICS_PROMETHEUS_URL"
            )
        raw = env.get(
            "METRICS_PROMETHEUS_FAMILIES",
            "notebooks=notebook_running,tpus=notebook_tpu_chips_in_use",
        )
        families = dict(
            pair.split("=", 1) for pair in raw.split(",") if "=" in pair
        )
        return PrometheusSource(url, families)
    if kind != "registry":
        raise ValueError(f"unknown METRICS_SOURCE {kind!r}")
    return RegistrySource(readers, pre_sample=pre_sample)
