"""Jupyter web app (JWA) backend: spawner + notebook management REST.

Route/behavior parity with the reference
(``crud-web-apps/jupyter/backend/apps``):

  GET    /api/config                                   (get.py:14-17)
  GET    /api/namespaces/<ns>/notebooks                (get.py:52-57)
  GET    /api/namespaces/<ns>/notebooks/<name>         (get.py:59-62)
  GET    /api/namespaces/<ns>/notebooks/<name>/pod     (get.py:64-77)
  GET    /api/namespaces/<ns>/notebooks/<name>/events  (get.py:89-95)
  GET    /api/namespaces/<ns>/pvcs                     (get.py:20-27)
  GET    /api/namespaces/<ns>/poddefaults              (get.py:29-49)
  GET    /api/tpus                 ← generalizes /api/gpus (get.py:99-120):
         TPU availability = node pools matching (accelerator, topology)
  POST   /api/namespaces/<ns>/notebooks  — form → CR with readOnly guard +
         dry-run-first semantics (post.py:11-73)
  PATCH  /api/namespaces/<ns>/notebooks/<name>  stop/start via the
         kubeflow-resource-stopped annotation (patch.py:18-76)
  DELETE /api/namespaces/<ns>/notebooks/<name>  (delete.py)

Status derivation for the index table follows the reference's CR+events logic
(``apps/common/status.py:9-99``).
"""
from __future__ import annotations

from kubeflow_tpu import scheduler as sched
from kubeflow_tpu import sessions as sess
from kubeflow_tpu.api import types as api
from kubeflow_tpu.obs import timeline as tl
from kubeflow_tpu.auth.rbac import Authorizer
from kubeflow_tpu.controllers.notebook_controller import REWRITE_ANNOTATION
from kubeflow_tpu.culler.culler import format_time
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    parse_topology,
    validate_against_node_capacity,
)
from kubeflow_tpu.utils.metrics import NotebookMetrics
from kubeflow_tpu.webapps import spawner_config
from kubeflow_tpu.webapps import base
from kubeflow_tpu.webapps.base import App, get_json, success
from kubeflow_tpu.webapps.cache import ReadCache

import time


def _blocking_detail(nb: dict) -> str | None:
    """The top blocking verdict from the scheduler's placement explanation
    (scheduler/explain.py), or None when the gang carries none. The
    message already names the verdict's substance — which pools rejected
    the shape and why — and for a merely-fragmented fleet it IS the
    fragmentation hint ("N chips free, largest contiguous block M,
    defragmentation would admit it")."""
    exp = sched.explanation_of(nb)
    if exp is None:
        return None
    return exp.get("message") or exp.get("reason")


def _capacity_pending_detail(nb: dict, capacity) -> str | None:
    """The autoscaler's "chips are on their way" line for an unbound gang:
    shown instead of a bare Unschedulable (and appended to a queued row)
    when an open scale-up request covers the gang's family. The ETA is the
    time-to-first-chip p50 — the SLO the dashboard charts — so the spawner
    promises what the platform actually delivers."""
    if capacity is None:
        return None
    topo = api.notebook_topology(nb)
    if topo is None:
        return None
    try:
        pending = capacity.pending_for(topo.accelerator.name)
    except Exception:
        return None  # a provider hiccup must never 500 the listing
    if not pending:
        return None
    detail = f"capacity pending — provisioning {pending['chips']} chips"
    eta = pending.get("etaS")
    if eta:
        detail += f", ETA ~{eta:.0f}s from time-to-first-chip p50"
    return detail


def notebook_status(nb: dict, events: list[dict], capacity=None) -> dict:
    """Derive UI status (ref status.py:9-99), extended with the fleet
    scheduler's conditions — a queued gang says WHERE it is in line instead
    of a generic "pending", an unschedulable one says why it never will be —
    and the session lifecycle (sessions/): a suspending gang says its work
    is being snapshotted, a suspended one that resume restores it, a
    resuming one that the snapshot is loading."""
    anns = ko.annotations(nb)
    ready = nb.get("status", {}).get("readyReplicas", 0)
    topo = api.notebook_topology(nb)
    expected = (
        topo.num_hosts * api.notebook_num_slices(nb) if topo else 1
    )
    state = sess.session_state(nb)
    snapshot = sess.snapshot_record(nb)
    if api.STOP_ANNOTATION in anns:
        if state == sess.STATE_SUSPENDING or (
            ready > 0 and sess.suspend_request(nb) is not None
            and snapshot is None
        ):
            return {
                "phase": "terminating",
                "message": "Suspending: snapshotting session state "
                           "before scaling down.",
            }
        if ready == 0:
            if snapshot is not None:
                return {
                    "phase": "suspended",
                    "message": "Suspended. Starting the server resumes "
                               "from the saved session snapshot.",
                }
            return {"phase": "stopped", "message": "No Pods are currently running."}
        return {"phase": "terminating", "message": "Notebook Server is stopping."}
    if ready >= expected:
        return {"phase": "ready", "message": "Running"}
    unsched = sched.condition(nb, sched.COND_UNSCHEDULABLE)
    if unsched is not None and unsched.get("status") == "True":
        pending = _capacity_pending_detail(nb, capacity)
        if pending is not None:
            # the autoscaler already acted on this verdict: the honest
            # status is "chips are coming", not a dead-end warning
            return {"phase": "waiting", "message": f"{pending}."}
        # the top blocking verdict from the scheduler's explanation
        # annotation, not the generic string: "why not" is the product
        # surface here (a malformed/absent annotation falls back to the
        # condition message — the UI never 500s on a user-edited CR)
        return {
            "phase": "warning",
            "message": f"Unschedulable: {_blocking_detail(nb) or unsched.get('message') or 'no fitting node pool'}",
        }
    queued = sched.condition(nb, sched.COND_QUEUED)
    if queued is not None and queued.get("status") == "True":
        detail = queued.get("message") or "waiting for capacity"
        message = f"Queued for TPU capacity ({detail})."
        pending = _capacity_pending_detail(nb, capacity)
        if pending:
            message += f" {pending[0].upper()}{pending[1:]}."
        preempted = sched.condition(nb, sched.COND_PREEMPTED)
        if preempted is not None and preempted.get("status") == "True":
            message = (
                f"Preempted ({preempted.get('message') or 'by a higher-priority gang'}); "
                f"re-queued ({detail})."
            )
        blocking = _blocking_detail(nb)
        if blocking:
            # a queued gang the pack phase judged and failed (blocked head,
            # failed backfill, a re-queued victim still waiting): the
            # verdict rides along AFTER the position/preemption text —
            # "position N of M" stays exactly as today for every queued row
            message += f" Blocked: {blocking}."
        if state == sess.STATE_RESUMING or (
            state == sess.STATE_SUSPENDED and snapshot is not None
        ):
            # queue wait first, restore after: both facts on one line
            message += " Session snapshot will be restored on start."
        return {"phase": "waiting", "message": message}
    if state in (sess.STATE_RESUMING, sess.STATE_SUSPENDED):
        return {
            "phase": "resuming",
            "message": "Resuming: restoring the saved session snapshot."
            if snapshot is not None
            else "Resuming (no snapshot was saved; starting fresh).",
        }
    warnings = [e for e in events if e.get("type") == "Warning"]
    if warnings:
        return {"phase": "warning", "message": warnings[-1].get("message", "")}
    return {"phase": "waiting", "message": "Starting Notebook Server."}


def _spmd_payload(nb: dict) -> dict | None:
    """Derived-mesh detail for a TPU notebook; None for CPU / invalid specs.

    Same derivation the controller stamps on pod templates and the pods
    build at bootstrap (``spmd/mesh.py``): placement-first, spec fallback —
    so the detail page shows what the gang will ACTUALLY build.
    """
    from kubeflow_tpu.spmd import mesh as spmd_mesh

    try:
        topo = api.notebook_topology(nb)
    except ValueError:
        return None
    if topo is None:
        return None
    num_slices = api.notebook_num_slices(nb)
    placement = sched.placement_of(nb)
    slices = (placement or {}).get("slices") or []
    dm = None
    if slices:
        try:
            dm = spmd_mesh.from_placement_slice(slices[0], num_slices)
        except ValueError:
            dm = None
    if dm is None:
        dm = spmd_mesh.from_topology(topo, num_slices)
    out = dm.to_dict()
    out["bound"] = bool(slices)
    return out


def notebook_summary(nb: dict, events: list[dict], capacity=None) -> dict:
    """Index-table row (ref utils.notebook_dict_from_k8s_obj)."""
    # guard: CRs created out-of-band (kubectl) may omit containers entirely;
    # one malformed CR must not 500 the whole namespace listing
    pod_spec = nb.get("spec", {}).get("template", {}).get("spec", {})
    container = (pod_spec.get("containers") or [{}])[0]
    topo = api.notebook_topology(nb)
    tpu = topo.to_dict() if topo else None
    if tpu and api.notebook_num_slices(nb) > 1:
        tpu["numSlices"] = api.notebook_num_slices(nb)
    return {
        "name": ko.name(nb),
        "namespace": ko.namespace(nb),
        "serverType": ko.annotations(nb).get(api.SERVER_TYPE_ANNOTATION, "jupyter"),
        "image": container.get("image"),
        "cpu": container.get("resources", {}).get("requests", {}).get("cpu"),
        "memory": container.get("resources", {}).get("requests", {}).get("memory"),
        "tpu": tpu,
        "status": notebook_status(nb, events, capacity),
        "volumes": [v.get("name") for v in pod_spec.get("volumes", [])],
        "lastActivity": ko.annotations(nb).get(api.LAST_ACTIVITY_ANNOTATION, ""),
    }


JWA_KINDS = (
    "Notebook", "Event", "Node", "Pod", "PersistentVolumeClaim", "PodDefault",
)


def create_app(
    cluster: FakeCluster,
    *,
    authorizer: Authorizer | None = None,
    config_path: str | None = None,
    metrics: NotebookMetrics | None = None,
    telemetry=None,
    gang=None,
    profiler=None,
    timeline=None,
    ledger=None,
    capacity=None,
    cache: ReadCache | None = None,
    use_cache: bool = True,
) -> App:
    metrics = metrics or NotebookMetrics()
    app = App(
        "jupyter-web-app",
        authorizer=authorizer or Authorizer(cluster),
        metrics_registry=metrics.registry,
    )
    # watch-backed read layer (webapps/cache.py): every GET below serves
    # from replicated in-memory state, never the authoritative store; an
    # injected cache (standalone: one cache shared by every app) is reused,
    # use_cache=False keeps the direct O(fleet) reads (the loadtest's
    # uncached A/B arm)
    if cache is not None:
        cache.ensure_kinds(JWA_KINDS)
    elif use_cache:
        cache = ReadCache(
            cluster, JWA_KINDS, metrics=app.web_metrics
        ).start()
        app.on_close(cache.close)

    def _etag(*scopes, principal=None, extra=""):
        if cache is None:
            return None
        return cache.etag(*scopes, principal=principal, extra=extra)

    def _tel_extra() -> str:
        # telemetry/timeline/ledger/capacity payloads change without any CR
        # rv moving; the collector's pass counter, the ledger's tick
        # counter, and the autoscaler's open-request/first-chip state fold
        # that freshness into the ETag
        tel = telemetry if telemetry is not None else getattr(
            timeline, "telemetry", None
        )
        parts = []
        if tel is not None:
            parts.append(f"tel:{getattr(tel, 'scrape_passes', 0)}")
        if gang is not None:
            parts.append(f"gang:{getattr(gang, 'scrape_passes', 0)}")
        if profiler is not None:
            parts.append(f"prof:{getattr(profiler, 'capture_passes', 0)}")
        if ledger is not None:
            parts.append(f"led:{getattr(ledger, 'ticks', 0)}")
        cap = _cap_extra()
        if cap:
            parts.append(cap)
        return ",".join(parts)

    def _cap_extra() -> str:
        # the list row's "capacity pending" message moves with the
        # autoscaler's state generation — bumped by its cycle whenever the
        # open-request set, the provider's pending set, or a first-chip
        # delivery changes (and nothing chattier: the ledger's every-tick
        # counter would defeat the list route's 304s). The generation also
        # covers the restart window where pending_for() answers from
        # provider.pending() while the in-memory open set is empty.
        if capacity is None:
            return ""
        return f"cap:{getattr(capacity, 'state_gen', 0)}"

    app.attach_frontend("jupyter")
    base.add_namespaces_route(app, cluster)

    @app.route("/api/config")
    def get_config(request):
        app.current_user(request)  # authn like every sibling route
        return success("config", spawner_config.load_config(config_path))

    @app.route("/api/tpus")
    def get_tpus(request):
        """Available (accelerator, topology) pairs probed from node capacity —
        the TPU generalization of the reference's GPU vendor intersection."""
        app.current_user(request)  # node capacity is cluster-internal info
        config = spawner_config.load_config(config_path)
        tpu_cfg = config["spawnerFormDefaults"].get("tpu", {})
        all_nodes = None  # lazy: only listed when an accel needs the scan
        available = []
        for accel in tpu_cfg.get("accelerators", []):
            known = ACCELERATORS.get(accel["name"])
            if cache is not None and known is not None:
                # nodes-by-accelerator index: probe only this generation's
                # pool instead of re-listing every Node per click
                nodes = cache.nodes_for_accelerator(known.gke_accelerator)
            else:
                if all_nodes is None:
                    all_nodes = (
                        cache.list("Node") if cache is not None
                        else cluster.list("Node")
                    )
                nodes = all_nodes
            topologies = [
                t for t in accel.get("topologies", [])
                if validate_against_node_capacity(
                    parse_topology(accel["name"], t), nodes
                )
            ]
            if topologies:
                available.append(
                    {"name": accel["name"], "topologies": topologies}
                )
        return success("tpus", available)

    @app.route("/api/namespaces/<namespace>/notebooks")
    def list_notebooks(request, namespace):
        user = app.ensure(request, "list", "notebooks", namespace)
        # the UI polls this route; revalidation first — a matching
        # If-None-Match skips the whole join+serialize for a 304
        etag = _etag(
            ("Notebook", namespace), ("Event", namespace),
            principal=user.name, extra=_cap_extra(),
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        if cache is not None:
            # involved-object index: per-notebook event lookups, not an
            # O(events x notebooks) namespace join per render (copy=False:
            # summary building only reads)
            out = [
                notebook_summary(
                    nb, cache.events_for(nb, principal=user.name, copy=False),
                    capacity,
                )
                for nb in cache.list(
                    "Notebook", namespace, principal=user.name, copy=False
                )
            ]
        else:
            # one Events list per render, grouped by object — not one per
            # notebook (N+1 against the real API server at poll cadence)
            events_by_name: dict[str, list] = {}
            for ev in cluster.list("Event", namespace):
                io = ev.get("involvedObject", {})
                if io.get("kind") == "Notebook":
                    events_by_name.setdefault(io.get("name", ""), []).append(ev)
            out = [
                notebook_summary(
                    nb, events_by_name.get(ko.name(nb), []), capacity
                )
                for nb in cluster.list("Notebook", namespace)
            ]
        return base.set_etag(success("notebooks", out), etag)

    @app.route("/api/namespaces/<namespace>/notebooks/<name>")
    def get_notebook(request, namespace, name):
        """Detail-page payload: the index summary enriched with the CR's
        conditions/age (ref notebook-page overview tab) plus the raw CR."""
        user = app.ensure(request, "get", "notebooks", namespace)
        etag = _etag(
            ("Notebook", namespace), ("Event", namespace),
            principal=user.name, extra=_tel_extra(),
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        if cache is not None:
            nb = cache.get("Notebook", name, namespace, principal=user.name)
            events = cache.events_for(nb, principal=user.name)
        else:
            nb = cluster.get("Notebook", name, namespace)
            events = cluster.events_for(nb)
        summary = notebook_summary(nb, events, capacity)
        summary["status"]["conditions"] = nb.get("status", {}).get(
            "conditions", []
        )
        # the full decoded placement explanation (scheduler/explain.py) on
        # the overview tab: per-pool verdicts, fragmentation indices, the
        # preemption trail — None for a bound/unexplained notebook, so the
        # UI can distinguish "placed" from "never judged"
        summary["explanation"] = sched.explanation_of(nb)
        # the derived SPMD mesh (spmd/mesh.py rule) for TPU notebooks: the
        # axes every host of the gang will build (dcn/data/model), from the
        # bound placement's cuboid when one exists — the detail-page answer
        # to "what mesh does my notebook get". None for CPU notebooks.
        summary["spmd"] = _spmd_payload(nb)
        summary["age"] = nb["metadata"].get("creationTimestamp", "")
        # keep CR status fields reachable (status.tpu incl. numSlices)
        summary["status"].update(
            {
                k: v
                for k, v in (nb.get("status") or {}).items()
                if k not in ("conditions",)
            }
        )
        # the event stream ON the detail payload (not just /events): the
        # controllers now record Created/Bound/Queued/Preempted/Culled with
        # dedup counts — the "what happened to my notebook" timeline the
        # overview tab renders without a second round trip
        summary["events"] = [
            {
                "reason": e.get("reason", ""),
                "message": e.get("message", ""),
                "type": e.get("type", "Normal"),
                "count": e.get("count", 1),
                "firstTimestamp": e.get("firstTimestamp", ""),
                "lastTimestamp": e.get("lastTimestamp", ""),
                "source": (e.get("source") or {}).get("component", ""),
            }
            for e in sorted(
                events, key=lambda e: (e.get("lastTimestamp") or "",
                                       e.get("metadata", {}).get("name", ""))
            )
        ]
        if telemetry is not None:
            # device telemetry on the detail payload (telemetry/): current
            # duty cycle + HBM with freshness and the recent series — the
            # "is my slice actually working" answer next to the status.
            # None (vs absent) for a session the collector has never seen,
            # so the UI can distinguish "no agent" from "telemetry off".
            summary["telemetry"] = telemetry.session_payload(namespace, name)
        if gang is not None:
            # gang step telemetry (telemetry/gang.py): per-host step
            # timeline, skew/straggler verdict, and the named culprit —
            # the "which host is dragging my gang" answer. None for a
            # single-host session or one the aggregator has never scraped.
            summary["gang"] = gang.gang_payload(namespace, name)
        if profiler is not None:
            # finding-triggered captures (obs/profiler.py): what the
            # platform traced when this gang's findings froze — capture
            # status, the culprit + reference hosts, and the TensorBoard
            # logdirs the traces render under. None for a gang never
            # captured, so the UI can distinguish "healthy" from
            # "profiler off".
            summary["profiles"] = profiler.profiles_payload(namespace, name)
        if timeline is not None:
            # the click-to-ready timeline (obs/timeline.py): per-phase
            # attribution of this session's startup — "which layer ate the
            # time" rendered right on the overview tab
            summary["timeline"] = timeline.build(namespace, name)
        if ledger is not None:
            # the efficiency ledger's per-notebook account (obs/ledger.py):
            # where this session's chip-time went (busy vs idle vs barrier
            # windows) and its busy/allocated ratio — the same registry
            # families the dashboard's fleet series roll up. None for a
            # session the ledger never attributed an interval to, so the
            # UI can distinguish "new session" from "ledger off".
            summary["efficiency"] = ledger.notebook_payload(namespace, name)
        return base.set_etag(success("notebook", summary, raw=nb), etag)

    @app.route("/api/namespaces/<namespace>/notebooks/<name>/pod")
    def get_notebook_pod(request, namespace, name):
        user = app.ensure(request, "get", "pods", namespace)
        pods = (
            cache.pods_for_notebook(namespace, name, principal=user.name)
            if cache is not None
            else cluster.list(
                "Pod", namespace, {"matchLabels": {"notebook-name": name}}
            )
        )
        if not pods:
            from werkzeug.exceptions import NotFound

            raise NotFound("No pod detected.")
        return success("pod", pods[0], pods=pods)  # all gang pods for TPU view

    @app.route(
        "/api/namespaces/<namespace>/notebooks/<name>/pod/<pod>/logs"
    )
    def get_pod_logs(request, namespace, name, pod):
        # ref crud_backend/api/pod.py: authorize the pods/log subresource
        # (not just pod read) and return only the notebook container's logs —
        # sidecar (istio-proxy/oauth-proxy) logs must not leak to users.
        user = app.ensure(request, "get", "pods/log", namespace)
        pods = (
            cache.pods_for_notebook(namespace, name, principal=user.name)
            if cache is not None
            else cluster.list(
                "Pod", namespace, {"matchLabels": {"notebook-name": name}}
            )
        )
        if not any(ko.name(p) == pod for p in pods):
            from werkzeug.exceptions import NotFound

            raise NotFound(f"Pod {pod} is not part of notebook {name}.")
        text = cluster.pod_logs(pod, namespace, container=name)
        return success("logs", text.splitlines())

    @app.route("/api/namespaces/<namespace>/notebooks/<name>/events")
    def get_notebook_events(request, namespace, name):
        user = app.ensure(request, "list", "events", namespace)
        etag = _etag(
            ("Notebook", namespace), ("Event", namespace),
            principal=user.name,
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        if cache is not None:
            nb = cache.get("Notebook", name, namespace, principal=user.name)
            events = cache.events_for(nb, principal=user.name)
        else:
            nb = cluster.get("Notebook", name, namespace)
            events = cluster.events_for(nb)
        return base.set_etag(success("events", events), etag)

    @app.route("/api/namespaces/<namespace>/pvcs")
    def list_pvcs(request, namespace):
        user = app.ensure(request, "list", "persistentvolumeclaims", namespace)
        etag = _etag(
            ("PersistentVolumeClaim", namespace), principal=user.name
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        pvcs = (
            cache.list(
                "PersistentVolumeClaim", namespace,
                principal=user.name, copy=False,
            )
            if cache is not None
            else cluster.list("PersistentVolumeClaim", namespace)
        )
        out = [
            {
                "name": ko.name(pvc),
                "size": pvc.get("spec", {}).get("resources", {}).get("requests", {}).get("storage"),
                "mode": (pvc.get("spec", {}).get("accessModes") or [None])[0],
            }
            for pvc in pvcs
        ]
        return base.set_etag(success("pvcs", out), etag)

    @app.route("/api/namespaces/<namespace>/poddefaults")
    def list_poddefaults(request, namespace):
        user = app.ensure(request, "list", "poddefaults", namespace)
        etag = _etag(("PodDefault", namespace), principal=user.name)
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        pds = (
            # copy=False: the loop below deep-copies each pd itself before
            # decorating it
            cache.list(
                "PodDefault", namespace, principal=user.name, copy=False
            )
            if cache is not None
            else cluster.list("PodDefault", namespace)
        )
        out = []
        for pd in pds:
            labels = pd["spec"].get("selector", {}).get("matchLabels", {})
            pd = ko.deep_copy(pd)
            pd["label"] = next(iter(labels), "")
            pd["desc"] = pd["spec"].get("desc") or ko.name(pd)
            out.append(pd)
        return base.set_etag(success("poddefaults", out), etag)

    @app.route("/api/namespaces/<namespace>/notebooks", methods=("POST",))
    def post_notebook(request, namespace):
        user = app.ensure(request, "create", "notebooks", namespace)
        body = get_json(request, "name")
        defaults = spawner_config.load_config(config_path)
        nb, new_pvcs = build_notebook(body, namespace, defaults, user.name)
        # origin propagation (obs/timeline.py): the request trace id and
        # the click time ride the CR, so reconcile spans, scheduler bind
        # writes, and the startup timeline all link back to this POST
        ko.set_annotation(nb, tl.REQUEST_ID_ANNOTATION, base.request_id(request))
        ko.set_annotation(
            nb, tl.TIMELINE_ANNOTATION,
            tl.encode_marks({"requestedAt": time.time()}),
        )

        # dry-run everything first (ref post.py:48-54): all-or-nothing UX
        api_errors = api.validate_notebook(nb)
        if api_errors:
            raise ValueError("; ".join(api_errors))
        if cluster.try_get("Notebook", ko.name(nb), namespace):
            raise ValueError(f"Notebook {ko.name(nb)} already exists")
        for pvc in new_pvcs:
            if cluster.try_get("PersistentVolumeClaim", ko.name(pvc), namespace):
                raise ValueError(f"PVC {ko.name(pvc)} already exists")

        for pvc in new_pvcs:
            stored_pvc = cluster.create(pvc)
            if cache is not None:
                cache.note_write(stored_pvc, principal=user.name)
        stored = cluster.create(nb)
        if cache is not None:
            # read-your-writes: the committed CR lands in the cache NOW and
            # the creating session is pinned to its rv — the spawner's
            # immediate redirect-to-list must show the new notebook even if
            # the watch stream is down
            cache.note_write(stored, principal=user.name)
        metrics.notebook_created(namespace)
        return success("message", "Notebook created successfully.")

    @app.route(
        "/api/namespaces/<namespace>/notebooks/<name>", methods=("PATCH",)
    )
    def patch_notebook(request, namespace, name):
        user = app.ensure(request, "patch", "notebooks", namespace)
        body = get_json(request)
        nb = cluster.get("Notebook", name, namespace)
        if "stopped" in body:
            # ref patch.py:18-76
            if body["stopped"]:
                ko.set_annotation(nb, api.STOP_ANNOTATION, format_time(time.time()))
                ko.remove_annotation(nb, api.LAST_ACTIVITY_ANNOTATION)
            else:
                # a restart of a STOPPED notebook is a new click: fresh
                # timeline generation with this request as its origin (the
                # controller cleared the previous generation's marks at
                # teardown). A stopped=false on an already-running notebook
                # (client retry/double-send) is a no-op — overwriting the
                # live generation would wipe its marks and make the next
                # reconcile observe a fake ~0s start into the SLO.
                if api.STOP_ANNOTATION in ko.annotations(nb):
                    ko.set_annotation(
                        nb, tl.REQUEST_ID_ANNOTATION,
                        base.request_id(request),
                    )
                    ko.set_annotation(
                        nb, tl.TIMELINE_ANNOTATION,
                        tl.encode_marks({"requestedAt": time.time()}),
                    )
                ko.remove_annotation(nb, api.STOP_ANNOTATION)
            stored = cluster.update(nb)
            if cache is not None:
                cache.note_write(stored, principal=user.name)
        return success("message", "Notebook updated")

    @app.route(
        "/api/namespaces/<namespace>/notebooks/<name>", methods=("PUT",)
    )
    def put_notebook(request, namespace, name):
        """Editable-YAML apply (detail page's editor tab): the full edited
        CR replaces the stored spec, authz'd as update, schema-checked, with
        ?dryRun=true validating without persisting."""
        user = app.ensure(request, "update", "notebooks", namespace)
        return base.handle_cr_put(
            request, cluster, "Notebook", name, namespace,
            validate=api.validate_notebook,
            cache=cache, principal=user.name,
        )

    @app.route(
        "/api/namespaces/<namespace>/notebooks/<name>", methods=("DELETE",)
    )
    def delete_notebook(request, namespace, name):
        user = app.ensure(request, "delete", "notebooks", namespace)
        cluster.delete("Notebook", name, namespace)
        if cache is not None:
            cache.note_delete(
                "Notebook", name, namespace, principal=user.name
            )
        return success("message", "Notebook deleted")

    return app


def _cpu_value(s: str) -> float:
    s = str(s).strip()
    return float(s[:-1]) / 1000.0 if s.endswith("m") else float(s)


# binary suffixes first: "Gi" must match before "G"
_MEM_UNITS = {
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
    "Ei": 2**60,
    "k": 10**3, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
    "P": 10**15, "E": 10**18,
}


def _mem_bytes(s: str) -> float:
    s = str(s).strip()
    for unit, mult in _MEM_UNITS.items():
        if s.endswith(unit):
            return float(s[: -len(unit)]) * mult
    return float(s)


def compute_limit(request: str, explicit, factor, *, kind: str) -> str | None:
    """Resource limit per the reference's set_notebook_cpu/memory
    (form.py:117-175): an explicit limit wins (a limit below the request is
    a 400); else request * limitFactor (config), clamped to never round
    below the request; limitFactor 'none'/absent means no scaling — limits
    fall back to the request (Guaranteed QoS)."""
    value = _cpu_value if kind == "cpu" else _mem_bytes
    if explicit not in (None, ""):
        if value(explicit) < value(request):
            raise ValueError(
                f"{kind} limit {explicit!r} must be at least the request "
                f"{request!r}"
            )
        return str(explicit)
    if factor in (None, "", "none"):
        return None
    f = float(factor)
    if kind == "cpu":
        scaled = str(round(_cpu_value(request) * f, 3))
    else:
        # preserve the request's unit (ref assumes Gi; we scale in place)
        s = str(request).strip()
        for unit in _MEM_UNITS:
            if s.endswith(unit):
                scaled = str(round(float(s[: -len(unit)]) * f, 2)) + unit
                break
        else:
            scaled = str(round(float(s) * f))
    # rounding can land a hair under the request (e.g. factor 1.0 on
    # 1.555Gi): the request itself is the floor, never an error
    return str(scaled) if value(scaled) >= value(request) else str(request)


def _resolve_option(body: dict, defaults: dict, field: str, id_key: str) -> dict | None:
    """Look up the form's keyed choice in the config section's options list
    (shared shape of tolerationGroup and affinityConfig, ref form.py:178-223).
    Returns None for "none"; raises for a key absent from the config — the
    reference only logs a warning there, but a silently dropped scheduling
    constraint is worse than a 400."""
    key = spawner_config.form_value(body, defaults, field)
    if not key or key == "none":
        return None
    options = (
        defaults.get("spawnerFormDefaults", {}).get(field, {}).get("options", [])
    )
    for option in options:
        if option.get(id_key) == key:
            return ko.deep_copy(option)
    raise ValueError(f"No {field} option with key {key!r} in the config")


def set_notebook_tolerations(nb: dict, body: dict, defaults: dict) -> None:
    """tolerationGroup → pod tolerations (ref form.py:178-198)."""
    group = _resolve_option(body, defaults, "tolerationGroup", "groupKey")
    if group is None:
        return
    pod_spec = nb["spec"]["template"]["spec"]
    pod_spec.setdefault("tolerations", []).extend(group.get("tolerations", []))


def set_notebook_affinity(nb: dict, body: dict, defaults: dict) -> None:
    """affinityConfig → pod affinity (ref form.py:201-223). Schema extension
    over the reference: an option may also carry ``tolerations``, applied
    together with the affinity — a node-targeting affinity (e.g. TPU pools)
    is unschedulable without the matching taint toleration, so the two must
    ship as one choice."""
    cfg = _resolve_option(body, defaults, "affinityConfig", "configKey")
    if cfg is None:
        return
    pod_spec = nb["spec"]["template"]["spec"]
    pod_spec["affinity"] = cfg.get("affinity", {})
    if cfg.get("tolerations"):
        pod_spec.setdefault("tolerations", []).extend(cfg["tolerations"])


def build_notebook(body: dict, namespace: str, defaults: dict, creator: str) -> tuple[dict, list[dict]]:
    """Assemble the Notebook CR from the form (ref form.py + post.py flow),
    honoring readOnly config fields, plus TPU topology validation."""
    fv = spawner_config.form_value
    name = body["name"]

    tpu = fv(body, defaults, "tpu") or {}
    accelerator = tpu.get("accelerator") or "none"
    tpu_kwargs = {}
    if accelerator != "none":
        raw_slices = tpu.get("numSlices")
        if raw_slices in (None, ""):
            raw_slices = 1
        try:
            num_slices = int(raw_slices)
        except (TypeError, ValueError):
            raise ValueError(
                f"tpu.numSlices must be a positive integer, got {raw_slices!r}"
            )
        # api.notebook rejects < 1 too, but erroring here names the FORM
        # field (the old `or 1` silently ran numSlices=0 as a single slice)
        if num_slices < 1:
            raise ValueError(
                f"tpu.numSlices must be a positive integer, got {raw_slices!r}"
            )
        tpu_kwargs = {
            "tpu_accelerator": accelerator,
            "tpu_topology": tpu.get("topology", ""),
            "tpu_num_slices": num_slices,
        }

    server_type = fv(body, defaults, "serverType")
    annotations = {
        api.CREATOR_ANNOTATION: creator,
        api.SERVER_TYPE_ANNOTATION: server_type,
    }
    if server_type in ("codeserver", "rstudio"):
        # these servers cannot serve under an arbitrary prefix; the
        # VirtualService rewrites /notebook/<ns>/<name>/ -> / for them
        # (ref JWA form.py sets the same rewrite annotations)
        annotations[REWRITE_ANNOTATION] = "/"
    cpu = str(fv(body, defaults, "cpu"))
    memory = str(fv(body, defaults, "memory"))
    sections = defaults.get("spawnerFormDefaults", {})
    # limits go through form_value too: a readOnly cpuLimit/memoryLimit
    # config section pins them like any other field (the request being
    # readOnly while its limit is user-writable would defeat the pin)
    cpu_limit = compute_limit(
        cpu, fv(body, defaults, "cpuLimit", optional=True),
        sections.get("cpu", {}).get("limitFactor"), kind="cpu",
    )
    memory_limit = compute_limit(
        memory, fv(body, defaults, "memoryLimit", optional=True),
        sections.get("memory", {}).get("limitFactor"), kind="memory",
    )
    nb = api.notebook(
        name,
        namespace,
        image=fv(body, defaults, "image"),
        cpu=cpu,
        memory=memory,
        cpu_limit=cpu_limit,
        memory_limit=memory_limit,
        annotations=annotations,
        labels={c: "true" for c in fv(body, defaults, "configurations") or []},
        **tpu_kwargs,
    )
    nb["spec"]["template"]["spec"]["serviceAccountName"] = "default-editor"

    pod_spec = nb["spec"]["template"]["spec"]
    container = pod_spec["containers"][0]

    # imagePullPolicy → container (ref form.py:86-92 set_notebook_image_pull_policy)
    pull_policy = fv(body, defaults, "imagePullPolicy")
    if pull_policy:
        if pull_policy not in ("Always", "IfNotPresent", "Never"):
            raise ValueError(f"Invalid imagePullPolicy: {pull_policy!r}")
        container["imagePullPolicy"] = pull_policy

    # tolerationGroup → pod tolerations (ref form.py:178-198): the form carries
    # a groupKey; the config's options list maps it to concrete tolerations.
    set_notebook_tolerations(nb, body, defaults)
    # affinityConfig → pod affinity (ref form.py:201-223)
    set_notebook_affinity(nb, body, defaults)
    new_pvcs: list[dict] = []
    volumes = []
    mounts = []

    # Missing form fields fall back to the config default (the spawner UI
    # pre-fills them from /api/config; API callers get the same defaults).
    workspace = fv(body, defaults, "workspace", "workspaceVolume")
    if body.get("workspace") is None and "workspace" in body:
        workspace = None  # explicit null = "no workspace volume"
    datavols = fv(body, defaults, "datavols", "dataVolumes") or []
    for vol in ([workspace] if workspace else []) + list(datavols):
        vol = ko.deep_copy(vol)
        new_pvc = vol.get("newPvc")
        if new_pvc:
            pvc_name = (
                new_pvc.get("metadata", {}).get("name", f"{name}-vol")
                .replace("{notebook-name}", name)
            )
            pvc = {
                "apiVersion": "v1",
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": pvc_name, "namespace": namespace},
                "spec": ko.deep_copy(new_pvc.get("spec", {})),
            }
            new_pvcs.append(pvc)
        else:
            pvc_name = vol.get("existingSource", vol.get("name", ""))
        vol_name = pvc_name
        volumes.append(
            {"name": vol_name, "persistentVolumeClaim": {"claimName": pvc_name}}
        )
        mounts.append({"name": vol_name, "mountPath": vol.get("mount", "/data")})

    if fv(body, defaults, "shm"):
        volumes.append({"name": "dshm", "emptyDir": {"medium": "Memory"}})
        mounts.append({"name": "dshm", "mountPath": "/dev/shm"})
    if volumes:
        pod_spec["volumes"] = volumes
        container["volumeMounts"] = mounts
    return nb, new_pvcs
