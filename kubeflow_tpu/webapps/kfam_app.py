"""Access-management REST service (kfam).

Route parity with the reference (``access-management/kfam/routers.go:32-88``):

  GET/POST/DELETE /kfam/v1/bindings
  GET/POST/DELETE /kfam/v1/profiles[/<name>]
  GET             /kfam/v1/role/clusteradmin

Contributor management rule (ref api_default.go): only the profile owner or a
cluster admin may add/remove contributors in a namespace.
"""
from __future__ import annotations

from kubeflow_tpu.auth.kfam import BindingClient, ProfileClient
from kubeflow_tpu.auth.rbac import Forbidden
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.webapps.base import App, get_json, success


def create_app(
    cluster: FakeCluster,
    *,
    userid_header: str = "kubeflow-userid",
    userid_prefix: str = "",
    cluster_admins: set[str] | None = None,
) -> App:
    app = App(
        "kfam", userid_header=userid_header, userid_prefix=userid_prefix
    )
    bindings = BindingClient(
        cluster, userid_header=userid_header, userid_prefix=userid_prefix
    )
    profiles = ProfileClient(cluster, cluster_admins=cluster_admins)

    def _can_manage(user: str, namespace: str) -> bool:
        if profiles.is_cluster_admin(user):
            return True
        prof = cluster.try_get("Profile", namespace)
        return (
            prof is not None
            and prof.get("spec", {}).get("owner", {}).get("name") == user
        )

    @app.route("/kfam/v1/bindings")
    def list_bindings(request):
        app.current_user(request)
        ns = request.args.get("namespace")
        return success(
            "bindings",
            bindings.list(
                user=request.args.get("user", ""),
                namespaces=[ns] if ns else None,
                role=request.args.get("role", ""),
            ),
        )

    @app.route("/kfam/v1/bindings", methods=("POST",))
    def create_binding(request):
        user = app.current_user(request)
        body = get_json(request, "user", "referredNamespace", "roleRef")
        ns = body["referredNamespace"]
        if not _can_manage(user.name, ns):
            raise Forbidden(
                f"User '{user.name}' may not manage contributors in '{ns}'"
            )
        bindings.create(body["user"], ns, body["roleRef"]["name"])
        return success("message", "Binding created")

    @app.route("/kfam/v1/bindings", methods=("DELETE",))
    def delete_binding(request):
        user = app.current_user(request)
        body = get_json(request, "user", "referredNamespace", "roleRef")
        ns = body["referredNamespace"]
        if not _can_manage(user.name, ns):
            raise Forbidden(
                f"User '{user.name}' may not manage contributors in '{ns}'"
            )
        bindings.delete(body["user"], ns, body["roleRef"]["name"])
        return success("message", "Binding deleted")

    @app.route("/kfam/v1/profiles", methods=("POST",))
    def create_profile(request):
        user = app.current_user(request)
        body = get_json(request, "metadata", "spec")
        profile = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "Profile",
            "metadata": {"name": body["metadata"]["name"]},
            "spec": body["spec"],
        }
        owner = profile["spec"].get("owner", {}).get("name")
        if owner != user.name and not profiles.is_cluster_admin(user.name):
            raise Forbidden("may only create a profile owned by yourself")
        profiles.create(profile)
        return success("message", "Profile created")

    @app.route("/kfam/v1/profiles/<name>")
    def get_profile(request, name):
        app.current_user(request)
        return success("profile", profiles.get(name))

    @app.route("/kfam/v1/profiles/<name>", methods=("DELETE",))
    def delete_profile(request, name):
        user = app.current_user(request)
        if not _can_manage(user.name, name):
            raise Forbidden(f"User '{user.name}' may not delete profile '{name}'")
        profiles.delete(name)
        return success("message", "Profile deleted")

    @app.route("/kfam/v1/role/clusteradmin")
    def cluster_admin(request):
        user = app.current_user(request)
        return success("role", profiles.is_cluster_admin(user.name))

    return app
