"""TPU-native notebook platform."""
