"""Spawner form configuration with value/readOnly semantics.

The reference drives its spawner form from a ConfigMap-mounted YAML where
every field carries ``value`` + ``readOnly``
(``apps/common/yaml/spawner_ui_config.yaml:1-17``; loader fallback chain
``apps/common/utils.py:22-53``). Same contract here, with the GPU vendor
section (``spawner_ui_config.yaml:113-126``) replaced by a first-class **TPU
topology picker**: the form offers validated (accelerator, topology) pairs and
the backend cross-checks them against live node capacity — no free-typed
resource-limit strings.
"""
from __future__ import annotations

import os
from typing import Any, Mapping

import yaml


CONFIG_PATH_ENV = "SPAWNER_UI_CONFIG"
DEFAULT_CONFIG_PATH = "/etc/config/spawner_ui_config.yaml"

DEFAULT_CONFIG: dict = {
    "spawnerFormDefaults": {
        "image": {
            "value": "kubeflow-tpu/jupyter-jax:latest",
            "options": [
                "kubeflow-tpu/jupyter-scipy:latest",
                "kubeflow-tpu/jupyter-jax:latest",
                "kubeflow-tpu/jupyter-jax-full:latest",
                "kubeflow-tpu/jupyter-pytorch-xla:latest",
            ],
            "readOnly": False,
        },
        "imagePullPolicy": {"value": "IfNotPresent", "readOnly": False},
        "cpu": {"value": "0.5", "limitFactor": "1.2", "readOnly": False},
        "memory": {"value": "1.0Gi", "limitFactor": "1.2", "readOnly": False},
        # explicit limit overrides (ref form.py:123-128); empty value =
        # "derive from limitFactor"; set readOnly to pin alongside cpu/memory
        "cpuLimit": {"value": "", "readOnly": False},
        "memoryLimit": {"value": "", "readOnly": False},
        "workspaceVolume": {
            "value": {
                "mount": "/home/jovyan",
                "newPvc": {
                    "metadata": {"name": "{notebook-name}-workspace"},
                    "spec": {
                        "resources": {"requests": {"storage": "10Gi"}},
                        "accessModes": ["ReadWriteOnce"],
                    },
                },
            },
            "readOnly": False,
        },
        "dataVolumes": {"value": [], "readOnly": False},
        # TPU replaces the reference's `gpus` vendor dropdown
        "tpu": {
            "value": {"accelerator": "none", "topology": ""},
            "accelerators": [
                {
                    "name": name,
                    "displayName": f"TPU {name}",
                    "topologies": _topos,
                }
                for name, _topos in (
                    ("v4", ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4"]),
                    ("v5e", ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8"]),
                    ("v5p", ["2x2x1", "2x2x2", "2x4x4", "4x4x4"]),
                    ("v6e", ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8"]),
                )
            ],
            "readOnly": False,
        },
        # TPU node pools carry a google.com/tpu taint; the groups below let the
        # form opt a CPU-only server onto them (TPU servers get the toleration
        # from the controller automatically).
        "tolerationGroup": {
            "value": "none",
            "options": [
                {
                    "groupKey": "tpu-node-pool",
                    "displayName": "Schedule on TPU node pools",
                    "tolerations": [
                        {
                            "key": "google.com/tpu",
                            "operator": "Exists",
                            "effect": "NoSchedule",
                        }
                    ],
                },
            ],
            "readOnly": False,
        },
        "affinityConfig": {
            "value": "none",
            "options": [
                {
                    "configKey": "exclusive__tpu-host",
                    "displayName": "Exclusive: one notebook per TPU host",
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "cloud.google.com/gke-tpu-accelerator",
                                                "operator": "Exists",
                                            }
                                        ]
                                    }
                                ]
                            }
                        },
                        "podAntiAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {
                                        "matchExpressions": [
                                            {
                                                "key": "notebook-name",
                                                "operator": "Exists",
                                            }
                                        ]
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                }
                            ]
                        },
                    },
                    # schema extension (see jupyter.set_notebook_affinity):
                    # targeting tainted TPU pools requires the toleration too,
                    # or the pod is permanently unschedulable.
                    "tolerations": [
                        {
                            "key": "google.com/tpu",
                            "operator": "Exists",
                            "effect": "NoSchedule",
                        }
                    ],
                },
            ],
            "readOnly": False,
        },
        "configurations": {"value": [], "readOnly": False},
        "shm": {"value": True, "readOnly": False},
        "serverType": {"value": "jupyter", "readOnly": False},
    }
}


def load_config(path: str | None = None) -> dict:
    """Fallback chain: explicit path → env → mounted ConfigMap → in-tree
    default (ref utils.py:22-53)."""
    candidates = [
        p for p in (path, os.environ.get(CONFIG_PATH_ENV), DEFAULT_CONFIG_PATH)
        if p
    ]
    for candidate in candidates:
        if os.path.isfile(candidate):
            with open(candidate) as f:
                loaded = yaml.safe_load(f) or {}
            if "spawnerFormDefaults" in loaded:
                return loaded
    return DEFAULT_CONFIG


def form_value(body: Mapping, defaults: Mapping, body_field: str,
               config_field: str | None = None, optional: bool = False) -> Any:
    """readOnly enforcement (ref form.py:16-60): a readOnly field always takes
    the configured value; otherwise the user's value, falling back to config."""
    config_field = config_field or body_field
    section = defaults.get("spawnerFormDefaults", {}).get(config_field, {})
    if section.get("readOnly"):
        return section.get("value")
    if body_field in body:
        return body[body_field]
    if optional:
        return None
    return section.get("value")
