"""Tensorboards web app (TWA) backend.

Parity with ``crud-web-apps/tensorboards/backend/app/routes``
(get.py:9-23, post.py:14, delete.py:8): Tensorboard CR CRUD with status.
"""
from __future__ import annotations

from kubeflow_tpu.api import types as api
from kubeflow_tpu.auth.rbac import Authorizer
from kubeflow_tpu.controllers.tensorboard_controller import parse_logspath
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.webapps import base
from kubeflow_tpu.webapps.base import App, get_json, success
from kubeflow_tpu.webapps.cache import ReadCache

TWA_KINDS = ("Tensorboard",)


def create_app(
    cluster: FakeCluster,
    *,
    authorizer: Authorizer | None = None,
    cache: ReadCache | None = None,
    use_cache: bool = True,
) -> App:
    app = App("tensorboards-web-app", authorizer=authorizer or Authorizer(cluster))
    if cache is not None:
        cache.ensure_kinds(TWA_KINDS)
    elif use_cache:
        cache = ReadCache(
            cluster, TWA_KINDS, metrics=app.web_metrics
        ).start()
        app.on_close(cache.close)

    app.attach_frontend("tensorboards")
    base.add_namespaces_route(app, cluster)

    @app.route("/api/namespaces/<namespace>/tensorboards")
    def list_tensorboards(request, namespace):
        user = app.ensure(request, "list", "tensorboards", namespace)
        etag = (
            cache.etag(("Tensorboard", namespace), principal=user.name)
            if cache is not None else None
        )
        hit = base.not_modified(request, etag)
        if hit is not None:
            return hit
        tbs = (
            cache.list(
                "Tensorboard", namespace, principal=user.name, copy=False
            )
            if cache is not None
            else cluster.list("Tensorboard", namespace)
        )
        out = []
        for tb in tbs:
            scheme, _ = parse_logspath(tb["spec"].get("logspath", ""))
            ready = tb.get("status", {}).get("readyReplicas", 0)
            out.append(
                {
                    "name": ko.name(tb),
                    "namespace": namespace,
                    "logspath": tb["spec"].get("logspath"),
                    "storage": scheme,
                    "phase": "ready" if ready else "waiting",
                }
            )
        return base.set_etag(success("tensorboards", out), etag)

    @app.route("/api/namespaces/<namespace>/tensorboards", methods=("POST",))
    def post_tensorboard(request, namespace):
        user = app.ensure(request, "create", "tensorboards", namespace)
        body = get_json(request, "name", "logspath")
        stored = cluster.create(
            api.tensorboard(body["name"], namespace, body["logspath"])
        )
        if cache is not None:
            cache.note_write(stored, principal=user.name)
        return success("message", "Tensorboard created successfully.")

    @app.route("/api/namespaces/<namespace>/tensorboards/<name>")
    def get_tensorboard(request, namespace, name):
        user = app.ensure(request, "get", "tensorboards", namespace)
        tb = (
            cache.get("Tensorboard", name, namespace, principal=user.name)
            if cache is not None
            else cluster.get("Tensorboard", name, namespace)
        )
        return success("tensorboard", tb)

    @app.route(
        "/api/namespaces/<namespace>/tensorboards/<name>", methods=("PUT",)
    )
    def put_tensorboard(request, namespace, name):
        """Editable-YAML apply (editor module save path), authz'd as update;
        ?dryRun=true validates without persisting."""
        user = app.ensure(request, "update", "tensorboards", namespace)

        def validate(tb: dict) -> list[str]:
            logspath = (tb.get("spec") or {}).get("logspath")
            if not logspath or not isinstance(logspath, str):
                return ["spec.logspath is required"]
            scheme, _ = parse_logspath(logspath)
            if scheme == "unknown":
                return [
                    f"spec.logspath {logspath!r} must use pvc://, gs:// or s3://"
                ]
            return []

        return base.handle_cr_put(
            request, cluster, "Tensorboard", name, namespace,
            validate=validate, cache=cache, principal=user.name,
        )

    @app.route(
        "/api/namespaces/<namespace>/tensorboards/<name>", methods=("DELETE",)
    )
    def delete_tensorboard(request, namespace, name):
        user = app.ensure(request, "delete", "tensorboards", namespace)
        cluster.delete("Tensorboard", name, namespace)
        if cache is not None:
            cache.note_delete(
                "Tensorboard", name, namespace, principal=user.name
            )
        return success("message", "Tensorboard deleted")

    return app
