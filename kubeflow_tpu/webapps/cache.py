"""Watch-backed read layer for the web apps (the NotebookOS argument:
serve interactive reads from replicated in-memory state, never from the
authoritative store).

Every JWA/dashboard read used to be O(fleet): ``list_notebooks`` re-listed
all Notebooks AND all Events per request and joined them per notebook;
``get_tpus`` re-listed every Node per click. The scheduler's informer cache
(PR 8) proved the pattern pays ~5x on this codebase; :class:`ReadCache`
generalizes it for the serving path:

- **Per-kind stores fed by watches** — the same watch machinery the
  controllers use (``cluster.watch``), so under the chaos harness the cache
  is faultable like any client: streams drop, reconnects replay the current
  list as ADDED, duplicates arrive. Out-of-order and duplicate deliveries
  are absorbed by resourceVersion comparison; deletions replayed stale are
  absorbed by tombstones.
- **Positive freshness** — absence of watch events is indistinguishable
  from a severed stream, so freshness comes from confirmation, not silence:
  every ``resync_interval_s`` the read path polls the store's rv index
  (``resource_versions`` — no body copies) and falls back to a full re-list
  on divergence. A cache that cannot confirm within ``staleness_bound_s``
  refuses to serve from memory and reads through to the cluster (a cold
  start — watches installed but never synced — serves the same way). This
  is the bound the chaos soak's read-path audit enforces: the cache never
  serves an object deleted more than ``staleness_bound_s`` ago.
- **Secondary indexes** — notebooks-by-namespace, events-by-involved-object
  (killing the O(events x notebooks) join), nodes-by-accelerator,
  pods-by-claim and pods-by-notebook. Maintained incrementally at ingest.
- **Read-your-writes** — mutating handlers write through (``note_write`` /
  ``note_delete``) and pin the writing principal to at-least-that-rv; a
  read whose pin the store cannot prove falls back to the authoritative
  list, so the UI's immediate re-list after a POST/PATCH/DELETE always
  shows the change even if the watch stream is down.
- **ETags** — ``etag()`` derives a content signature from the backing
  objects' (key, resourceVersion) pairs, no serialization. A matching
  If-None-Match turns the whole list/detail render into a 304.

Thread-safe; reads return deep copies by default (``copy=False`` is for
handlers that provably only read, e.g. summary builders).
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Iterable, Mapping

from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import NotFound

# index-key builders per kind: index name -> fn(obj) -> iterable of keys
IndexFn = Callable[[dict], Iterable[str]]


def _rv_int(obj: Mapping) -> int:
    try:
        return int(ko.meta(dict(obj)).get("resourceVersion", 0))
    except (TypeError, ValueError):
        return 0


def _event_involved_key(ev: Mapping) -> Iterable[str]:
    io = ev.get("involvedObject") or {}
    if io.get("name"):
        yield f"{io.get('namespace', '')}/{io.get('kind', '')}/{io['name']}"


def _node_accelerator_key(node: Mapping) -> Iterable[str]:
    accel = (node.get("metadata", {}).get("labels") or {}).get(
        "cloud.google.com/gke-tpu-accelerator"
    )
    if accel:
        yield accel


def _pod_claim_keys(pod: Mapping) -> Iterable[str]:
    for vol in pod.get("spec", {}).get("volumes", []) or []:
        claim = (vol.get("persistentVolumeClaim") or {}).get("claimName")
        if claim:
            yield f"{ko.namespace(pod)}/{claim}"


def _pod_notebook_key(pod: Mapping) -> Iterable[str]:
    name = (pod.get("metadata", {}).get("labels") or {}).get("notebook-name")
    if name:
        yield f"{ko.namespace(pod)}/{name}"


INDEXERS: dict[str, dict[str, IndexFn]] = {
    "Event": {"involved": _event_involved_key},
    "Node": {"accelerator": _node_accelerator_key},
    "Pod": {"claim": _pod_claim_keys, "notebook": _pod_notebook_key},
}

DEFAULT_KINDS = (
    "Notebook",
    "Event",
    "Node",
    "Pod",
    "PersistentVolumeClaim",
    "PodDefault",
)


class _KindStore:
    """One kind's objects + rv bookkeeping + secondary indexes. All methods
    assume the owning ReadCache's lock is held."""

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self.objects: dict[tuple[str, str], dict] = {}
        self.rvs: dict[tuple[str, str], int] = {}
        self.by_namespace: dict[str, set[tuple[str, str]]] = {}
        self.rv_high = 0
        # highest rv ever ingested per namespace: with monotonic, never-reused
        # rvs, (live count, max rv) is a sound O(1) change signature — any
        # add/update raises max, any delete changes count, and max can never
        # return to an old value, so no two distinct states ever collide
        self.ns_max_rv: dict[str, int] = {}
        # key -> (rv at removal, removal time): ignores stale re-list ADDEDs
        # of a deleted object (rv <= tombstone) while letting a genuine
        # recreate (fresh, higher rv) through
        self.tombstones: dict[tuple[str, str], tuple[int, float]] = {}
        self.index_fns: dict[str, IndexFn] = dict(INDEXERS.get(kind, {}))
        self.indexes: dict[str, dict[str, set[tuple[str, str]]]] = {
            name: {} for name in self.index_fns
        }
        self._index_membership: dict[
            tuple[str, str], dict[str, tuple[str, ...]]
        ] = {}
        self.last_confirmed = 0.0  # 0 = never: cold caches must read through

    # ------------------------------------------------------------- mutation

    def ingest(self, obj: dict, now: float) -> bool:
        key = (ko.namespace(obj), ko.name(obj))
        rv = _rv_int(obj)
        tomb = self.tombstones.get(key)
        if tomb is not None:
            if rv <= tomb[0]:
                return False  # stale replay of an object we saw deleted
            del self.tombstones[key]
        old_rv = self.rvs.get(key)
        if old_rv is not None and rv <= old_rv:
            return False  # duplicate / out-of-order delivery
        self._unindex(key)
        self.objects[key] = obj
        self.rvs[key] = rv
        self.by_namespace.setdefault(key[0], set()).add(key)
        membership: dict[str, tuple[str, ...]] = {}
        for name, fn in self.index_fns.items():
            idx_keys = tuple(fn(obj))
            for ik in idx_keys:
                self.indexes[name].setdefault(ik, set()).add(key)
            membership[name] = idx_keys
        self._index_membership[key] = membership
        self.rv_high = max(self.rv_high, rv)
        if rv > self.ns_max_rv.get(key[0], 0):
            self.ns_max_rv[key[0]] = rv
        return True

    def remove(self, key: tuple[str, str], now: float, rv: int = 0) -> None:
        self._unindex(key)
        self.objects.pop(key, None)
        known_rv = self.rvs.pop(key, 0)
        ns_set = self.by_namespace.get(key[0])
        if ns_set is not None:
            ns_set.discard(key)
            if not ns_set:
                del self.by_namespace[key[0]]
        # preserve an existing tombstone's rv: a second remove of an
        # already-removed key (handler note_delete after the synchronous
        # watch DELETED) knows no rv, and clobbering the recorded one with
        # 0 would let a stale replay resurrect the deleted object
        prior = self.tombstones.get(key, (0, 0.0))[0]
        self.tombstones[key] = (max(rv, known_rv, prior), now)

    def _unindex(self, key: tuple[str, str]) -> None:
        membership = self._index_membership.pop(key, None)
        if not membership:
            return
        for name, idx_keys in membership.items():
            index = self.indexes[name]
            for ik in idx_keys:
                members = index.get(ik)
                if members is not None:
                    members.discard(key)
                    if not members:
                        del index[ik]

    def replace_all(self, objs: Iterable[dict], now: float) -> None:
        """Absorb a full authoritative list: ingest everything, drop keys
        the list no longer contains (the missed-DELETE recovery path)."""
        seen: set[tuple[str, str]] = set()
        for obj in objs:
            key = (ko.namespace(obj), ko.name(obj))
            seen.add(key)
            self.ingest(obj, now)
        for key in [k for k in self.objects if k not in seen]:
            self.remove(key, now)

    def prune_tombstones(self, now: float, keep_s: float) -> None:
        for key in [
            k for k, (_, t) in self.tombstones.items() if now - t > keep_s
        ]:
            del self.tombstones[key]


class ReadCache:
    """Shared watch-backed read layer the web apps serve from.

    ``start()`` installs one watch per kind and primes each store from an
    initial list. Reads confirm freshness lazily (rv poll / re-list) on the
    caller's thread — there is no background loop to leak, which also keeps
    the cache deterministic under the chaos harness's virtual clock.
    """

    def __init__(
        self,
        cluster,
        kinds: Iterable[str] = DEFAULT_KINDS,
        *,
        clock: Callable[[], float] = time.time,
        resync_interval_s: float = 5.0,
        staleness_bound_s: float = 30.0,
        metrics=None,
    ) -> None:
        self.cluster = cluster
        self.clock = clock
        self.resync_interval_s = resync_interval_s
        self.staleness_bound_s = staleness_bound_s
        self.metrics = metrics
        self._lock = threading.RLock()
        self._stores: dict[str, _KindStore] = {}
        self._handlers: list = []
        self._started = False
        # (principal, kind) -> rv the principal's reads must reflect
        self._pins: dict[tuple[str, str], int] = {}
        for kind in kinds:
            self._stores[kind] = _KindStore(kind)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ReadCache":
        """Install watches and prime every store (idempotent). A prime
        failure leaves that kind cold — reads fall back until a later
        confirm succeeds, which is the cold-start contract."""
        with self._lock:
            if self._started:
                return self
            self._started = True
            for kind in list(self._stores):
                self._install(kind)
        return self

    def _install(self, kind: str) -> None:
        handler = self._make_handler(kind)
        self.cluster.watch(kind, handler)
        self._handlers.append(handler)
        try:
            objs = self.cluster.list(kind)
        except Exception:
            return  # cold: the first read confirms via fallback
        now = self.clock()
        with self._lock:
            store = self._stores[kind]
            store.replace_all(objs, now)
            store.last_confirmed = now
        if self.metrics is not None:
            self._observe_store(kind, store, now)

    def ensure_kinds(self, kinds: Iterable[str]) -> "ReadCache":
        """Lazily add kinds to an already-started cache (one shared cache
        serving several apps with different kind sets)."""
        with self._lock:
            for kind in kinds:
                if kind in self._stores:
                    continue
                self._stores[kind] = _KindStore(kind)
                if self._started:
                    self._install(kind)
        return self

    def close(self) -> None:
        unwatch = getattr(self.cluster, "unwatch", None)
        if unwatch is not None:
            for handler in self._handlers:
                unwatch(handler)
        self._handlers = []
        self._started = False

    def _make_handler(self, kind: str):
        def handle(event: str, obj: dict) -> None:
            now = self.clock()
            with self._lock:
                store = self._stores.get(kind)
                if store is None:
                    return
                if event == "DELETED":
                    store.remove(
                        (ko.namespace(obj), ko.name(obj)), now, rv=_rv_int(obj)
                    )
                else:
                    store.ingest(obj, now)
            if self.metrics is not None:
                self.metrics.cache_watch_events.inc(kind=kind)

        return handle

    # ------------------------------------------------------------ freshness

    def _confirm(self, kind: str, now: float) -> bool:
        """Positive freshness: True when the store is provably current
        within the staleness bound. Cheap rv poll first; full re-list on
        divergence or when the cluster has no rv index. Confirmation
        failures (transient read faults) keep serving from memory only
        while inside the bound."""
        store = self._stores[kind]
        if now - store.last_confirmed < self.resync_interval_s and (
            store.last_confirmed > 0
        ):
            return True
        rv_fn = getattr(self.cluster, "resource_versions", None)
        try:
            if rv_fn is not None and store.last_confirmed > 0:
                current = rv_fn(kind)
                with self._lock:
                    mine = {k: str(v) for k, v in store.rvs.items()}
                    if mine == current:
                        store.last_confirmed = now
                        store.prune_tombstones(
                            now, 4 * self.staleness_bound_s
                        )
                        confirmed = True
                    else:
                        confirmed = False
                if confirmed:
                    if self.metrics is not None:
                        self._observe_store(kind, store, now)
                    return True
            objs = self.cluster.list(kind)
        except Exception:
            # transient read fault: within the bound the memory copy is
            # still certified; beyond it the caller must read through
            return 0 < now - store.last_confirmed <= self.staleness_bound_s
        with self._lock:
            store.replace_all(objs, now)
            store.last_confirmed = now
            store.prune_tombstones(now, 4 * self.staleness_bound_s)
        if self.metrics is not None:
            self.metrics.cache_relists.inc(kind=kind)
            self._observe_store(kind, store, now)
        return True

    def _observe_store(self, kind: str, store: _KindStore, now: float) -> None:
        """Gauge refresh at confirmation cadence (NOT per read — a 1k-row
        render makes thousands of store reads)."""
        self.metrics.cache_staleness.set(
            max(0.0, now - store.last_confirmed)
            if store.last_confirmed
            else float("inf"),
            kind=kind,
        )
        self.metrics.cache_objects.set(len(store.objects), kind=kind)

    def _serviceable(
        self, kind: str, principal: str | None, now: float
    ) -> bool:
        store = self._stores.get(kind)
        if store is None:
            return False
        if not self._confirm(kind, now):
            return False
        if principal:
            pin = self._pins.get((principal, kind), 0)
            if pin > store.rv_high:
                return False  # read-your-writes: the store hasn't proven it
        return True

    def _count_read(self, kind: str, source: str) -> None:
        if self.metrics is not None:
            self.metrics.cache_reads.inc(kind=kind, source=source)

    # ---------------------------------------------------------------- reads

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        *,
        principal: str | None = None,
        copy: bool = True,
    ) -> list[dict]:
        now = self.clock()
        if not self._serviceable(kind, principal, now):
            objs = self.cluster.list(kind, namespace)
            self._absorb(kind, objs, now)
            self._count_read(kind, "fallback")
            return objs
        self._count_read(kind, "cache")
        with self._lock:
            store = self._stores[kind]
            keys = (
                store.by_namespace.get(namespace, set())
                if namespace is not None
                else store.objects.keys()
            )
            out = [store.objects[k] for k in sorted(keys)]
        return [ko.deep_copy(o) for o in out] if copy else out

    def get(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        *,
        principal: str | None = None,
    ) -> dict:
        now = self.clock()
        if self._serviceable(kind, principal, now):
            with self._lock:
                obj = self._stores[kind].objects.get((namespace, name))
            if obj is not None:
                self._count_read(kind, "cache")
                return ko.deep_copy(obj)
        # miss or unserviceable: the authoritative answer (NotFound
        # propagates — a just-created object the watch hasn't delivered yet
        # must not 404)
        obj = self.cluster.get(kind, name, namespace)
        self._absorb(kind, [obj], now)
        self._count_read(kind, "fallback")
        return ko.deep_copy(obj)

    def events_for(
        self,
        involved: Mapping,
        *,
        principal: str | None = None,
        copy: bool = True,
    ) -> list[dict]:
        """The involved-object index: the O(1) replacement for every
        full-namespace Event scan on a request path."""
        now = self.clock()
        if not self._serviceable("Event", principal, now):
            self._count_read("Event", "fallback")
            return self.cluster.events_for(involved)
        self._count_read("Event", "cache")
        ns = ko.namespace(involved)
        ik = f"{ns}/{involved.get('kind', '')}/{ko.name(involved)}"
        uid = (involved.get("metadata") or {}).get("uid")
        with self._lock:
            store = self._stores["Event"]
            keys = sorted(store.indexes["involved"].get(ik, set()))
            out = []
            for key in keys:
                ev = store.objects[key]
                ev_uid = (ev.get("involvedObject") or {}).get("uid")
                # uid-aware like FakeCluster.events_for (kubectl describe
                # semantics): a recreated object does not inherit history
                if uid and ev_uid and ev_uid != uid:
                    continue
                out.append(ko.deep_copy(ev) if copy else ev)
        return out

    def events_in(
        self, namespace: str, *, principal: str | None = None
    ) -> list[dict]:
        return self.list("Event", namespace, principal=principal)

    def nodes_for_accelerator(self, gke_accelerator: str) -> list[dict]:
        """Nodes carrying the given gke-tpu-accelerator label (the
        /api/tpus availability probe's working set)."""
        now = self.clock()
        if not self._serviceable("Node", None, now):
            self._count_read("Node", "fallback")
            return [
                n
                for n in self.cluster.list("Node")
                if (n.get("metadata", {}).get("labels") or {}).get(
                    "cloud.google.com/gke-tpu-accelerator"
                )
                == gke_accelerator
            ]
        self._count_read("Node", "cache")
        with self._lock:
            store = self._stores["Node"]
            keys = sorted(
                store.indexes["accelerator"].get(gke_accelerator, set())
            )
            return [ko.deep_copy(store.objects[k]) for k in keys]

    def pods_using_claim(self, namespace: str, claim: str) -> list[str]:
        now = self.clock()
        if not self._serviceable("Pod", None, now):
            self._count_read("Pod", "fallback")
            return [
                ko.name(p)
                for p in self.cluster.list("Pod", namespace)
                if any(
                    v.get("persistentVolumeClaim", {}).get("claimName")
                    == claim
                    for v in p.get("spec", {}).get("volumes", []) or []
                )
            ]
        self._count_read("Pod", "cache")
        with self._lock:
            store = self._stores["Pod"]
            keys = sorted(store.indexes["claim"].get(f"{namespace}/{claim}", set()))
            return [k[1] for k in keys]

    def pods_for_notebook(
        self, namespace: str, name: str, *, principal: str | None = None
    ) -> list[dict]:
        now = self.clock()
        if not self._serviceable("Pod", principal, now):
            self._count_read("Pod", "fallback")
            return self.cluster.list(
                "Pod", namespace, {"matchLabels": {"notebook-name": name}}
            )
        self._count_read("Pod", "cache")
        with self._lock:
            store = self._stores["Pod"]
            keys = sorted(
                store.indexes["notebook"].get(f"{namespace}/{name}", set())
            )
            return [ko.deep_copy(store.objects[k]) for k in keys]

    def _absorb(self, kind: str, objs: Iterable[dict], now: float) -> None:
        """Opportunistically ingest fallback-read results (no removals —
        a scoped list proves nothing about other namespaces)."""
        store = self._stores.get(kind)
        if store is None:
            return
        with self._lock:
            for obj in objs:
                store.ingest(ko.deep_copy(obj), now)

    # -------------------------------------------------------------- writes

    def note_write(self, stored: Mapping, *, principal: str | None = None) -> None:
        """Write-through after a successful mutating handler: the returned
        object (with its committed resourceVersion) lands in the store
        immediately, and the principal is pinned to at-least-that-rv so a
        cache replaced behind their back still serves their write."""
        kind = stored.get("kind", "")
        store = self._stores.get(kind)
        if store is None:
            return
        now = self.clock()
        rv = _rv_int(stored)
        with self._lock:
            store.ingest(ko.deep_copy(dict(stored)), now)
            if principal:
                key = (principal, kind)
                self._pins[key] = max(self._pins.get(key, 0), rv)

    def note_delete(
        self, kind: str, name: str, namespace: str = "", *, principal: str | None = None
    ) -> None:
        store = self._stores.get(kind)
        if store is None:
            return
        with self._lock:
            store.remove((namespace, name), self.clock())
            if principal:
                # deletes carry no rv; pin to everything the store has seen
                # so this session's reads can never be satisfied by an older
                # replacement of the cache than the one that saw the delete
                key = (principal, kind)
                self._pins[key] = max(self._pins.get(key, 0), store.rv_high)

    # ---------------------------------------------------------------- etag

    def etag(
        self,
        *scopes: tuple[str, str | None],
        principal: str | None = None,
        extra: str = "",
    ) -> str | None:
        """Content signature over the backing object sets: sha1 over each
        ``(kind, namespace)`` scope's (live count, max ingested rv) pair —
        O(1) per scope, and sound because rvs are monotonic and never
        reused (any add/update raises max, any delete changes count, and
        max can never revisit an old value) — plus ``extra`` material
        (e.g. a telemetry freshness stamp). None when any scope is
        unserviceable for this principal — the handler then serves a full
        response and skips revalidation, never a wrong 304."""
        now = self.clock()
        h = hashlib.sha1()
        for kind, namespace in scopes:
            if not self._serviceable(kind, principal, now):
                return None
            with self._lock:
                store = self._stores[kind]
                if namespace is None:
                    count, high = len(store.objects), store.rv_high
                else:
                    count = len(store.by_namespace.get(namespace, ()))
                    high = store.ns_max_rv.get(namespace, 0)
            h.update(f"{kind}/{namespace}:{count}@{high};".encode())
        if extra:
            h.update(extra.encode())
        return h.hexdigest()

    # ---------------------------------------------------------------- debug

    def stats(self) -> dict:
        now = self.clock()
        with self._lock:
            return {
                kind: {
                    "objects": len(store.objects),
                    "rv_high": store.rv_high,
                    "tombstones": len(store.tombstones),
                    "staleness_s": (
                        round(now - store.last_confirmed, 3)
                        if store.last_confirmed
                        else None
                    ),
                }
                for kind, store in self._stores.items()
            }


__all__ = ["ReadCache", "DEFAULT_KINDS", "NotFound"]
