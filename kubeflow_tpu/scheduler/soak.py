"""Seeded chaos soak for the fleet scheduler (``tools/sched_soak.py``).

The scheduler's whole safety argument is that the placement-annotation set is
the store of record: every cycle rebuilds occupancy from it, so any
interleaving of API faults, node drains, capacity flaps, and scheduler
crash-restarts *between bind writes* must preserve two hard invariants at
every observable state —

- **zero chip double-booking**: no two gangs' committed placements overlap;
- **gang atomicity**: a placement annotation always carries every slice of
  its gang (the bind is one write), and a gang's StatefulSets hold either
  all their pods or none.

— and converge, once the faults heal, to a fixed point where the scheduler
itself has nothing left to do: the queue head does not fit free capacity, no
eligible preemption would make it fit, and no strictly-smaller gang behind it
could backfill (otherwise "every feasible gang eventually binds" is broken —
a quiesced-but-wrong scheduler would pass a pure quiescence check, so the
final audit re-derives the policy's own fixed-point condition from the
store).

Reuses the control-plane chaos layer (:mod:`kubeflow_tpu.testing.chaos`) for
verb faults, lost responses, watch drops, and crash-restart arming; the
scheduler-specific chaos — drains, flaps, priority bumps, stop/start churn —
is the seeded op timeline. Everything flows from the seed: a printed failure
reproduces with ``python tools/sched_soak.py --seed N``.
"""
from __future__ import annotations

import collections
import dataclasses
import random
from typing import Callable

from kubeflow_tpu import scheduler as sched
from kubeflow_tpu.api import types as api
from kubeflow_tpu.controllers.notebook_controller import NotebookReconciler
from kubeflow_tpu.culler.culler import Culler
from kubeflow_tpu.obs.events import EventRecorder, audit_events
from kubeflow_tpu.obs.slo import SLOMetrics
from kubeflow_tpu.obs.timeline import TimelineRecorder, audit_timeline
from kubeflow_tpu.obs.tracing import Tracer
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime import sharding
from kubeflow_tpu.runtime.fake import (
    AlreadyExists,
    Conflict,
    FakeCluster,
    NotFound,
)
from kubeflow_tpu.runtime.manager import Manager
from kubeflow_tpu.scheduler import explain as explain_mod
from kubeflow_tpu.scheduler import preemption as preempt
from kubeflow_tpu.scheduler.binpack import ceil_div_shape
from kubeflow_tpu.scheduler.controller import SchedulerReconciler
from kubeflow_tpu.scheduler.fleet import Fleet
from kubeflow_tpu.scheduler.queue import GangQueue, GangRequest
from kubeflow_tpu.testing.chaos import (
    SOAK_MAX_REQUEUE_S,
    ChaosCluster,
    ChaosConfig,
    check_invariants,
    fingerprint,
)
from kubeflow_tpu.tpu.topology import ACCELERATORS, parse_topology
from kubeflow_tpu.utils.config import ControllerConfig
from kubeflow_tpu.utils.metrics import SchedulerMetrics
from kubeflow_tpu.webhooks import tpu_env

# Short aging interval so the soak's virtual timeline (minutes, not hours)
# actually crosses aging boundaries — the quiescence check then proves the
# continuous-aging design claim: relative queue order is time-invariant.
SOAK_AGING_INTERVAL_S = 60.0


def make_pool(
    base: FakeCluster, accelerator: str, topology: str, pool_name: str
) -> list[dict]:
    """One TPU node pool with explicit pool + host-index labels (the GKE
    labels ``Fleet.from_nodes`` keys on); returns the created Node objects
    so a capacity flap can re-create them verbatim."""
    topo = parse_topology(accelerator, topology)
    accel = ACCELERATORS[accelerator]
    nodes = []
    for i in range(topo.num_hosts):
        nodes.append(
            base.add_node(
                f"{pool_name}-{i}",
                labels={
                    "cloud.google.com/gke-tpu-accelerator": accel.gke_accelerator,
                    "cloud.google.com/gke-tpu-topology": topology,
                    sched.POOL_LABEL: pool_name,
                    sched.HOST_INDEX_LABEL: str(i),
                },
                capacity={"google.com/tpu": str(topo.chips_per_host)},
            )
        )
    return nodes


# ------------------------------------------------------------------- audits


def _nb_key(nb: dict) -> str:
    return f"{ko.namespace(nb)}/{ko.name(nb)}"


def _healthy_fleet(base: FakeCluster) -> Fleet:
    """The fleet model with every known host treated usable — the geometry
    double-booking is judged against (a drained host still HOLDS the chips
    its gang was bound to; it does not hand them to a second gang)."""
    fleet = Fleet.from_nodes(base.list("Node"))
    for pool in fleet.pools.values():
        pool.clear_used()  # drop blocked cells: gang-vs-gang only
    return fleet


def audit_placements(
    base: FakeCluster, *, strict: bool = False, where: str = ""
) -> list[str]:
    """The two always-invariants, checked straight from the store.

    Non-strict (mid-run) tolerates a placement into a pool whose every node
    object is currently flapped away — the scheduler has not reacted yet and
    the geometry is unknowable; strict (fixed point, data plane healed)
    tolerates nothing.
    """
    out: list[str] = []
    fleet = _healthy_fleet(base)
    for nb in base.list("Notebook"):
        placement = sched.placement_of(nb)
        if placement is None:
            continue
        key = _nb_key(nb)
        try:
            topo = api.notebook_topology(nb)
        except ValueError:
            topo = None
        if topo is None:
            out.append(f"{where}: {key}: placement on a non-TPU notebook")
            continue
        slices = placement["slices"]
        num_slices = api.notebook_num_slices(nb)
        if len(slices) != num_slices:
            out.append(
                f"{where}: {key}: gang atomicity violated — "
                f"{len(slices)} slices placed, {num_slices} requested"
            )
            continue
        unknown = [s.get("pool") for s in slices if s.get("pool") not in fleet.pools]
        if unknown:
            if strict:
                out.append(f"{where}: {key}: slice in unknown pool {unknown}")
            continue
        if not fleet.occupy_gang(key, slices):
            out.append(
                f"{where}: {key}: placement overlaps an earlier gang or "
                f"falls outside its pool (CHIP DOUBLE-BOOKING)"
            )
            continue
        if strict:
            for j, s in enumerate(slices):
                pool = fleet.pools[s["pool"]]
                want = ceil_div_shape(s["shape"], pool.accel.host_block)
                expected_hosts = 1
                for d in want:
                    expected_hosts *= d
                if len(s.get("nodes") or []) != expected_hosts:
                    out.append(
                        f"{where}: {key}/s{j}: {len(s.get('nodes') or [])} "
                        f"assigned nodes for a {expected_hosts}-host slice"
                    )
    return out


def audit_fixed_point(
    base: FakeCluster,
    now: float,
    *,
    aging_interval_s: float = SOAK_AGING_INTERVAL_S,
    backfill_window: int = preempt.DEFAULT_BACKFILL_WINDOW,
    where: str = "final",
) -> list[str]:
    """Everything that must hold once faults are healed and the state has
    quiesced. Re-derives the scheduler's own fixed-point condition from the
    store alone, so a scheduler that silently stopped cycling (lost requeue)
    fails here even though the state looks quiet."""
    out = audit_placements(base, strict=True, where=where)
    fleet = _healthy_fleet(base)
    bound: list[preempt.BoundGang] = []
    queue = GangQueue(aging_interval_s=aging_interval_s)

    for nb in base.list("Notebook"):
        try:
            topo = api.notebook_topology(nb)
        except ValueError:
            continue
        if topo is None:
            continue
        key = _nb_key(nb)
        ns, name = ko.namespace(nb), ko.name(nb)
        num_slices = api.notebook_num_slices(nb)
        anns = ko.annotations(nb)
        active = api.STOP_ANNOTATION not in anns
        placement = sched.placement_of(nb)

        # -- workload gating: all pods or none, gated on the bind ----------
        expected = topo.num_hosts if (active and placement) else 0
        for j in range(num_slices):
            sts_name = name if num_slices == 1 else f"{name}-s{j}"
            sts = base.try_get("StatefulSet", sts_name, ns)
            replicas = (sts or {}).get("spec", {}).get("replicas", 0)
            if replicas != expected:
                out.append(
                    f"{where}: {key}: slice {j} StatefulSet has "
                    f"{replicas} replicas, want {expected} "
                    f"({'bound' if placement else 'unbound'} gang)"
                )

        if not active:
            if placement is not None:
                out.append(f"{where}: {key}: stopped gang still holds a placement")
            if sched.QUEUED_AT_ANNOTATION in anns:
                out.append(
                    f"{where}: {key}: stopped gang still queued "
                    f"(ghost capacity claim)"
                )
            for t in sched.SCHEDULER_CONDITION_TYPES:
                if sched.condition_is_true(nb, t):
                    out.append(f"{where}: {key}: stopped gang still marked {t}")
            continue

        if placement is not None:
            fleet.occupy_gang(key, placement["slices"])
            bound.append(
                preempt.BoundGang(
                    key=key,
                    priority=sched.gang_priority(nb),
                    queued_at=float(anns.get(sched.QUEUED_AT_ANNOTATION, now)),
                    chips=topo.num_chips * num_slices,
                    topo=topo,
                    num_slices=num_slices,
                )
            )
            if sched.condition_is_true(nb, sched.COND_QUEUED):
                out.append(f"{where}: {key}: bound gang still marked Queued")
            continue

        if not fleet.feasible_on_empty(topo, num_slices):
            if not sched.condition_is_true(nb, sched.COND_UNSCHEDULABLE):
                out.append(
                    f"{where}: {key}: infeasible gang not marked Unschedulable"
                )
            continue
        if not sched.condition_is_true(nb, sched.COND_QUEUED):
            out.append(f"{where}: {key}: waiting feasible gang not marked Queued")
        raw = anns.get(sched.QUEUED_AT_ANNOTATION)
        if raw is None:
            out.append(f"{where}: {key}: queued gang has no queued-at annotation")
            continue
        queue.push(
            GangRequest(
                key=key,
                priority=sched.gang_priority(nb),
                queued_at=float(raw),
                topo=topo,
                num_slices=num_slices,
            )
        )

    # -- the policy's own fixed-point condition ----------------------------
    # heads are per accelerator (a blocked v4 head must not hide starvation
    # of a v5e gang on an idle v5e pool — the scheduler's _schedule loop
    # uses the same rule)
    order = queue.ordered(now)
    heads: dict[str, GangRequest] = {}
    for req in order:
        heads.setdefault(req.topo.accelerator.name, req)
    for accel in sorted(heads):
        head = heads[accel]
        if fleet.clone().place_gang(head.key, head.topo, head.num_slices):
            out.append(
                f"{where}: STARVATION — {accel} queue head {head.key} fits "
                f"free capacity but was never bound"
            )
            continue
        if preempt.select_victims(fleet, bound, head) is not None:
            out.append(
                f"{where}: head {head.key} could bind by preempting "
                f"junior gangs but was never bound"
            )
        for cand in preempt.backfill_candidates(
            order, head, window=backfill_window
        ):
            if fleet.clone().place_gang(cand.key, cand.topo, cand.num_slices):
                out.append(
                    f"{where}: STARVATION — {cand.key} is backfillable "
                    f"behind blocked head {head.key} but was never bound"
                )
    return out


def audit_shards(
    base: FakeCluster, router, *, where: str = "final"
) -> list[str]:
    """Cross-shard invariants of the sharded control plane
    (docs/architecture.md "control-plane sharding"), re-derived from the
    store alone:

    - every gang with a scheduler footprint (queued-at claim or committed
      placement) carries the ownership stamp of the shard the CURRENT
      router computes as its owner — orphans from killed leaders, crashed
      adoptions, and generation changes must all have converged;
    - no placement ever lands in a pool of a different accelerator family
      than the gang's own — the structural guarantee that per-family
      scheduler shards share no free space (combined with the global
      overlap audit in :func:`audit_placements`, this is the zero
      cross-shard double-booking proof).
    """
    out: list[str] = []
    fleet = _healthy_fleet(base)
    for nb in base.list("Notebook"):
        try:
            topo = api.notebook_topology(nb)
        except ValueError:
            continue
        key = _nb_key(nb)
        anns = ko.annotations(nb)
        if topo is None:
            if sharding.SHARD_ANNOTATION in anns:
                out.append(f"{where}: {key}: non-gang carries a shard stamp")
            continue
        fam = topo.accelerator.name
        owner = router.shard_for_family(fam)
        placement = sched.placement_of(nb)
        stamped = sharding.owner_of(nb)
        if sched.QUEUED_AT_ANNOTATION in anns or placement is not None:
            if stamped != (router.shards, owner):
                out.append(
                    f"{where}: {key}: scheduler footprint with stamp "
                    f"{anns.get(sharding.SHARD_ANNOTATION)!r}, owner is "
                    f"shard {owner} of {router.shards}"
                )
            got_label = ko.labels(nb).get(sharding.FAMILY_LABEL)
            if got_label != fam:
                out.append(
                    f"{where}: {key}: family label {got_label!r} drifted "
                    f"from spec family {fam!r} (the owner's filtered "
                    f"ingest must heal it)"
                )
        if placement is not None:
            for j, s in enumerate(placement["slices"]):
                pool = fleet.pools.get(s.get("pool", ""))
                if pool is not None and pool.accel.name != fam:
                    out.append(
                        f"{where}: {key}/s{j}: {fam} gang placed in "
                        f"{pool.accel.name} pool {pool.name} (cross-family "
                        f"bind — shards would share this space)"
                    )
    return out


# ----------------------------------------------------------------- scenario

# (accelerator, pool topology): small enough that seeds run fast, varied
# enough to exercise rotation, multi-pool spread, and cross-accel queues.
_POOL_CHOICES = [
    ("v4", "4x4x4"),   # 16 hosts / 64 chips, 3-d torus
    ("v4", "2x2x4"),   # 4 hosts
    ("v5e", "4x8"),    # 4 hosts / 32 chips, 2-d
    ("v5p", "2x2x4"),  # 4 hosts
]
_GANG_TOPOLOGIES = {
    "v4": ["2x2x1", "2x2x2", "2x2x4", "4x4x4"],
    "v5e": ["2x4", "4x4", "4x8"],
    "v5p": ["2x2x1", "2x2x2", "2x2x4"],
}
# Valid shapes no soak pool can ever hold — must surface as Unschedulable.
_INFEASIBLE = [("v4", "8x8x8"), ("v5e", "8x16"), ("v5p", "4x4x8")]


class SchedScenario:
    """A seeded fleet + gang workload + hostile op timeline.

    ``namespaces``: the sharded soak spreads gangs over several namespaces
    (manager shards partition by namespace hash) from a *separate* RNG
    stream, so the default single-namespace scenario draws — and therefore
    every existing seed's timeline — are bit-identical to before.
    """

    N_ROUNDS = 6
    NAMESPACE = "team-a"

    def __init__(
        self, seed: int, namespaces: tuple[str, ...] | None = None
    ) -> None:
        rng = random.Random(f"sched-scenario-{seed}")
        self.seed = seed
        self.namespaces = tuple(namespaces) if namespaces else (self.NAMESPACE,)
        self.culling = rng.random() < 0.3
        n_pools = 1 + (rng.random() < 0.6) + (rng.random() < 0.2)
        picks = rng.sample(_POOL_CHOICES, k=min(n_pools, len(_POOL_CHOICES)))
        self.pools = {
            f"pool-{accel}-{i}": (accel, topo)
            for i, (accel, topo) in enumerate(picks)
        }
        pool_accels = sorted({a for a, _ in self.pools.values()})
        self.gangs: dict[str, dict] = {}
        for i in range(rng.randint(5, 10)):
            if rng.random() < 0.12:
                accel, topo = _INFEASIBLE[rng.randrange(len(_INFEASIBLE))]
            else:
                accel = pool_accels[rng.randrange(len(pool_accels))]
                shapes = _GANG_TOPOLOGIES[accel]
                topo = shapes[rng.randrange(len(shapes))]
            gang = dict(tpu_accelerator=accel, tpu_topology=topo)
            if rng.random() < 0.2 and parse_topology(accel, topo).num_hosts <= 2:
                gang["tpu_num_slices"] = 2
            prio = (0, 0, 0, 1, 5)[rng.randrange(5)]
            if prio:
                gang["annotations"] = {sched.PRIORITY_ANNOTATION: str(prio)}
            self.gangs[f"g{i}"] = gang
        # busy gangs survive the culler; the rest are idle and cullable
        self.busy = {g for g in sorted(self.gangs) if rng.random() < 0.7}
        if len(self.namespaces) > 1:
            ns_rng = random.Random(f"sched-ns-{seed}")
            self.gang_ns = {
                g: self.namespaces[ns_rng.randrange(len(self.namespaces))]
                for g in sorted(self.gangs)
            }
        else:
            self.gang_ns = {g: self.namespaces[0] for g in self.gangs}
        self.node_specs: dict[str, dict] = {}
        self.rounds = self._op_timeline(rng)

    def _op_timeline(self, rng: random.Random) -> list[list[tuple[str, str]]]:
        node_names = [
            f"{pool}-{i}"
            for pool, (accel, topo) in sorted(self.pools.items())
            for i in range(parse_topology(accel, topo).num_hosts)
        ]
        alive_nb, dead_nb = set(self.gangs), set()
        drained: set[str] = set()
        flapped: set[str] = set()
        rounds: list[list[tuple[str, str]]] = []
        for _ in range(self.N_ROUNDS):
            ops: list[tuple[str, str]] = []
            for _ in range(rng.randint(0, 2)):
                choices: list[tuple[str, str]] = []
                for nb in sorted(alive_nb):
                    choices += [
                        ("stop", nb), ("start", nb),
                        ("bump_priority", nb), ("delete_nb", nb),
                    ]
                    shapes = _GANG_TOPOLOGIES[
                        self.gangs[nb]["tpu_accelerator"]
                    ]
                    choices.append(
                        ("resize", f"{nb}:{shapes[rng.randrange(len(shapes))]}")
                    )
                choices += [("recreate_nb", nb) for nb in sorted(dead_nb)]
                for node in node_names:
                    if node in flapped:
                        choices.append(("restore", node))
                    elif node in drained:
                        choices.append(("undrain", node))
                    else:
                        choices += [("drain", node), ("flap", node)]
                op = choices[rng.randrange(len(choices))]
                verb, target = op
                if verb == "delete_nb":
                    alive_nb.discard(target); dead_nb.add(target)
                elif verb == "recreate_nb":
                    dead_nb.discard(target); alive_nb.add(target)
                elif verb == "drain":
                    drained.add(target)
                elif verb == "undrain":
                    drained.discard(target)
                elif verb == "flap":
                    flapped.add(target); drained.discard(target)
                elif verb == "restore":
                    flapped.discard(target)
                ops.append(op)
            rounds.append(ops)
        return rounds

    # -- world construction (user / API-server side: never faulted) --------

    def _nb(self, name: str) -> dict:
        return api.notebook(name, self.gang_ns[name], **self.gangs[name])

    def setup(self, base: FakeCluster) -> None:
        for pool, (accel, topo) in sorted(self.pools.items()):
            for node in make_pool(base, accel, topo, pool):
                self.node_specs[ko.name(node)] = {
                    "apiVersion": "v1",
                    "kind": "Node",
                    "metadata": {
                        "name": ko.name(node),
                        "labels": dict(ko.labels(node)),
                    },
                    "status": ko.deep_copy(node.get("status", {})),
                }
        for name in sorted(self.gangs):
            base.create(self._nb(name))

    def apply(self, base: FakeCluster, op: tuple[str, str], round_no: int) -> None:
        verb, target = op
        ns = self.gang_ns.get(target.split(":", 1)[0], self.NAMESPACE)
        try:
            if verb == "stop":
                base.patch("Notebook", target, ns, {"metadata": {"annotations": {
                    api.STOP_ANNOTATION: "2026-01-01T00:00:00Z"}}})
            elif verb == "start":
                base.patch("Notebook", target, ns, {"metadata": {"annotations": {
                    api.STOP_ANNOTATION: None,
                    api.LAST_ACTIVITY_ANNOTATION: None}}})
            elif verb == "bump_priority":
                base.patch("Notebook", target, ns, {"metadata": {"annotations": {
                    sched.PRIORITY_ANNOTATION: str((round_no % 3) * 5)}}})
            elif verb == "resize":
                # spec.tpu edited in place: a bound gang's committed
                # placement no longer matches and must be released
                name, topo = target.split(":", 1)
                base.patch("Notebook", name, ns, {"spec": {"tpu": {
                    "topology": topo}}})
            elif verb == "delete_nb":
                base.delete("Notebook", target, ns)
            elif verb == "recreate_nb":
                base.create(self._nb(target))
            elif verb == "drain":
                base.patch("Node", target, "", {"spec": {"unschedulable": True}})
            elif verb == "undrain":
                base.patch("Node", target, "", {"spec": {"unschedulable": None}})
            elif verb == "flap":
                base.delete("Node", target)
            elif verb == "restore":
                base.create(self.node_specs[target], skip_admission=True)
        except (NotFound, AlreadyExists, Conflict):
            pass  # op raced a controller write; a later round retries

    def heal_data_plane(self, base: FakeCluster) -> None:
        """Undrain and restore every node: the final audit judges the
        scheduler against a fully healthy fleet (feasible ⇒ eventually
        bound has no meaning while the capacity itself is still gone)."""
        for name, spec in sorted(self.node_specs.items()):
            node = base.try_get("Node", name)
            if node is None:
                base.create(spec, skip_admission=True)
            elif (node.get("spec") or {}).get("unschedulable"):
                base.patch("Node", name, "", {"spec": {"unschedulable": None}})

    def make_fetcher(self) -> Callable:
        busy = set(self.busy)

        def fetch(namespace: str, name: str):
            if name in busy:
                return [{"execution_state": "busy"}]
            return []  # reachable server, zero kernels: idle by definition

        return fetch


# -------------------------------------------------------------------- runner


class _Clock:
    def __init__(self, start: float) -> None:
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


@dataclasses.dataclass
class SchedSeedResult:
    seed: int
    violations: list[str]
    quiesced: bool
    restarts: int
    binds: int
    preemptions: int
    fault_counts: collections.Counter
    shards: int = 1

    @property
    def ok(self) -> bool:
        return self.quiesced and not self.violations

    def describe(self) -> str:
        if self.ok:
            faults = sum(self.fault_counts.values())
            return (
                f"seed {self.seed}: converged ({self.binds} binds, "
                f"{self.preemptions} preemptions, {faults} faults, "
                f"{self.restarts} scheduler restarts)"
            )
        flag = f" --shards {self.shards}" if self.shards > 1 else ""
        lines = [f"seed {self.seed}: FAILED "
                 f"(repro: python tools/sched_soak.py --seed {self.seed}"
                 f"{flag})"]
        if not self.quiesced:
            lines.append("  state never quiesced after faults healed")
        lines += [f"  invariant: {v}" for v in self.violations[:10]]
        if len(self.violations) > 10:
            lines.append(f"  ... {len(self.violations) - 10} more")
        return "\n".join(lines)


def run_sched_seed(
    seed: int,
    faults: ChaosConfig | None = None,
    *,
    shards: int = 1,
    max_restarts_per_tick: int = 6,
    lost_update_audit: bool = True,
    explain_audit: bool = True,
    ledger_audit: bool = True,
) -> SchedSeedResult:
    """One seeded soak run: hostile timeline under chaos, heal, settle,
    quiesce, then the fixed-point audit. ``faults=None`` runs the same
    timeline fault-free (a sanity baseline for targeted tests).

    ``shards=1`` (the default) is the historical single-manager run,
    bit-identical to before sharding existed. ``shards=N`` runs the SHARDED
    control plane over the same store: N managers (namespace-hash filtered
    notebook controllers, per-family scheduler shards with ownership
    stamping), gangs spread across four namespaces, one shard's leader
    killed EVERY round (shutdown + cold rebuild — the stand-down/takeover
    cycle), and the per-seed audits extended with the cross-shard checks
    (:func:`audit_shards`): converged stamps, zero cross-family binds,
    and — together with the global overlap audit — zero cross-shard chip
    double-booking."""
    router = sharding.ShardRouter(shards) if shards > 1 else None
    namespaces = (
        ("team-a", "team-b", "team-c", "team-d") if shards > 1 else None
    )
    scenario = SchedScenario(seed, namespaces=namespaces)
    base = FakeCluster()
    tpu_env.install(base)
    chaos = (
        ChaosCluster(
            base, seed=seed, config=faults, lost_update_audit=lost_update_audit
        )
        if faults is not None
        else None
    )
    cluster = chaos if chaos is not None else base
    clock = _Clock(1_000_000.0)
    cfg = ControllerConfig(scheduler_enabled=True)
    culler = Culler(
        enabled=scenario.culling,
        cull_idle_minutes=1.0,
        check_period_minutes=0.5,
        fetch_kernels=scenario.make_fetcher(),
        clock=clock,
    )
    # per-shard SchedulerMetrics on one registry (the shard label keeps the
    # series disjoint — exactly the production layout); shards==1 keeps the
    # historical unlabeled schema. The shared registry must start BARE: a
    # throwaway unsharded instance would freeze the label schemas without
    # ``shard`` and every sharded observation would then raise (Registry
    # rejects exactly that mix at registration now).
    if router is None:
        shard_metrics = [SchedulerMetrics()]
    else:
        from kubeflow_tpu.utils.metrics import Registry

        registry = Registry()
        shard_metrics = [
            SchedulerMetrics(registry, shard=str(i)) for i in range(shards)
        ]
    # one tracer spans the whole run (the trace audit is a run property);
    # recorders are per-incarnation — a restart loses the dedup hot cache
    # and must rediscover Events instead of storming new ones
    tracer = Tracer(clock=clock)

    # one SLO ring across restarts (an observer, like the tracer); the
    # timeline recorder itself is stateless — marks live on the CRs
    slo = SLOMetrics(clock=clock)

    # the efficiency ledger: an observer across restarts (like the tracer),
    # ticked only by the harness driver, reading the unfaulted base. This
    # soak's drains/flaps/preemptions are exactly the traffic the
    # conservation invariant must survive — chips moving between gangs,
    # blocked cells, and fragmentation strands, every chip-second still
    # landing in exactly one bucket (docs/chaos.md "efficiency ledger").
    from kubeflow_tpu.obs.ledger import FleetEfficiencyLedger

    ledger = FleetEfficiencyLedger(base, clock=clock, interval_s=1.0)

    # Differential-audit sink shared across scheduler incarnations: every
    # cycle of every incarnation cross-checks the incremental fleet model
    # (persistent pools, carve/release deltas, notebook rv-cache) against a
    # from-scratch rebuild + full replay. One surviving mismatch anywhere
    # in the hostile timeline fails the seed.
    diff_failures: list[str] = []

    def build(shard_id: int = 0) -> Manager:
        m = Manager(
            cluster, clock=clock, tracer=tracer,
            enqueue_filter=(
                sharding.shard_enqueue_filter(router, shard_id)
                if router is not None
                else None
            ),
        )
        m.register(
            NotebookReconciler(
                cfg, culler=culler, recorder=EventRecorder(clock=clock),
                timeline=TimelineRecorder(slo=slo, clock=clock),
            )
        )
        # a crash-restart loses every bit of in-memory scheduler state —
        # a fresh reconciler instance models exactly that (the incremental
        # model, fit cache, and notebook cache all start cold)
        sched_rec = SchedulerReconciler(
            metrics=shard_metrics[shard_id],
            recorder=EventRecorder(clock=clock),
            clock=clock,
            aging_interval_s=SOAK_AGING_INTERVAL_S,
            differential_audit=True,
            families=(
                router.families_for(shard_id) if router is not None else None
            ),
            router=router,
            shard_id=shard_id,
        )
        sched_rec.audit_failures = diff_failures
        m.register(sched_rec)
        return m

    scenario.setup(base)
    managers = [build(i) for i in range(shards if router is not None else 1)]
    violations: list[str] = []
    restarts = 0
    # the leader-kill target: ONE shard's leader dies repeatedly, every
    # round — the other shards must keep converging their slices while the
    # victim's takeover starts cold and adopts whatever it finds
    kill_target = seed % shards if router is not None else None

    def tick() -> None:
        nonlocal restarts
        for idx in range(len(managers)):
            for _ in range(max_restarts_per_tick):
                crashed = False
                try:
                    managers[idx].tick()
                except Exception:
                    crashed = True
                if chaos is not None and chaos.take_crash():
                    crashed = True
                if not crashed:
                    break
                restarts += 1
                managers[idx].shutdown()
                managers[idx] = build(idx)

    def drive(where: str, *, sub_ticks: int = 3, dt: float = 10.0) -> None:
        for s in range(sub_ticks):
            cluster.step_kubelet()
            if chaos is not None:
                chaos.tick_watches()
            ledger.tick(force=True)
            tick()
            if chaos is not None:
                lat = chaos.take_latency()
                if lat:
                    clock.advance(lat)
            sub_where = f"{where}.{s}"
            violations.extend(
                audit_placements(base, strict=False, where=sub_where)
            )
            for m in managers:
                violations.extend(
                    check_invariants(
                        base, m,
                        max_requeue_s=SOAK_MAX_REQUEUE_S,
                        where=sub_where,
                    )
                )
        clock.advance(dt)

    for r, ops in enumerate(scenario.rounds):
        for op in ops:
            scenario.apply(base, op, r)
        if kill_target is not None:
            # that shard's leader loses its lease: stand-down tears the
            # manager away mid-whatever, the takeover builds a cold one
            restarts += 1
            managers[kill_target].shutdown()
            managers[kill_target] = build(kill_target)
        drive(f"round {r}")

    scenario.heal_data_plane(base)
    if chaos is not None:
        chaos.heal()

    # settle past the cull threshold (60 s) and the backoff cap (64 s)
    for s in range(6):
        drive(f"settle {s}", sub_ticks=2, dt=45.0)

    # quiesce: the normalized store must stop changing even as the clock
    # keeps crossing aging intervals (continuous aging keeps order stable)
    prev = None
    quiesced = False
    for s in range(20):
        cluster.step_kubelet()
        ledger.tick(force=True)
        tick()
        fp = fingerprint(base)
        if fp == prev:
            quiesced = True
            break
        prev = fp
        clock.advance(65.0)
    for m in managers:
        violations.extend(
            check_invariants(
                base, m,
                max_requeue_s=SOAK_MAX_REQUEUE_S,
                where="final", final=True,
            )
        )
    violations.extend(audit_fixed_point(base, clock()))
    if router is not None:
        violations.extend(audit_shards(base, router, where="final"))
    if explain_audit:
        # explanation audit (docs/scheduler.md "explainability"): every
        # claim in every emitted placement explanation re-proven against
        # the ground-truth fleet — a verdict that says "no pool fits" while
        # the shape packs into real free space fails the seed. With a
        # router, also proves each explanation carries its OWNING shard's
        # stamp.
        violations.extend(
            explain_mod.audit_explanations(base, router=router, where="final")
        )
    if ledger_audit:
        # conservation audit (docs/chaos.md "efficiency ledger"): per seed,
        # Σ buckets == ∫ capacity dt exactly — across every drain, flap,
        # preemption handoff, and crash-restart in the timeline
        violations.extend(ledger.audit(where="final"))
    # incremental-vs-from-scratch model divergence anywhere in the run
    violations.extend(diff_failures)
    # causality + event-storm audits (obs/): every write attributable to a
    # reconcile span; Event dedup bounded under crash-restart loops
    violations.extend(tracer.audit())
    violations.extend(audit_events(base, where="final"))
    # timeline audit: every gang's startup timeline gap-free, monotone,
    # phase-partitioned — queue waits must land in the scheduler-owned
    # 'queued' phase, never smeared across layers (docs/observability.md)
    violations.extend(audit_timeline(base, where="final"))
    if chaos is not None:
        # lost-update audit (docs/chaos.md): a condition/status write whose
        # base rv went stale fails the seed at the WRITE, not via whatever
        # double-booking it would eventually cause
        violations.extend(chaos.lost_update_findings)
    return SchedSeedResult(
        seed=seed,
        violations=violations,
        quiesced=quiesced,
        restarts=restarts,
        binds=int(sum(m.binds.get() for m in shard_metrics)),
        preemptions=int(sum(m.preemptions.get() for m in shard_metrics)),
        fault_counts=(
            chaos.fault_counts if chaos is not None else collections.Counter()
        ),
        shards=shards,
    )
