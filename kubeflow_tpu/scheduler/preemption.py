"""Preemption and backfill policy.

Preemption runs only for the head of the queue — the single gang whose
delay defines everyone else's (strict priority order). Victims must be
strictly junior to the head: lower base priority, or same priority but
queued later. Eligible victims are tried in policy order — **lowest
priority first, then youngest, then fewest chips** — and eviction is
greedy-minimal: stop at the first prefix whose removal actually fits the
head, evict nothing if even the full set would not (useless evictions are
worse than waiting; a victim evicted without freeing enough space for the
head would thrash forever).

Backfill fills the holes behind a blocked head: gangs further down the
queue may bind now iff they are strictly smaller than the head (a backfill
as large as the head could simply *be* the head) and fit current free
space. Without run-time estimates there is no reservation to respect;
fairness is restored by aging — a backfilled junior gang is preemptible the
moment the aged head can use its chips.
"""
from __future__ import annotations

import dataclasses

from kubeflow_tpu.scheduler.fleet import Fleet
from kubeflow_tpu.scheduler.queue import GangRequest
from kubeflow_tpu.tpu.topology import SliceTopology

DEFAULT_BACKFILL_WINDOW = 32


@dataclasses.dataclass(frozen=True)
class BoundGang:
    """A gang currently holding capacity (rebuilt from its annotation)."""

    key: str
    priority: int
    queued_at: float
    chips: int
    # carried so an evicted victim re-enters the same cycle's queue with its
    # real request (and its original queued_at: seniority survives eviction)
    topo: SliceTopology
    num_slices: int

    def as_request(self) -> GangRequest:
        return GangRequest(
            key=self.key,
            priority=self.priority,
            queued_at=self.queued_at,
            topo=self.topo,
            num_slices=self.num_slices,
        )


def eligible_victim(victim: BoundGang, head: GangRequest) -> bool:
    if victim.priority != head.priority:
        return victim.priority < head.priority
    return victim.queued_at > head.queued_at


def select_victims(
    fleet: Fleet,
    bound: list[BoundGang],
    head: GangRequest,
    *,
    suspending: frozenset | set | None = None,
) -> list[BoundGang] | None:
    """Minimal victim prefix whose eviction lets the head bind, or None.

    Pure trial: simulates on a clone, never mutates ``fleet`` — the caller
    commits evictions through the cluster (annotation removal) so a crash
    between evict and bind leaves only re-queued victims, never a
    double-booking. The clone also means the trial is blind to the
    controller's negative-fit cache by construction: victim space is not
    free space, so a cached "doesn't fit" verdict must never veto an
    eviction that would make the head fit. Candidates are scoped to the
    head's accelerator: evicting a gang whose chips the head cannot use
    frees nothing for it (the greedy prefix would evict junior cross-accel
    gangs pointlessly before reaching a victim that matters).

    ``suspending``: gang keys already inside a deadline-bearing suspend
    handoff (a prior preemption, or a spot revocation — capacity/). Those
    order STRICTLY before every priority-based victim: their teardown is
    already paid for, so counting them first both avoids evicting a second
    gang for space the barrier is about to free anyway and keeps repeat
    victim selection stable across the cycles a handoff spans.
    """
    accel = head.topo.accelerator.name
    in_flight = suspending or frozenset()
    candidates = sorted(
        (
            v for v in bound
            if v.topo.accelerator.name == accel and eligible_victim(v, head)
        ),
        key=lambda v: (
            v.key not in in_flight, v.priority, -v.queued_at, v.chips, v.key,
        ),
    )
    if not candidates:
        return None
    trial = fleet.clone()
    evicted: list[BoundGang] = []
    for victim in candidates:
        trial.free_gang(victim.key)
        evicted.append(victim)
        if trial.place_gang(head.key, head.topo, head.num_slices) is not None:
            return evicted
    return None


def backfill_candidates(
    queue_order: list[GangRequest],
    head: GangRequest,
    *,
    window: int = DEFAULT_BACKFILL_WINDOW,
) -> list[GangRequest]:
    """Gangs behind a blocked head allowed to try the holes it cannot use.

    Scoped to the head's accelerator: a blocked v4 head says nothing about
    v5e capacity, so gangs for other accelerators are never held behind it —
    they get their own head (cross-accel head-of-line blocking would starve
    a gang on an idle pool of a different generation forever)."""
    accel = head.topo.accelerator.name
    behind = [
        r for r in queue_order
        if r.key != head.key and r.topo.accelerator.name == accel
    ]
    return [r for r in behind[:window] if r.chips < head.chips]
