"""Fleet capacity model: node pools as free/used torus cuboids.

A ``Pool`` is one TPU slice node pool — a torus of chips whose shape comes
from the nodes' ``gke-tpu-topology`` label — tracked at host-block
granularity. Its state is the *used* cuboid set (bound gangs plus blocked
cells for unavailable hosts); the free set is always derived from it
(``binpack.decompose_free``), so freeing a gang coalesces by construction.

``Fleet`` aggregates pools from live Node objects and carries the gang
operations the scheduler controller uses: all-or-nothing trial placement of
a multi-slice gang, occupancy replay from committed placement annotations,
and the accounting the metrics layer scrapes. The fleet is rebuilt from the
cluster every scheduling cycle — the annotation set IS the store of record,
which is what makes crash-restart between bind writes safe: a restarted
scheduler replays committed placements before computing new ones.
"""
from __future__ import annotations

import math
import re
from typing import Iterable, Mapping, Sequence

from kubeflow_tpu.scheduler import HOST_INDEX_LABEL, POOL_LABEL
from kubeflow_tpu.scheduler import binpack
from kubeflow_tpu.scheduler.binpack import Cuboid, ceil_div_shape
from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    SliceTopology,
    TpuAccelerator,
    parse_topology,
)

_TRAILING_ORDINAL = re.compile(r"-(\d+)$")
_BLOCKED_PREFIX = "!node/"  # used-set keys for unavailable host cells


def node_is_available(node: Mapping) -> bool:
    """Schedulable = Ready and not cordoned (``spec.unschedulable``)."""
    if (node.get("spec") or {}).get("unschedulable"):
        return False
    for cond in (node.get("status") or {}).get("conditions", []) or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def _host_index(node: Mapping) -> int | None:
    labels = node.get("metadata", {}).get("labels", {}) or {}
    idx = labels.get(HOST_INDEX_LABEL)
    if idx is not None:
        try:
            return int(idx)
        except ValueError:
            return None
    m = _TRAILING_ORDINAL.search(node.get("metadata", {}).get("name", ""))
    return int(m.group(1)) if m else None


class Pool:
    """One node pool's torus, occupied by gang cuboids (host-block units)."""

    def __init__(
        self,
        name: str,
        accel: TpuAccelerator,
        chip_shape: Sequence[int],
        *,
        labeled: bool = True,
    ) -> None:
        self.name = name
        self.accel = accel
        # False when the name was synthesized (nodes carry no nodepool
        # label): the bind then must not be pinned via that label — no node
        # would match and the gang's pods would stay Pending forever.
        self.labeled = labeled
        self.chip_shape = tuple(chip_shape)
        self.grid = ceil_div_shape(self.chip_shape, accel.host_block)
        self.num_hosts = math.prod(self.grid)
        # host ordinal -> node name, C-order over the block grid (matches
        # add_tpu_node_pool's per-host fan-out and GKE's worker numbering)
        self.nodes: dict[int, str] = {}
        self.used: dict[str, Cuboid] = {}

    # ------------------------------------------------------------- geometry

    def _coord(self, host_index: int) -> tuple[int, ...]:
        coord = []
        rem = host_index
        for dim in reversed(self.grid):
            coord.append(rem % dim)
            rem //= dim
        return tuple(reversed(coord))

    def _ordinal(self, coord: Sequence[int]) -> int:
        out = 0
        for c, dim in zip(coord, self.grid):
            out = out * dim + c
        return out

    def add_host(self, index: int, node_name: str, available: bool) -> None:
        if index < 0 or index >= self.num_hosts:
            return
        self.nodes[index] = node_name
        if not available:
            self.block_host(index)

    def block_host(self, index: int) -> None:
        """Mark one host cell unusable (drained / cordoned / NotReady)."""
        self.used[f"{_BLOCKED_PREFIX}{index}"] = Cuboid(
            self._coord(index), (1,) * len(self.grid)
        )

    def missing_hosts(self) -> None:
        """Block every host cell with no backing Node (capacity flap: the
        node object is gone, its chips with it)."""
        for i in range(self.num_hosts):
            if i not in self.nodes:
                self.block_host(i)

    def nodes_for(self, block_cuboid: Cuboid) -> list[str]:
        return sorted(
            self.nodes.get(self._ordinal(c), f"<missing-{self._ordinal(c)}>")
            for c in block_cuboid.cells()
        )

    # ------------------------------------------------------------ occupancy

    def place(
        self, topo: SliceTopology
    ) -> tuple[Cuboid, tuple[int, ...]] | None:
        return binpack.best_fit(
            self.grid, self.used.values(), self.accel, topo.shape
        )

    def occupy(self, key: str, block_cuboid: Cuboid) -> bool:
        """Commit (or replay) an allocation; False if invalid/conflicting."""
        if not block_cuboid.within(self.grid):
            return False
        if any(block_cuboid.overlaps(c) for c in self.used.values()):
            return False
        self.used[key] = block_cuboid
        return True

    def free(self, key: str) -> None:
        self.used.pop(key, None)

    def gang_keys(self) -> list[str]:
        return [k for k in self.used if not k.startswith(_BLOCKED_PREFIX)]

    # ----------------------------------------------------------- accounting

    @property
    def total_chips(self) -> int:
        return math.prod(self.chip_shape)

    @property
    def chips_per_block(self) -> int:
        return self.accel.chips_per_host

    def used_chips(self) -> int:
        return sum(
            c.volume * self.chips_per_block for c in self.used.values()
        )

    def free_chips(self) -> int:
        return self.total_chips - self.used_chips()

    def clone(self) -> "Pool":
        out = Pool(self.name, self.accel, self.chip_shape, labeled=self.labeled)
        out.nodes = dict(self.nodes)
        out.used = dict(self.used)  # Cuboids are frozen; shallow is enough
        return out


class Fleet:
    """Every pool, plus gang-level (all-or-nothing) operations."""

    def __init__(self, pools: Mapping[str, Pool] | None = None) -> None:
        self.pools: dict[str, Pool] = dict(pools or {})

    @classmethod
    def from_nodes(cls, nodes: Iterable[Mapping]) -> "Fleet":
        """Build the capacity model from live Node objects. Nodes without
        the TPU topology labels are not TPU hosts and are ignored; a pool's
        torus shape must be consistent across its nodes (first node wins —
        a mislabeled straggler cannot corrupt the whole pool)."""
        fleet = cls()
        for node in nodes:
            labels = node.get("metadata", {}).get("labels", {}) or {}
            gke_accel = labels.get("cloud.google.com/gke-tpu-accelerator")
            topology = labels.get("cloud.google.com/gke-tpu-topology")
            if not gke_accel or not topology:
                continue
            accel = next(
                (a for a in ACCELERATORS.values()
                 if a.gke_accelerator == gke_accel),
                None,
            )
            if accel is None:
                continue
            labeled = POOL_LABEL in labels
            pool_name = labels.get(POOL_LABEL) or f"{accel.name}-{topology}"
            pool = fleet.pools.get(pool_name)
            if pool is None:
                try:
                    topo = parse_topology(accel.name, topology)
                except ValueError:
                    continue
                pool = Pool(pool_name, accel, topo.shape, labeled=labeled)
                fleet.pools[pool_name] = pool
            idx = _host_index(node)
            if idx is None:
                continue
            pool.add_host(
                idx, node.get("metadata", {}).get("name", ""),
                node_is_available(node),
            )
        for pool in fleet.pools.values():
            pool.missing_hosts()
        return fleet

    def clone(self) -> "Fleet":
        return Fleet({n: p.clone() for n, p in self.pools.items()})

    # ------------------------------------------------------ gang operations

    def place_gang(
        self, key: str, topo: SliceTopology, num_slices: int = 1
    ) -> list[dict] | None:
        """All-or-nothing placement of every slice of a gang.

        Slices place independently (multislice joins over DCN, so slices
        may land in different pools); each takes the best-fit across all
        pools. Commits into this fleet on success; on any slice missing,
        rolls back and returns None.
        """
        committed: list[tuple[Pool, str]] = []
        slices: list[dict] = []
        for j in range(num_slices):
            best: tuple[tuple[int, str], Pool, Cuboid, tuple[int, ...]] | None = None
            for pool in sorted(self.pools.values(), key=lambda p: p.name):
                if pool.accel.name != topo.accelerator.name:
                    continue
                fit = pool.place(topo)
                if fit is None:
                    continue
                block_cuboid, chips = fit
                # tightest pool first: least free chips remaining after the
                # placement packs gangs together, preserving large holes
                score = (pool.free_chips() - topo.num_chips, pool.name)
                if best is None or score < best[0]:
                    best = (score, pool, block_cuboid, chips)
            if best is None:
                for pool, k in committed:
                    pool.free(k)
                return None
            _, pool, block_cuboid, chips = best
            slice_key = f"{key}/s{j}"
            pool.occupy(slice_key, block_cuboid)
            committed.append((pool, slice_key))
            slices.append(
                {
                    "pool": pool.name,
                    "poolLabeled": pool.labeled,
                    "accelerator": pool.accel.name,
                    "poolTopology": "x".join(map(str, pool.chip_shape)),
                    "offset": [
                        o * b
                        for o, b in zip(
                            block_cuboid.offset, pool.accel.host_block
                        )
                    ],
                    "shape": list(chips),
                    "nodes": pool.nodes_for(block_cuboid),
                }
            )
        return slices

    def occupy_gang(self, key: str, slices: list[dict]) -> bool:
        """Replay a committed placement annotation into the model.

        False if any slice is invalid — unknown pool, misaligned offset,
        out of bounds, or overlapping an earlier occupant (including blocked
        cells of drained hosts): the caller must then unbind the gang.
        All-or-nothing: a partial replay is rolled back.
        """
        committed: list[tuple[Pool, str]] = []
        for j, s in enumerate(slices):
            pool = self.pools.get(s.get("pool", ""))
            if pool is None:
                break
            offset = s.get("offset") or []
            shape = s.get("shape") or []
            if len(offset) != len(pool.grid) or len(shape) != len(pool.grid):
                break
            if any(o % b for o, b in zip(offset, pool.accel.host_block)):
                break
            cuboid = Cuboid(
                tuple(o // b for o, b in zip(offset, pool.accel.host_block)),
                ceil_div_shape(shape, pool.accel.host_block),
            )
            if not pool.occupy(f"{key}/s{j}", cuboid):
                break
            committed.append((pool, f"{key}/s{j}"))
        else:
            return True
        for pool, k in committed:
            pool.free(k)
        return False

    def free_gang(self, key: str) -> None:
        prefix = f"{key}/s"
        for pool in self.pools.values():
            for k in [k for k in pool.used if k.startswith(prefix)]:
                pool.free(k)

    def feasible_on_empty(
        self, topo: SliceTopology, num_slices: int = 1
    ) -> bool:
        """Could this gang EVER bind — on a fully drained fleet with every
        host healthy? False means Unschedulable (a topology no pool can
        hold), not merely queued."""
        empty = Fleet(
            {
                n: Pool(p.name, p.accel, p.chip_shape)
                for n, p in self.pools.items()
            }
        )
        for n, p in self.pools.items():
            empty.pools[n].nodes = dict(p.nodes)
        return empty.place_gang("probe", topo, num_slices) is not None

    # ----------------------------------------------------------- accounting

    def total_chips(self) -> int:
        return sum(p.total_chips for p in self.pools.values())

    def used_chips(self) -> int:
        return sum(p.used_chips() for p in self.pools.values())

    def utilization(self) -> float:
        total = self.total_chips()
        return (self.used_chips() / total) if total else 0.0

    def assert_no_overlap(self) -> list[str]:
        """Double-booking audit over the in-memory model (the soak audits
        the cluster-state analog from annotations). Empty == healthy."""
        out = []
        for pool in self.pools.values():
            entries = sorted(pool.used.items())
            for i, (ka, ca) in enumerate(entries):
                if not ca.within(pool.grid):
                    out.append(f"{pool.name}: {ka} out of bounds {ca}")
                for kb, cb in entries[i + 1:]:
                    if ca.overlaps(cb):
                        out.append(
                            f"{pool.name}: {ka} overlaps {kb} ({ca} vs {cb})"
                        )
        return out
