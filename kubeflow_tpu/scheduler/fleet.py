"""Fleet capacity model: node pools as free/used torus cuboids.

A ``Pool`` is one TPU slice node pool — a torus of chips whose shape comes
from the nodes' ``gke-tpu-topology`` label — tracked at host-block
granularity. Its state is the *used* cuboid set (bound gangs plus blocked
cells for unavailable hosts); the free set is always derived from it
(``binpack.decompose_free``), so freeing a gang coalesces by construction.

``Fleet`` aggregates pools from live Node objects and carries the gang
operations the scheduler controller uses: all-or-nothing trial placement of
a multi-slice gang, occupancy replay from committed placement annotations,
and the accounting the metrics layer scrapes. The annotation set IS the
store of record, which is what makes crash-restart between bind writes
safe: a restarted scheduler replays committed placements before computing
new ones.

``FleetModel`` is the incremental fast path over that contract: a fleet
carried *across* scheduling cycles. Node changes rebuild only the pool they
touch (per-pool fingerprints), committed placements are applied/released as
carve/coalesce deltas against each pool's persistent free decomposition
(``binpack.FreeSet``), and every event that can turn a failed fit into a
successful one — a release, a drain-undo, a capacity grant — bumps the
pool's ``epoch``, the negative-fit cache's invalidation token. Correctness
still rests on the from-scratch semantics: a fresh incarnation rebuilds
everything, and the soak differentially audits the incremental model
against ``Fleet.from_nodes`` + full replay every cycle.
"""
from __future__ import annotations

import math
import re
from typing import Iterable, Mapping, MutableMapping, Sequence

from kubeflow_tpu.scheduler import (
    HOST_INDEX_LABEL,
    POOL_LABEL,
    REVOKED_ANNOTATION,
)
from kubeflow_tpu.scheduler import binpack
from kubeflow_tpu.scheduler.binpack import Cuboid, ceil_div_shape
from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    SliceTopology,
    TpuAccelerator,
    accelerator_for_gke_label,
    parse_topology,
)

_TRAILING_ORDINAL = re.compile(r"-(\d+)$")
_BLOCKED_PREFIX = "!node/"  # used-set keys for unavailable host cells


def node_is_revoked(node: Mapping) -> bool:
    """Spot revocation notice served on this node (capacity/): the node is
    still Ready and its pods still run — cordoning it outright would evict
    the gang mid-snapshot — but NEW gangs must not bind into a pool whose
    chips are leaving. ``place_gang`` skips revoked pools; replay of
    committed placements is untouched (existing gangs keep their chips
    through the suspend barrier until release or the provider's kill)."""
    anns = (node.get("metadata") or {}).get("annotations", {}) or {}
    return REVOKED_ANNOTATION in anns


def node_is_available(node: Mapping) -> bool:
    """Schedulable = Ready and not cordoned (``spec.unschedulable``)."""
    if (node.get("spec") or {}).get("unschedulable"):
        return False
    for cond in (node.get("status") or {}).get("conditions", []) or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def _host_index(node: Mapping) -> int | None:
    labels = node.get("metadata", {}).get("labels", {}) or {}
    idx = labels.get(HOST_INDEX_LABEL)
    if idx is not None:
        try:
            return int(idx)
        except ValueError:
            return None
    m = _TRAILING_ORDINAL.search(node.get("metadata", {}).get("name", ""))
    return int(m.group(1)) if m else None


class Pool:
    """One node pool's torus, occupied by gang cuboids (host-block units)."""

    def __init__(
        self,
        name: str,
        accel: TpuAccelerator,
        chip_shape: Sequence[int],
        *,
        labeled: bool = True,
    ) -> None:
        self.name = name
        self.accel = accel
        # False when the name was synthesized (nodes carry no nodepool
        # label): the bind then must not be pinned via that label — no node
        # would match and the gang's pods would stay Pending forever.
        self.labeled = labeled
        # Spot revocation in flight (any node carries REVOKED_ANNOTATION):
        # NEW binds are refused (place_gang skips the pool) while committed
        # placements keep replaying — pods stay up through the suspend
        # barrier until release or the provider's kill.
        self.revoked = False
        self.chip_shape = tuple(chip_shape)
        self.grid = ceil_div_shape(self.chip_shape, accel.host_block)
        self.num_hosts = math.prod(self.grid)
        # host ordinal -> node name, C-order over the block grid (matches
        # add_tpu_node_pool's per-host fan-out and GKE's worker numbering)
        self.nodes: dict[int, str] = {}
        # the used map and the free decomposition move in lockstep: mutate
        # only through occupy()/free()/block_host()/clear_used(), never the
        # dict directly (the FreeSet would silently drift)
        self.used: dict[str, Cuboid] = {}
        self.free_space = binpack.FreeSet(self.grid)
        # Invalidation token for the negative-fit cache: bumped by every
        # event that can turn "doesn't fit" into "fits" — a release, a
        # rebuild after node changes (FleetModel keeps it monotonic across
        # rebuilds). Carves never bump it: shrinking free space cannot
        # un-prove a failed fit.
        self.epoch = 0
        # Occupancy version: bumped by EVERY free-space mutation, carves
        # included (unlike the epoch — a carve can turn "fragmented" into
        # "insufficient", so the explanation layer must re-judge on it).
        # FleetModel keeps it monotonic across rebuilds too.
        self.version = 0

    # ------------------------------------------------------------- geometry

    def _coord(self, host_index: int) -> tuple[int, ...]:
        coord = []
        rem = host_index
        for dim in reversed(self.grid):
            coord.append(rem % dim)
            rem //= dim
        return tuple(reversed(coord))

    def _ordinal(self, coord: Sequence[int]) -> int:
        out = 0
        for c, dim in zip(coord, self.grid):
            out = out * dim + c
        return out

    def add_host(self, index: int, node_name: str, available: bool) -> None:
        if index < 0 or index >= self.num_hosts:
            return
        self.nodes[index] = node_name
        if not available:
            self.block_host(index)

    def block_host(self, index: int) -> None:
        """Mark one host cell unusable (drained / cordoned / NotReady)."""
        key = f"{_BLOCKED_PREFIX}{index}"
        if key in self.used:
            return
        cub = Cuboid(self._coord(index), (1,) * len(self.grid))
        self.used[key] = cub
        self.free_space.carve(cub)
        self.version += 1

    def missing_hosts(self) -> None:
        """Block every host cell with no backing Node (capacity flap: the
        node object is gone, its chips with it)."""
        for i in range(self.num_hosts):
            if i not in self.nodes:
                self.block_host(i)

    def nodes_for(self, block_cuboid: Cuboid) -> list[str]:
        return sorted(
            self.nodes.get(self._ordinal(c), f"<missing-{self._ordinal(c)}>")
            for c in block_cuboid.cells()
        )

    # ------------------------------------------------------------ occupancy

    def place(
        self, topo: SliceTopology
    ) -> tuple[Cuboid, tuple[int, ...]] | None:
        return binpack.best_fit_free(self.free_space, self.accel, topo.shape)

    def occupy(self, key: str, block_cuboid: Cuboid) -> bool:
        """Commit (or replay) an allocation; False if invalid/conflicting.
        Conflict detection is O(request cells): free = grid minus used, so
        "every requested cell is free" is exactly "overlaps nothing"."""
        if key in self.used or not block_cuboid.within(self.grid):
            return False
        free_cells = self.free_space.cells
        if any(c not in free_cells for c in block_cuboid.cells()):
            return False
        self.used[key] = block_cuboid
        self.free_space.carve(block_cuboid)
        self.version += 1
        return True

    def free(self, key: str) -> None:
        cub = self.used.pop(key, None)
        if cub is not None:
            self.free_space.release(cub)
            self.epoch += 1
            self.version += 1

    def clear_used(self) -> None:
        """Drop every occupant and blocked cell (audit helper: judge
        geometry against a fully healthy, empty pool)."""
        self.used.clear()
        self.free_space = binpack.FreeSet(self.grid)
        self.epoch += 1
        self.version += 1

    def gang_keys(self) -> list[str]:
        return [k for k in self.used if not k.startswith(_BLOCKED_PREFIX)]

    # ----------------------------------------------------------- accounting

    @property
    def total_chips(self) -> int:
        return math.prod(self.chip_shape)

    @property
    def chips_per_block(self) -> int:
        return self.accel.chips_per_host

    def used_chips(self) -> int:
        # free cells are tracked, so occupancy is O(1) per query
        return (self.num_hosts - len(self.free_space.cells)) * self.chips_per_block

    def free_chips(self) -> int:
        return self.total_chips - self.used_chips()

    def free_cells(self) -> int:
        return len(self.free_space.cells)

    def clone(self) -> "Pool":
        out = Pool.__new__(Pool)
        out.name = self.name
        out.accel = self.accel
        out.labeled = self.labeled
        out.revoked = self.revoked
        out.chip_shape = self.chip_shape
        out.grid = self.grid
        out.num_hosts = self.num_hosts
        out.nodes = dict(self.nodes)
        out.used = dict(self.used)  # Cuboids are frozen; shallow is enough
        out.free_space = self.free_space.clone()
        out.epoch = self.epoch
        out.version = self.version
        return out


# One TPU node flattened into the fields the pool model is a function of:
# (accel name, topology label, labeled, host index, node name, available,
# revoked). A pool's node-entry list IS its fingerprint — two node snapshots
# yielding equal entry lists build equal pools, which is what lets
# FleetModel skip rebuilding untouched pools (and what makes a revocation
# notice rebuild exactly the pool it marks).
_NodeEntry = tuple[str, str, bool, int | None, str, bool, bool]


def group_tpu_nodes(
    nodes: Iterable[Mapping],
) -> dict[str, list[_NodeEntry]]:
    """Group Node objects into per-pool entry lists, preserving iteration
    order (first node wins the pool's shape, as in ``Fleet.from_nodes``).
    Nodes without the TPU labels are not TPU hosts and are ignored."""
    groups: dict[str, list[_NodeEntry]] = {}
    for node in nodes:
        labels = node.get("metadata", {}).get("labels", {}) or {}
        gke_accel = labels.get("cloud.google.com/gke-tpu-accelerator")
        topology = labels.get("cloud.google.com/gke-tpu-topology")
        if not gke_accel or not topology:
            continue
        accel = accelerator_for_gke_label(gke_accel)
        if accel is None:
            continue
        labeled = POOL_LABEL in labels
        pool_name = labels.get(POOL_LABEL) or f"{accel.name}-{topology}"
        groups.setdefault(pool_name, []).append((
            accel.name,
            topology,
            labeled,
            _host_index(node),
            node.get("metadata", {}).get("name", ""),
            node_is_available(node),
            node_is_revoked(node),
        ))
    return groups


def build_pool(name: str, entries: Sequence[_NodeEntry]) -> Pool | None:
    """One pool from its node entries: the first entry whose topology
    parses defines the torus (a mislabeled straggler cannot corrupt the
    whole pool); hosts without a backing node end up blocked."""
    pool: Pool | None = None
    for accel_name, topology, labeled, idx, node_name, available, revoked in entries:
        if pool is None:
            try:
                topo = parse_topology(accel_name, topology)
            except ValueError:
                continue
            pool = Pool(
                name, ACCELERATORS[accel_name], topo.shape, labeled=labeled
            )
        if revoked:
            # one noticed node marks the whole pool: spot reclamation takes
            # the slice, not a host (and a partial torus is useless anyway)
            pool.revoked = True
        if idx is None:
            continue
        pool.add_host(idx, node_name, available)
    if pool is not None:
        pool.missing_hosts()
    return pool


class Fleet:
    """Every pool, plus gang-level (all-or-nothing) operations."""

    def __init__(self, pools: Mapping[str, Pool] | None = None) -> None:
        self.pools: dict[str, Pool] = dict(pools or {})

    @classmethod
    def from_nodes(cls, nodes: Iterable[Mapping]) -> "Fleet":
        """Build the capacity model from live Node objects — the from-
        scratch reference path (fresh incarnations, audits, trials);
        :class:`FleetModel` maintains the same state incrementally."""
        fleet = cls()
        for pool_name, entries in group_tpu_nodes(nodes).items():
            pool = build_pool(pool_name, entries)
            if pool is not None:
                fleet.pools[pool_name] = pool
        return fleet

    def clone(self) -> "Fleet":
        return Fleet({n: p.clone() for n, p in self.pools.items()})

    # ------------------------------------------------------ gang operations

    def place_gang(
        self,
        key: str,
        topo: SliceTopology,
        num_slices: int = 1,
        *,
        fit_cache: "FitCache | None" = None,
    ) -> list[dict] | None:
        """All-or-nothing placement of every slice of a gang.

        Slices place independently (multislice joins over DCN, so slices
        may land in different pools); each takes the best-fit across all
        pools. Commits into this fleet on success; on any slice missing,
        rolls back and returns None.

        ``fit_cache`` (controller-owned) skips pools whose current epoch
        already proved this shape unplaceable. New negatives are recorded
        only against pools untouched by this gang's own trial carves — a
        rollback restores their space without an epoch bump, so a negative
        observed mid-trial could go stale. Preemption trials run on clones
        and pass no cache: victim space is not free space.
        """
        committed: list[tuple[Pool, str]] = []
        slices: list[dict] = []
        trial_pools: set[str] = set()
        pools = sorted(self.pools.values(), key=lambda p: p.name)
        for j in range(num_slices):
            best: tuple[tuple[int, str], Pool, Cuboid, tuple[int, ...]] | None = None
            for pool in pools:
                if pool.accel.name != topo.accelerator.name:
                    continue
                if pool.revoked:
                    # chips under a revocation notice are leaving: binding a
                    # fresh gang into them schedules its own eviction
                    continue
                if fit_cache is not None and fit_cache.hit(pool, topo):
                    continue
                fit = pool.place(topo)
                if fit is None:
                    if fit_cache is not None and pool.name not in trial_pools:
                        fit_cache.record_miss(pool, topo)
                    continue
                block_cuboid, chips = fit
                # tightest pool first: least free chips remaining after the
                # placement packs gangs together, preserving large holes
                score = (pool.free_chips() - topo.num_chips, pool.name)
                if best is None or score < best[0]:
                    best = (score, pool, block_cuboid, chips)
            if best is None:
                for pool, k in committed:
                    pool.free(k)
                return None
            _, pool, block_cuboid, chips = best
            slice_key = f"{key}/s{j}"
            pool.occupy(slice_key, block_cuboid)
            committed.append((pool, slice_key))
            trial_pools.add(pool.name)
            slices.append(
                {
                    "pool": pool.name,
                    "poolLabeled": pool.labeled,
                    "accelerator": pool.accel.name,
                    "poolTopology": "x".join(map(str, pool.chip_shape)),
                    "offset": [
                        o * b
                        for o, b in zip(
                            block_cuboid.offset, pool.accel.host_block
                        )
                    ],
                    "shape": list(chips),
                    "nodes": pool.nodes_for(block_cuboid),
                }
            )
        return slices

    def occupy_gang(self, key: str, slices: list[dict]) -> bool:
        """Replay a committed placement annotation into the model.

        False if any slice is invalid — unknown pool, misaligned offset,
        out of bounds, or overlapping an earlier occupant (including blocked
        cells of drained hosts): the caller must then unbind the gang.
        All-or-nothing: a partial replay is rolled back.
        """
        committed: list[tuple[Pool, str]] = []
        for j, s in enumerate(slices):
            pool = self.pools.get(s.get("pool", ""))
            if pool is None:
                break
            offset = s.get("offset") or []
            shape = s.get("shape") or []
            if len(offset) != len(pool.grid) or len(shape) != len(pool.grid):
                break
            if any(o % b for o, b in zip(offset, pool.accel.host_block)):
                break
            cuboid = Cuboid(
                tuple(o // b for o, b in zip(offset, pool.accel.host_block)),
                ceil_div_shape(shape, pool.accel.host_block),
            )
            if not pool.occupy(f"{key}/s{j}", cuboid):
                break
            committed.append((pool, f"{key}/s{j}"))
        else:
            return True
        for pool, k in committed:
            pool.free(k)
        return False

    def free_gang(self, key: str) -> None:
        prefix = f"{key}/s"
        for pool in self.pools.values():
            for k in [k for k in pool.used if k.startswith(prefix)]:
                pool.free(k)

    def feasible_on_empty(
        self, topo: SliceTopology, num_slices: int = 1
    ) -> bool:
        """Could this gang EVER bind — on a fully drained fleet with every
        host healthy? False means Unschedulable (a topology no pool can
        hold), not merely queued."""
        empty = Fleet(
            {
                n: Pool(p.name, p.accel, p.chip_shape)
                for n, p in self.pools.items()
            }
        )
        for n, p in self.pools.items():
            empty.pools[n].nodes = dict(p.nodes)
        return empty.place_gang("probe", topo, num_slices) is not None

    # ----------------------------------------------------------- accounting

    def total_chips(self) -> int:
        return sum(p.total_chips for p in self.pools.values())

    def used_chips(self) -> int:
        return sum(p.used_chips() for p in self.pools.values())

    def utilization(self) -> float:
        total = self.total_chips()
        return (self.used_chips() / total) if total else 0.0

    def accel_free_cells(self, accel_name: str) -> int:
        """Free host cells across an accelerator's pools — zero means the
        schedule loop can stop attempting fits for that accelerator
        entirely (saturation short-circuit)."""
        return sum(
            p.free_cells()
            for p in self.pools.values()
            if p.accel.name == accel_name
        )

    def geometry_signature(self) -> tuple:
        """Hashable summary of what exists (not what's occupied): the
        feasibility cache is valid exactly while this is unchanged."""
        return tuple(sorted(
            (p.name, p.accel.name, p.chip_shape)
            for p in self.pools.values()
        ))

    def assert_no_overlap(self) -> list[str]:
        """Double-booking audit over the in-memory model (the soak audits
        the cluster-state analog from annotations). Empty == healthy."""
        out = []
        for pool in self.pools.values():
            entries = sorted(pool.used.items())
            for i, (ka, ca) in enumerate(entries):
                if not ca.within(pool.grid):
                    out.append(f"{pool.name}: {ka} out of bounds {ca}")
                for kb, cb in entries[i + 1:]:
                    if ca.overlaps(cb):
                        out.append(
                            f"{pool.name}: {ka} overlaps {kb} ({ca} vs {cb})"
                        )
        return out


class FitCache:
    """Negative-fit cache: (pool, oriented shape) → the pool epoch at which
    the shape was proven unplaceable.

    A hit is valid exactly while the pool's epoch is unchanged — carves
    only shrink free space (negatives stay proven), while every release,
    drain-undo, or capacity rebuild bumps the epoch and un-sticks every
    cached verdict for that pool in one comparison. The key uses the
    *sorted* chip shape: orientations are axis permutations, so rotation-
    equivalent requests share one verdict. Cache state is advisory only —
    a fresh scheduler incarnation starts empty and merely re-proves.
    """

    __slots__ = ("entries", "hits", "misses")

    def __init__(self) -> None:
        self.entries: MutableMapping[tuple, int] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(pool: Pool, topo: SliceTopology) -> tuple:
        return (pool.name, topo.accelerator.name, tuple(sorted(topo.shape)))

    def hit(self, pool: Pool, topo: SliceTopology) -> bool:
        if self.entries.get(self._key(pool, topo)) == pool.epoch:
            self.hits += 1
            return True
        return False

    def record_miss(self, pool: Pool, topo: SliceTopology) -> None:
        self.misses += 1
        self.entries[self._key(pool, topo)] = pool.epoch


class FleetModel:
    """The fleet carried across scheduling cycles.

    Holds the live :class:`Fleet` plus the bookkeeping that makes cycle
    cost proportional to the delta: per-pool node fingerprints (a node
    add/drain/label change rebuilds only its pool) and the applied-
    placement map (committed placements are applied/released as carve/
    coalesce deltas instead of replayed from scratch). ``audit`` is the
    differential check the soak runs every cycle: the incremental state
    must equal a from-scratch ``Fleet.from_nodes`` + full replay, and each
    pool's maintained free decomposition must equal ``decompose_free`` of
    its used set.
    """

    def __init__(self) -> None:
        self.fleet = Fleet()
        self.applied: dict[str, list[dict]] = {}
        self._fingerprints: dict[str, tuple] = {}
        # epochs survive pool rebuilds (and deletions) so a rebuilt pool
        # can never alias a stale negative-fit entry
        self._epochs: dict[str, int] = {}
        # occupancy versions survive rebuilds for the same aliasing reason
        # (the explanation layer's per-pool staleness token)
        self._versions: dict[str, int] = {}

    # ------------------------------------------------------------- node side

    def refresh_nodes(self, nodes: Iterable[Mapping]) -> bool:
        """Fold a Node snapshot in; returns True if any pool changed.
        Unchanged pools (equal node-entry fingerprint) keep their object,
        their applied carves, and their epoch untouched."""
        groups = group_tpu_nodes(nodes)
        changed = False
        for name in list(self._fingerprints):
            if name not in groups:
                self._drop_pool(name)
                changed = True
        for name, entries in groups.items():
            fp = tuple(entries)
            if self._fingerprints.get(name) == fp:
                continue
            changed = True
            self._drop_pool(name)
            self._fingerprints[name] = fp
            pool = build_pool(name, entries)
            if pool is None:
                continue
            # a rebuild may have healed capacity (undrain, node back):
            # the epoch bump is what un-sticks cached negative verdicts
            epoch = self._epochs.get(name, -1) + 1
            self._epochs[name] = epoch
            pool.epoch = epoch
            # a fresh build already bumped version per blocked cell; lift it
            # past every version the old pool object ever reached
            pool.version += self._versions.get(name, -1) + 1
            self._versions[name] = pool.version
            self.fleet.pools[name] = pool
        return changed

    def _drop_pool(self, name: str) -> None:
        self._fingerprints.pop(name, None)
        pool = self.fleet.pools.pop(name, None)
        if pool is None:
            return
        self._epochs[name] = max(self._epochs.get(name, -1), pool.epoch)
        self._versions[name] = max(self._versions.get(name, -1), pool.version)
        # gangs with a slice here lose their whole application (their
        # carves died with the pool object); the placement diff re-applies
        # or unbinds them against the rebuilt geometry
        for key in [
            k for k, slices in self.applied.items()
            if any(s.get("pool") == name for s in slices)
        ]:
            self.release(key)

    # -------------------------------------------------------- placement side

    def apply(self, key: str, slices: list[dict]) -> bool:
        ok = self.fleet.occupy_gang(key, slices)
        if ok:
            self.applied[key] = slices
        return ok

    def release(self, key: str) -> None:
        self.fleet.free_gang(key)
        self.applied.pop(key, None)

    def sync_placements(
        self, desired: Mapping[str, list[dict]]
    ) -> list[str]:
        """Diff the applied set to ``desired`` (an ordered mapping — apply
        order is the caller's deterministic replay order). Releases run
        first so re-applies land in freed space. Returns the keys whose
        apply failed (capacity gone: drained/blocked/overlapping)."""
        for key in [
            k for k, s in list(self.applied.items())
            if desired.get(k) != s
        ]:
            self.release(key)
        failed = []
        for key, slices in desired.items():
            if key in self.applied:
                continue
            if not self.apply(key, slices):
                failed.append(key)
        return failed

    # ------------------------------------------------------------- the audit

    def audit(self, nodes: Iterable[Mapping]) -> list[str]:
        """Differential audit: incremental model == from-scratch rebuild.

        Rebuilds the fleet from the same Node snapshot, replays every
        applied placement, and compares pool-for-pool: geometry, the used
        map, the free-cell set, and the canonical free decomposition
        (which also cross-checks every pool's FreeSet against
        ``decompose_free`` from scratch). Empty == healthy.
        """
        out: list[str] = []
        scratch = Fleet.from_nodes(nodes)
        for key in sorted(self.applied):
            if not scratch.occupy_gang(key, self.applied[key]):
                out.append(
                    f"differential: {key} applied incrementally but "
                    f"rejected by from-scratch replay"
                )
        live, ref = self.fleet.pools, scratch.pools
        if set(live) != set(ref):
            out.append(
                f"differential: pool sets differ "
                f"(incremental {sorted(live)} vs scratch {sorted(ref)})"
            )
        for name in sorted(set(live) & set(ref)):
            p, s = live[name], ref[name]
            if (
                p.grid, p.chip_shape, p.accel.name, p.labeled, p.revoked,
                p.nodes,
            ) != (
                s.grid, s.chip_shape, s.accel.name, s.labeled, s.revoked,
                s.nodes,
            ):
                out.append(f"differential: pool {name} geometry drifted")
                continue
            if p.used != s.used:
                out.append(
                    f"differential: pool {name} used sets differ "
                    f"({sorted(p.used)} vs {sorted(s.used)})"
                )
            if p.free_space.cells != s.free_space.cells:
                out.append(f"differential: pool {name} free cells drifted")
            canonical = binpack.decompose_free(p.grid, p.used.values())
            if p.free_space.cuboids != canonical:
                out.append(
                    f"differential: pool {name} incremental free "
                    f"decomposition != decompose_free from scratch"
                )
        return out
