"""Fleet scheduler reconciler.

Runs as one more reconciler under ``runtime/manager.py``, between the
notebook controller and the cluster: a Notebook CR with ``spec.tpu`` is not
a gang until this controller binds it. The bind is a single annotation
write (``scheduling.kubeflow.org/placement``) carrying every slice's pool,
cuboid, and node set — the atomic commit point. The notebook controller
keeps its StatefulSets at 0 replicas until the annotation appears, then
pins the gang to its pool (gang gating,
``notebook_controller.generate_statefulset``).

Level-triggered and stateless across restarts: every scheduling cycle
rebuilds the fleet from Nodes and the occupancy + queue from Notebook
annotations, replays committed placements, then runs admission in priority
order with aging, preemption for blocked heads, and hole-backfill. A crash
between any two writes (armed by the chaos layer) loses nothing: the next
incarnation replays the committed annotations before computing new
placements, so two gangs can never hold overlapping cuboids.

Every Notebook or Node event maps to ONE workqueue key (``@fleet``) — the
cycle is global (placement decisions are fleet-wide), so per-object keys
would run N full cycles for N events; the deduplicating workqueue collapses
them into exactly one (SNIPPETS.md batch-scheduler idiom).

Status surface: ``Queued`` (with queue position), ``Unschedulable`` (no
pool could ever hold the topology), ``Preempted`` (victim of a higher
priority gang or a node drain) — preserved by the notebook controller's
status rewrites and translated by ``webapps/jupyter.py`` for the spawner.

Suspend barrier (``sessions/``, enabled via ``suspend_deadline_s``): the
preemption path stops killing victims outright. A selected victim gets a
suspend-request annotation instead of an eviction; its chips stay held (and
its pods stay up) until the sessions controller acks a committed snapshot —
or the force deadline passes — and only then does one atomic write release
the placement *and* retire the spent request, letting the preemptor bind.
The head stays blocked behind the handoff and backfill is suppressed for
its accelerator (a backfill into the space the victims are about to free
would invalidate the eviction trial and strand everyone). Stopped gangs get
the same courtesy: their chips are not released while the teardown barrier
still holds their pods. Everything is re-derived from annotations each
cycle, so a crash between the snapshot commit and the chip release replays
instead of double-booking (the sessions soak arms exactly that crash).
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Iterable

from kubeflow_tpu import sessions as sess
from kubeflow_tpu.api import types as api
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime import sharding
from kubeflow_tpu.runtime.fake import Conflict, FakeCluster, NotFound
from kubeflow_tpu.runtime.manager import Reconciler, Result
from kubeflow_tpu.scheduler import (
    COND_PREEMPTED,
    COND_QUEUED,
    COND_UNSCHEDULABLE,
    EXPLANATION_ANNOTATION,
    PLACEMENT_ANNOTATION,
    QUEUED_AT_ANNOTATION,
    condition,
    encode_placement,
    gang_priority,
    merge_conditions,
    placement_matches,
    placement_of,
)
from kubeflow_tpu.scheduler import explain as explain_mod
from kubeflow_tpu.scheduler import preemption as preempt
from kubeflow_tpu.scheduler.fleet import Fleet, FitCache, FleetModel
from kubeflow_tpu.scheduler.preemption import BoundGang
from kubeflow_tpu.scheduler.queue import (
    DEFAULT_AGING_INTERVAL_S,
    GangQueue,
    GangRequest,
)
from kubeflow_tpu.tpu.topology import ACCELERATORS

log = logging.getLogger(__name__)

FLEET_KEY = "@fleet"  # the single coalesced reconcile key

# Condition-signature constants for the write phase's fast path.
_SIG_BOUND = ("bound",)
_SIG_OFF = ("off",)

# Beyond this queue depth, Queued messages stop carrying exact positions:
# every bind shifts every position behind it (and shrinks the "of n"), so
# exact messages mean one status write per queued notebook per cycle —
# write-amplification whose only reader is the spawner, which shows tens.
# 100 keeps exact positions for every queue a human actually watches while
# a 10k burst stays on the static message until it drains near the front.
POSITION_MESSAGE_DEPTH = 100


class SchedulerReconciler(Reconciler):
    """Capacity-aware gang scheduler for TPU notebooks."""

    # Pseudo-kind: no object of this kind ever exists (and no API server
    # could resolve it), so the primary watch is disabled outright; all real
    # events arrive via watches() mapped to FLEET_KEY.
    kind = "SchedulerCycle"
    watch_primary = False

    def __init__(
        self,
        *,
        metrics=None,
        recorder=None,
        clock: Callable[[], float] = time.time,
        aging_interval_s: float = DEFAULT_AGING_INTERVAL_S,
        backfill_window: int = preempt.DEFAULT_BACKFILL_WINDOW,
        resync_s: float = 30.0,
        suspend_deadline_s: float | None = None,
        differential_audit: bool = False,
        families: frozenset[str] | None = None,
        router: "sharding.ShardRouter | None" = None,
        shard_id: int = 0,
        explain: bool = True,
        explain_budget: int = explain_mod.DEFAULT_EXPLAIN_BUDGET,
    ) -> None:
        self.metrics = metrics
        # EventRecorder (obs/events.py): Queued/Bound/Preempted/Unschedulable
        # become real Event objects users see in the spawner. Emitted only on
        # TRANSITIONS (first admission, a bind commit, an eviction) — an
        # every-cycle emit would bump counts once per cycle forever on a
        # full fleet, which is exactly the write amplification the recorder's
        # dedup exists to prevent.
        self.recorder = recorder
        self.clock = clock
        self.aging_interval_s = aging_interval_s
        self.backfill_window = backfill_window
        self.resync_s = resync_s
        # Suspend barrier (sessions/): None keeps the legacy immediate-evict
        # preemption; a deadline turns every eviction into a suspend-request
        # handoff bounded by it (chips release on snapshot ack or deadline,
        # whichever first).
        self.suspend_deadline_s = suspend_deadline_s
        # The workqueue already serializes the single key; the lock is a
        # belt-and-braces guard for direct _cycle() callers (bench, tests).
        self._cycle_lock = threading.Lock()
        # --- the incremental fast path (docs/scheduler.md) ---------------
        # All of this is in-memory acceleration over the same annotations-
        # are-the-store-of-record contract: a crash-restart builds a fresh
        # reconciler whose first cycle rebuilds everything from scratch.
        self._model = FleetModel()
        self._nb_cache = _NotebookCache()
        self._fit_cache = FitCache()
        self._fit_seen = (0, 0)  # (hits, misses) already flushed to metrics
        self._feasible: dict[tuple, bool] = {}
        self._feasible_sig: tuple | None = None
        self._geo_gen = 0  # bumps when fleet geometry changes (adm cache)
        # Placement explainability (scheduler/explain.py): per-gang verdict
        # state carried across cycles like the fit cache — advisory only, a
        # crash-restart starts cold and re-adopts reason/since from the
        # annotations themselves. ``explain=False`` (the bench's A/B arm
        # for measuring the layer's overhead) skips the phase entirely.
        self._explainer = (
            explain_mod.ExplainRecorder(metrics=metrics, budget=explain_budget)
            if explain
            else None
        )
        # When True, every cycle cross-checks the incremental model against
        # a from-scratch rebuild + full replay (the soak's differential
        # audit); mismatches accumulate in audit_failures.
        self.differential_audit = differential_audit
        self.audit_failures: list[str] = []
        # --- control-plane sharding (runtime/sharding.py) ----------------
        # families: the accelerator families this scheduler shard owns —
        # None (the default) is the unsharded scheduler, bit-identical to
        # the pre-sharding behavior. Pools belong to exactly one family and
        # a gang can only bind into pools of its own family, so per-family
        # shards share no free space: no chip is ever visible as free to
        # two shards, with no coordination beyond the deterministic
        # family→shard map. router/shard_id drive the ownership stamp:
        # fresh gangs are stamped inside the admission (queued-at) write;
        # gangs stamped by another generation (a SHARDS change) or shard
        # (a family edit) are adopted — re-stamped in one write — before
        # this shard schedules them.
        self.families = frozenset(families) if families is not None else None
        self._router = router
        self.shard_id = shard_id
        # Event hints (sharded only): the cycle's notebook ingest polls the
        # FAMILY_LABEL-selected rv index — O(owned slice), not O(fleet) —
        # so gangs the filtered index cannot see (created unlabeled, or
        # label drifting after a spec edit) reach the cycle through the
        # watch mapper instead: it records every owned-family event's key
        # here, and the refresh fetches hinted bodies directly. Hints are
        # cleared only after a successful refresh (at-least-once), and a
        # restart re-populates them via the manager's initial watch replay.
        self._hints: set[tuple[str, str]] = set()
        self._hints_lock = threading.Lock()

    def watches(self):
        if self.families is None:
            return [("Notebook", _map_to_fleet), ("Node", _map_to_fleet)]
        # Sharded watch ingest: only events for owned-family gangs wake this
        # shard's cycle (a CPU notebook or a foreign family is never our
        # work), and each such event leaves a hint for the filtered
        # refresh. Node events stay unfiltered — a watch event carries only
        # the node's NEW labels, so a family-label edit would be invisible
        # to the losing shard; waking every shard costs one coalesced key
        # and the cycle's node list is selector-scoped to owned families.
        return [
            ("Notebook", self._map_owned_notebook),
            ("Node", _map_to_fleet),
        ]

    def _map_owned_notebook(self, obj: dict) -> Iterable[tuple[str, str]]:
        if sharding.notebook_family(obj) in self.families:
            with self._hints_lock:
                self._hints.add((ko.namespace(obj), ko.name(obj)))
            yield ("", FLEET_KEY)

    def reconcile(self, cluster: FakeCluster, namespace: str, name: str) -> Result | None:
        with self._cycle_lock:
            queue_depth, barrier_pending = self._cycle(cluster)
        if barrier_pending:
            # a force deadline crossing has no watch event to announce it;
            # poll the handoff tightly so a wedged snapshot can't stall the
            # preemptor past the deadline
            return Result(requeue_after=min(self.resync_s, 5.0))
        if queue_depth:
            # aging changes effective priorities over time with no event to
            # announce it; periodic resync keeps a waiting queue honest
            return Result(requeue_after=min(self.resync_s, self.aging_interval_s))
        return None

    # ----------------------------------------------------------- the cycle

    def _cycle(self, cluster: FakeCluster) -> tuple[int, bool]:
        """One full scheduling pass. Returns (queue depth, barrier pending).

        The pass is phase-structured and incremental (docs/scheduler.md
        fast path): **list** polls the resourceVersion index and re-fetches
        only moved Notebook bodies; **replay** folds node deltas into the
        persistent fleet model (rebuilding only changed pools) and diffs
        committed placements as carve/release deltas instead of replaying
        every annotation; **pack** runs admission + scheduling with the
        negative-fit cache; **write** batches the status-condition updates.
        Each phase's duration (on the injected clock: real wall time in
        production and benches, zero on the soaks' virtual clock — counts
        still attribute) lands in the cycle-phase histogram.
        """
        cycle_started = self.clock()
        barrier_pending = False
        now = self.clock()

        # -- list phase ---------------------------------------------------
        if self.families is None:
            nodes = cluster.list("Node")
            views = [
                v for v in self._nb_cache.refresh(cluster)
                if v.topo is not None
            ]  # malformed spec.tpu is admission's problem; CPU has no chips
        else:
            # Sharded scheduler: this cycle's world is the owned accelerator
            # families only, selected SERVER-SIDE — both the node list and
            # the notebook rv poll carry a label selector, so the shard's
            # list phase costs O(owned slice), not O(fleet). Foreign-family
            # pools never enter the fleet model and foreign-family gangs
            # never enter the queue, so the shard cannot bind into (or even
            # see) another shard's space. Selector-scoping the NODE list is
            # also what makes a pool family-label edit converge: the node
            # vanishes from the losing shard's list, its pool fingerprint
            # changes, and the model drops the pool.
            nodes = []
            for fam in sorted(self.families):
                accel = ACCELERATORS.get(fam)
                if accel is None:
                    continue
                nodes.extend(cluster.list("Node", None, {"matchLabels": {
                    "cloud.google.com/gke-tpu-accelerator":
                        accel.gke_accelerator,
                }}))
            with self._hints_lock:
                hints = set(self._hints)
            views = [
                v
                for v in self._nb_cache.refresh_filtered(
                    cluster,
                    [
                        {"matchLabels": {sharding.FAMILY_LABEL: fam}}
                        for fam in sorted(self.families)
                    ],
                    hints,
                    self.families,
                )
                if v.topo is not None
                and v.topo.accelerator.name in self.families
            ]
            with self._hints_lock:
                # consumed only on success: a refresh that faulted replays
                # the same hints next cycle (at-least-once ingest)
                self._hints -= hints
            if self._router is not None:
                self._adopt_orphans(cluster, views)
        t_list = self.clock()

        model = self._model
        fleet = model.fleet
        queue = GangQueue(aging_interval_s=self.aging_interval_s)
        bound: dict[str, BoundGang] = {}
        nb_by_key = {v.key: v.nb for v in views}
        preempted_now: dict[str, str] = {}  # key -> human reason
        released: set[str] = set()  # suspend handoffs completed this cycle
        handoff_accels: set[str] = set()  # accels with a handoff in flight
        # bound gangs whose deadline-bearing suspend (preemption handoff or
        # spot revocation) is still in flight: preemption victim selection
        # counts these STRICTLY first — their teardown is already paid for
        suspending_bound: set[str] = set()

        # -- replay phase: placement diff against the persistent model ----
        # Desired-occupancy build runs in deterministic order (bind time
        # then key), so when a rebuilt pool can no longer hold everything,
        # the same gang loses regardless of list order.
        model.refresh_nodes(nodes)
        desired: dict[str, list[dict]] = {}  # insertion order = apply order
        barrier_hold: set[str] = set()  # teardown-barrier keys in desired
        replaying: dict[str, BoundGang] = {}  # live keys in desired
        with_placement = sorted(
            (v for v in views if v.placement is not None),
            key=lambda v: (v.placement.get("boundAt", 0.0), v.key),
        )
        for view in with_placement:
            nb, key, topo = view.nb, view.key, view.topo
            num_slices, placement = view.num_slices, view.placement
            if not _wants_capacity(nb):
                if (
                    self.suspend_deadline_s is not None
                    and not sess.suspend_complete(nb, now)
                    and not self._gang_scaled_down(cluster, nb, num_slices)
                ):
                    # teardown barrier: the gang's pods are still up waiting
                    # for their snapshot to commit — the chips stay held (a
                    # release now would bind a second gang onto hosts whose
                    # pods have not exited). Occupancy failing means the
                    # capacity itself is gone (drain/flap): nothing to hold.
                    desired[key] = placement["slices"]
                    barrier_hold.add(key)
                    continue
                # stopped/culled while bound: release the chips and clear
                # every scheduler mark — a restart re-queues from scratch
                self._unbind(cluster, nb, drop_queued_at=True)
                continue
            if not placement_matches(placement, topo, num_slices):
                # spec.tpu edited while bound: the committed placement no
                # longer describes what the gang wants — release it and let
                # the new shape queue from scratch (keeping it would run
                # the gang at the stale shape forever)
                self._unbind(cluster, nb)
                continue
            request = (
                sess.suspend_request(nb)
                if self.suspend_deadline_s is not None
                else None
            )
            req_reason = request.get("reason") if request is not None else None
            if req_reason in sess.HANDOFF_REASONS:
                if sess.suspend_complete(nb, now):
                    # the handoff's commit point: ONE write releases the
                    # placement and retires the spent request, so a crash on
                    # either side replays cleanly (chips still held, or
                    # victim fully queued — never half). The victim keeps
                    # its queued-at: seniority survives suspension. Left out
                    # of the desired set, the diff releases its chips now.
                    self._release_suspended(cluster, nb)
                    if self.metrics is not None:
                        # handoff hold time: how long the chips were gated
                        # on the victim's snapshot barrier (preemptor-bound
                        # chips, or a revoked pool's last grace seconds)
                        self.metrics.observe_handoff(
                            now - request["requestedAt"]
                        )
                    preempted_now[key] = (
                        "suspended for a higher-priority gang"
                        if req_reason == sess.REASON_PREEMPTION
                        else "suspended for a spot capacity revocation"
                    )
                    released.add(key)
                    continue
                # barrier holds: the victim keeps its chips until the
                # snapshot commits or the force deadline passes
                barrier_pending = True
                suspending_bound.add(key)
                if req_reason == sess.REASON_PREEMPTION:
                    # only a PREEMPTION handoff freezes backfill: a waiting
                    # head is owed the victims' space. A revocation's space
                    # is leaving the fleet (the capacity layer cordons it),
                    # so freezing the family would stall unrelated binds
                    # for chips nobody can inherit.
                    handoff_accels.add(topo.accelerator.name)
            desired[key] = placement["slices"]
            replaying[key] = BoundGang(
                key=key,
                priority=view.priority,
                queued_at=_queued_at(nb, now),
                chips=topo.num_chips * num_slices,
                topo=topo,
                num_slices=num_slices,
            )
        failed = set(model.sync_placements(desired))
        for key in desired:
            if key in failed:
                nb = nb_by_key.get(key)
                if key in barrier_hold:
                    if nb is not None:
                        self._unbind(cluster, nb, drop_queued_at=True)
                else:
                    # node drain / capacity flap invalidated the placement
                    if nb is not None:
                        self._unbind(cluster, nb)
                    preempted_now[key] = "placement lost to node drain"
            elif key in barrier_hold:
                barrier_pending = True
            else:
                bound[key] = replaying[key]
        t_replay = self.clock()

        # -- pack phase: queue admission ----------------------------------
        unschedulable: dict[str, str] = {}
        sig = fleet.geometry_signature()
        if sig != self._feasible_sig:
            self._feasible_sig = sig
            self._feasible.clear()
            self._geo_gen += 1
        geo_gen = self._geo_gen
        for view in views:
            if view.key in bound:
                continue
            # admission is a pure function of (notebook body, fleet
            # geometry); cache the verdict per view so 10k unchanged queued
            # gangs cost two comparisons each, not a re-parse
            adm = (
                view.admission
                if view.adm_rv == view.rv and view.adm_sig == geo_gen
                else None
            )
            if adm is None:
                adm = self._admit(cluster, fleet, view, now)
                if adm is None:
                    continue  # raced a delete/write: next cycle re-admits
                view.admission = adm
                view.adm_rv = view.rv
                view.adm_sig = geo_gen
            if adm[0] == "queued":
                queue.push(adm[1])
            elif adm[0] == "unschedulable":
                unschedulable[view.key] = adm[1]

        # Victims already released while a same-accel handoff is still in
        # flight (multi-victim preemption resolving ack by ack) carry the
        # same re-bind hazard as this cycle's releases: their preserved
        # seniority would grab the partially-freed space back before the
        # head ever gets all of it. Their Preempted=True condition (kept
        # until re-bind) identifies them durably across cycles.
        deferred = set(released)
        if handoff_accels:
            for view in views:
                if (
                    view.key not in bound
                    and view.topo.accelerator.name in handoff_accels
                    and (condition(view.nb, COND_PREEMPTED) or {}).get(
                        "status") == "True"
                ):
                    deferred.add(view.key)

        # -- pack phase: the scheduling pass ------------------------------
        newly_bound, handoffs, pack_notes = self._schedule(
            cluster, fleet, queue, bound, preempted_now, now, nb_by_key,
            deferred, suspending_bound,
        )
        barrier_pending = barrier_pending or handoffs
        t_pack = self.clock()

        # -- explain phase (scheduler/explain.py): every gang the pack
        # phase actually judged and failed — admission-unschedulable gangs,
        # blocked heads, attempted-but-failed backfills, handoff-frozen
        # waiters — gets the structured per-pool verdict trail as ONE
        # annotation write per transition. Gangs the pass never attempted
        # (behind a head, outside the backfill window) carry no explanation:
        # a verdict nobody re-proves each cycle would go stale and lie.
        if self._explainer is not None:
            self._explain(cluster, fleet, views, bound, newly_bound,
                          unschedulable, pack_notes, now)
        t_explain = self.clock()

        # -- write phase: status conditions + metrics ---------------------
        # The loop is the batched write pass: desired conditions reduce to
        # a cheap signature per view, checked against the last written one
        # BEFORE any condition dicts are built or status lists scanned —
        # at 10k steady queued gangs the whole phase is signature compares.
        depth = len(queue)
        if depth <= POSITION_MESSAGE_DEPTH:
            positions = {
                r.key: i + 1 for i, r in enumerate(queue.ordered(now))
            }
        else:
            # deep queue: every message is the static one, so the ordering
            # (a second 10k-entry sort) has no reader at all
            positions = None
        for view in views:
            key = view.key
            if key in bound or key in newly_bound:
                if _SIG_BOUND == view.conds_sig and view.rv == view.conds_rv:
                    continue
                self._write_conditions(cluster, view, [{
                    "type": COND_QUEUED, "status": "False",
                    "reason": "Bound", "message": "",
                }], _SIG_BOUND)
            elif key in unschedulable:
                msg = unschedulable[key]
                sig = ("unschedulable", msg)
                if sig == view.conds_sig and view.rv == view.conds_rv:
                    continue
                # the transition Event is emitted by the explain phase (it
                # carries the verdict reason and dedups on it) — unless
                # explain is off, in which case the historical transition
                # emit here keeps `kubectl get events` answering at all
                if self._explainer is None and not (
                    (condition(view.nb, COND_UNSCHEDULABLE) or {}).get(
                        "status") == "True"
                ):
                    self._emit(
                        cluster, view.nb, "Unschedulable", msg,
                        type_="Warning",
                    )
                self._write_conditions(cluster, view, [{
                    "type": COND_UNSCHEDULABLE, "status": "True",
                    "reason": "NoFittingPool",
                    "message": msg,
                }], sig)
            elif key in queue:
                if positions is not None:
                    msg = f"position {positions[key]} of {depth}"
                else:
                    # depth changes every cycle; putting it in the message
                    # would rewrite every queued notebook's status per cycle
                    msg = "waiting for TPU capacity"
                # the carried Preempted condition is NOT in the signature:
                # it is derived from .status, which cannot change without
                # an rv bump, and the rv is part of the fast-path check
                sig = ("queued", msg, preempted_now.get(key) or "")
                if sig == view.conds_sig and view.rv == view.conds_rv:
                    continue
                conds = [{
                    "type": COND_QUEUED, "status": "True",
                    "reason": "WaitingForCapacity", "message": msg,
                }]
                reason = preempted_now.get(key)
                if reason is not None:
                    conds.append({
                        "type": COND_PREEMPTED, "status": "True",
                        "reason": "Preempted", "message": reason,
                    })
                else:
                    # a victim stays marked Preempted until it binds again
                    existing = condition(view.nb, COND_PREEMPTED)
                    if existing is not None and existing.get("status") == "True":
                        conds.append(existing)
                self._write_conditions(cluster, view, conds, sig)
            elif not _wants_capacity(view.nb):
                if _SIG_OFF == view.conds_sig and view.rv == view.conds_rv:
                    continue
                self._write_conditions(cluster, view, [], _SIG_OFF)
            # any other state (raced writes, transient gaps): leave the
            # conditions untouched — the next cycle re-derives them
        t_write = self.clock()

        if self.differential_audit:
            self.audit_failures.extend(model.audit(nodes))
        if self.metrics is not None:
            # clamped like the Manager's reconcile duration: the injected
            # clock defaults to time.time in production, which can step
            # backwards (NTP) — the histograms must never see a negative
            self.metrics.observe_cycle(
                fleet,
                queue_depth=depth,
                unschedulable=len(unschedulable),
                duration_s=max(0.0, t_write - cycle_started),
                phases={
                    "list": max(0.0, t_list - cycle_started),
                    "replay": max(0.0, t_replay - t_list),
                    "pack": max(0.0, t_pack - t_replay),
                    "explain": max(0.0, t_explain - t_pack),
                    "write": max(0.0, t_write - t_explain),
                },
                # every family the fleet models reads a depth (0 when its
                # queue drained — absence means the family LEFT the fleet,
                # and its series is retired)
                family_depths={
                    **{p.accel.name: 0 for p in fleet.pools.values()},
                    **queue.family_depths(),
                },
                # fragmentation telemetry off the live free decompositions:
                # O(pools) per cycle, the defrag-trigger series the
                # live-migration and autoscaling roadmap items consume
                pool_stats={
                    name: (
                        explain_mod.fragmentation_index(p),
                        explain_mod.largest_free_cuboid_cells(p)
                        * p.chips_per_block,
                    )
                    for name, p in fleet.pools.items()
                },
            )
            if self._explainer is not None:
                self.metrics.set_would_fit_after_defrag(
                    self._explainer.would_fit_count()
                )
            hits, misses = self._fit_cache.hits, self._fit_cache.misses
            seen_h, seen_m = self._fit_seen
            self.metrics.observe_fit_cache(hits - seen_h, misses - seen_m)
            self._fit_seen = (hits, misses)
        return depth, barrier_pending

    def _adopt_orphans(self, cluster: FakeCluster, views: list) -> None:
        """Ownership stamping for gangs that already carry scheduler state
        (a queued-at claim or a committed placement) but whose stamp names
        another generation or shard: a SHARDS change, a family edit, or an
        upgrade from the pre-sharding control plane. Adoption is ONE
        annotation write and everything else replays level-triggered from
        the CR — the placement, the preserved seniority, even a suspend
        handoff mid-flight all continue under the new owner. Fresh gangs
        (no scheduler footprint yet) are NOT stamped here: their stamp is
        folded into the admission write, so entering the queue costs no
        extra write. A raced delete/write just retries next cycle."""
        stamp = self._router.stamp(self.shard_id)
        for view in views:
            anns = ko.annotations(view.nb)
            fam = view.topo.accelerator.name
            need_stamp = anns.get(sharding.SHARD_ANNOTATION) != stamp
            # heal the family label alongside the stamp: after a spec
            # family edit the old label keeps the gang in the LOSING
            # shard's filtered index and out of ours — one write moves the
            # server-side filter to the new owner
            need_label = (
                ko.labels(view.nb).get(sharding.FAMILY_LABEL) != fam
            )
            if not (need_stamp or need_label):
                continue
            if (
                QUEUED_AT_ANNOTATION not in anns
                and view.placement is None
            ):
                continue  # no footprint: admission will stamp
            try:
                self._patch_annotations(
                    cluster, view.nb,
                    {sharding.SHARD_ANNOTATION: stamp} if need_stamp else {},
                    labels={sharding.FAMILY_LABEL: fam} if need_label else None,
                )
            except (NotFound, Conflict):
                continue
            # _patch_annotations folded the stored body back into the view
            # cache, so the rest of this cycle sees the adopted stamp

    def _explain(
        self,
        cluster: FakeCluster,
        fleet: Fleet,
        views: list,
        bound: dict,
        newly_bound: set[str],
        unschedulable: dict[str, str],
        pack_notes: dict[str, dict],
        now: float,
    ) -> None:
        """The explain phase: reconcile every gang's explanation annotation
        with what the pack phase just proved about it. Steady state is
        free — the recorder's signature check (per-pool occupancy versions
        + the pack note) returns the cached encoding without touching
        geometry, and equal encodings skip the write entirely; recomputes
        are budget-bounded per cycle (overflow keeps last cycle's
        annotation; blocked gangs persist, so the budget catches up)."""
        self._explainer.begin_cycle()
        self._explainer.sweep({v.key for v in views})
        stamp = (
            self._router.stamp(self.shard_id)
            if self._router is not None
            else None
        )
        for view in views:
            key = view.key
            if key in bound or key in newly_bound:
                # the bind write itself cleared the annotation; close out
                # the time-in-reason observation
                self._explainer.clear(key, now)
                continue
            note = pack_notes.get(key)
            if note is None and key in unschedulable:
                note = {"role": "unschedulable"}
            if note is None or not _wants_capacity(view.nb):
                # not judged this cycle (stopped, or waiting behind a head
                # outside the attempted set): an explanation nobody
                # re-proves would go stale — drop it
                self._explainer.clear(key, now)
                if EXPLANATION_ANNOTATION in ko.annotations(view.nb):
                    try:
                        self._patch_annotations(
                            cluster, view.nb,
                            {EXPLANATION_ANNOTATION: None},
                        )
                    except (NotFound, Conflict):
                        pass  # next cycle retries the clear
                continue
            # adopt() first: on a fresh incarnation it resumes the persisted
            # reason/since from the annotation, so a restart neither re-emits
            # the transition Event nor resets the time-in-reason clock
            prev_reason = self._explainer.adopt(view, now)
            encoded = self._explainer.explain(
                view, fleet, note, now, shard=stamp
            )
            if encoded is None:
                continue  # budget spent: keep last write, catch up later
            reason = self._explainer.reason_of(key)
            if reason is not None and reason != prev_reason:
                # transition INTO a blocking verdict (never the steady
                # state): the deduped Unschedulable Event carries the
                # verdict, so `kubectl get events` answers "why not".
                # Emitted BEFORE the annotation patch: the recorder already
                # committed the transition (counter, since-clock), so a
                # raced patch below must not swallow the one Event — the
                # annotation itself retries via the encoding compare.
                self._emit(
                    cluster, view.nb, "Unschedulable",
                    f"{reason}: {json.loads(encoded).get('message', '')}",
                    type_="Warning",
                )
            if ko.annotations(view.nb).get(EXPLANATION_ANNOTATION) != encoded:
                try:
                    self._patch_annotations(
                        cluster, view.nb, {EXPLANATION_ANNOTATION: encoded}
                    )
                except (NotFound, Conflict):
                    continue  # raced a delete/write; next cycle retries

    def _admit(
        self,
        cluster: FakeCluster,
        fleet: Fleet,
        view: "_NbView",
        now: float,
    ) -> tuple | None:
        """One gang's admission verdict: ``("stopped",)``,
        ``("unschedulable", message)``, or ``("queued", request)`` —
        or None when a raced write means the next cycle must retry.
        Side-effecting transitions (clearing a stopped gang's queued-at,
        stamping first admission + its Event) happen here, so a cached
        verdict is always side-effect-free to replay."""
        nb, topo, num_slices = view.nb, view.topo, view.num_slices
        if not _wants_capacity(nb):
            # stopped while still queued: the queue entry must go with
            # it — a ghost queued-at would hold a phantom capacity claim
            # and resurrect stale seniority on restart. A raced delete
            # or conflicting write must not abort the whole fleet cycle
            # for a gang that holds no geometry claim; the clear is
            # retried next cycle.
            if QUEUED_AT_ANNOTATION in ko.annotations(nb):
                try:
                    self._patch_annotations(
                        cluster, nb, {QUEUED_AT_ANNOTATION: None}
                    )
                except (NotFound, Conflict):
                    return None
            return ("stopped",)
        shape_key = (topo.accelerator.name, topo.shape, num_slices)
        feasible = self._feasible.get(shape_key)
        if feasible is None:
            feasible = fleet.feasible_on_empty(topo, num_slices)
            self._feasible[shape_key] = feasible
        if not feasible:
            return ("unschedulable", (
                f"no node pool can hold {topo.slice_name}"
                + (f" x{num_slices}" if num_slices > 1 else "")
            ))
        queued_at = _queued_at(nb, None)
        if queued_at is None:
            queued_at = now
            anns: dict = {QUEUED_AT_ANNOTATION: repr(queued_at)}
            labels = None
            if self._router is not None:
                # the ownership stamp (and, when drifted, the family
                # label the filtered ingest selects on) rides the
                # admission write: one patch claims AND admits the gang
                anns[sharding.SHARD_ANNOTATION] = self._router.stamp(
                    self.shard_id
                )
                fam = topo.accelerator.name
                if ko.labels(nb).get(sharding.FAMILY_LABEL) != fam:
                    labels = {sharding.FAMILY_LABEL: fam}
            try:
                self._patch_annotations(cluster, nb, anns, labels=labels)
            except (NotFound, Conflict):
                return None  # deleted/raced: next cycle re-admits
            # first admission is the transition worth an Event; the
            # queued-at annotation makes it exactly-once per wait
            self._emit(
                cluster, nb, "Queued",
                f"gang admitted to the TPU capacity queue "
                f"({topo.slice_name}"
                + (f" x{num_slices}" if num_slices > 1 else "") + ")",
            )
        return ("queued", self._request_for(view, queued_at))

    @staticmethod
    def _request_for(view: "_NbView", queued_at: float) -> GangRequest:
        """The view's GangRequest, rebuilt only when its inputs moved
        (an rv change resets it; a queued-at (re)stamp changes the value)."""
        req = view.request
        if req is None or req.queued_at != queued_at:
            req = GangRequest(
                key=view.key,
                priority=view.priority,
                queued_at=queued_at,
                topo=view.topo,
                num_slices=view.num_slices,
            )
            view.request = req
        return req

    def _schedule(
        self,
        cluster: FakeCluster,
        fleet: Fleet,
        queue: GangQueue,
        bound: dict[str, BoundGang],
        preempted_now: dict[str, str],
        now: float,
        nb_by_key: dict[str, dict] | None = None,
        deferred: set[str] | None = None,
        suspending: set[str] | None = None,
    ) -> tuple[set[str], bool, dict[str, dict]]:
        """Admission in effective-priority order; preemption for a blocked
        head, then hole-backfill of strictly smaller gangs behind it. Heads
        are PER ACCELERATOR: a blocked v4 head says nothing about v5e
        capacity, so gangs of other generations keep scheduling as their own
        heads (a global head would starve them on idle pools forever). One
        sort per cycle — the order is fixed at cycle start (an evicted victim
        re-enters *behind* the position it was evicted for, never ahead of
        the head that evicted it). Every bind commits through the cluster
        before the next decision, so the fleet model and the annotation set
        move in lockstep.

        Third return: the pack notes — one entry per gang this pass JUDGED
        and failed (a blocked head with its preemption trail, a failed or
        frozen backfill attempt), the raw material the explain phase turns
        into verdict annotations. Gangs the pass never attempted (behind a
        head past the backfill window, or not strictly smaller than it)
        get no note: an explanation nobody re-proves would go stale."""
        newly_bound: set[str] = set()
        pack_notes: dict[str, dict] = {}
        # note-taking (incl. the O(bound) juniors scan per blocked head) is
        # work whose only consumer is the explain phase: with explain off,
        # skip it entirely so the --no-explain A/B arm measures the whole
        # layer, not just the phase
        explaining = self._explainer is not None
        handoffs = False
        order = queue.ordered(now)
        if nb_by_key is not None:
            # Cross-cycle victim deferral: a Preempted victim stays behind
            # any STRICTLY senior gang still waiting on its accelerator —
            # that senior is (or stands in for) the preemptor it was
            # suspended for. Release and head-bind usually land in one
            # cycle (the `released` deferral below covers that), but a
            # faulted bind write leaves the preemptor queued with NO
            # handoff in flight; in plain aged order the victim's
            # preserved seniority would re-bind it straight into its own
            # freed chips, get it re-preempted, and ping-pong forever.
            # Strictly-senior scoping keeps aged fairness: once the senior
            # binds (or leaves), the victim's order is its own.
            senior: dict[str, int] = {}
            victims: list[tuple[str, str, int]] = []
            for r in order:
                nb = nb_by_key.get(r.key)
                if nb is None:
                    continue
                accel = r.topo.accelerator.name
                if (condition(nb, COND_PREEMPTED) or {}).get(
                        "status") == "True":
                    victims.append((r.key, accel, r.priority))
                elif accel not in senior or r.priority > senior[accel]:
                    senior[accel] = r.priority
            extra = {
                key for key, accel, prio in victims
                if senior.get(accel, prio) > prio
            }
            if extra:
                deferred = (deferred or set()) | extra
        if deferred:
            # A deferred gang that is STRICTLY senior to every
            # non-deferred waiter on its accelerator is not yielding to a
            # preemptor — it IS the head (e.g. a former victim whose
            # priority was bumped while its Preempted condition lingered).
            # Deferring the head hands the very space its preemption
            # trials free to the juniors behind it, re-preempting them
            # forever (sessions soak seed 698: a suspend/resume livelock
            # at thousands of cycles per seed).
            by_key = {r.key: r for r in order}
            top_other: dict[str, int] = {}
            for r in order:
                if r.key in deferred:
                    continue
                a = r.topo.accelerator.name
                if a not in top_other or r.priority > top_other[a]:
                    top_other[a] = r.priority
            deferred = {
                k for k in deferred
                if k not in by_key
                or by_key[k].priority <= top_other.get(
                    by_key[k].topo.accelerator.name, by_key[k].priority
                )
            }
        if deferred:
            # A suspend-released victim must be considered AFTER the head
            # that suspended it — its preserved submit time usually
            # out-ages the preemptor, and in plain aged order it would
            # re-bind straight into its own freed chips, get re-preempted,
            # and ping-pong forever (the sessions soak caught this as a
            # real livelock: thousands of suspend/resume cycles per seed).
            # The legacy evict path had the same rule implicitly: it bound
            # the head before appending victims to the order.
            order = (
                [r for r in order if r.key not in deferred]
                + [r for r in order if r.key in deferred]
            )
        blocked: dict[str, GangRequest] = {}  # accel -> its blocked head
        behind: dict[str, int] = {}  # same-accel entries seen past the head
        # accelerators whose head is waiting on a suspend handoff: backfill
        # is suppressed there — the eviction trial proved the head fits in
        # free+victim space, and a backfill binding into today's free space
        # would invalidate that proof (victims suspended for nothing, head
        # still blocked: a livelock the barrier must not introduce)
        barrier_accels: set[str] = set()
        i = 0
        while i < len(order):
            req = order[i]
            i += 1
            accel = req.topo.accelerator.name
            head = blocked.get(accel)
            if head is not None:
                # behind this accelerator's blocked head: backfill only —
                # strictly smaller than the head, within the window (same
                # predicate as preempt.backfill_candidates, which the soak's
                # fixed-point audit re-derives)
                behind[accel] += 1
                if accel in barrier_accels:
                    # judged by the barrier itself: backfill is frozen on
                    # this accelerator until the handoff resolves
                    if explaining:
                        pack_notes[req.key] = {
                            "role": "waiting", "head": head.key,
                            "preemption": {
                                "considered": False, "outcome": "",
                                "why": explain_mod.PREEMPT_FROZEN,
                            },
                        }
                    continue
                if behind[accel] > self.backfill_window:
                    continue
                if req.chips >= head.chips:
                    continue
                if fleet.accel_free_cells(accel) == 0:
                    # saturation short-circuit: zero free host cells means
                    # no backfill can possibly fit — the judgment IS the
                    # attempt (the explain phase re-proves it from the same
                    # zero-free-cells state), so the note still lands
                    if explaining:
                        pack_notes[req.key] = _backfill_note(head)
                    continue
                slices = fleet.place_gang(
                    req.key, req.topo, req.num_slices,
                    fit_cache=self._fit_cache,
                )
                if slices is not None:
                    self._commit_bind(cluster, req, slices, now)
                    queue.discard(req.key)
                    newly_bound.add(req.key)
                elif explaining:
                    pack_notes[req.key] = _backfill_note(head)
                continue
            slices = fleet.place_gang(
                req.key, req.topo, req.num_slices, fit_cache=self._fit_cache
            )
            if slices is not None:
                self._commit_bind(cluster, req, slices, now)
                queue.discard(req.key)
                newly_bound.add(req.key)
                continue
            # victims: only gangs bound by a PREVIOUS cycle — same-cycle
            # binds were just scheduled by current policy; evicting them
            # now would churn annotations for a decision the next cycle
            # reaches anyway. The trial runs on a clone with NO fit cache:
            # victim space is not free space, so cached "doesn't fit"
            # verdicts must never veto an eviction that would make it fit.
            victims = preempt.select_victims(
                fleet, list(bound.values()), req, suspending=suspending
            )
            if victims is not None:
                if self.suspend_deadline_s is not None:
                    # suspend barrier: request a suspend on each victim
                    # instead of evicting. Chips move only after the
                    # sessions controller acks a committed snapshot (or the
                    # deadline forces) — the replay phase of a LATER cycle
                    # performs the release. Until then the head stays
                    # blocked and its accelerator is backfill-frozen.
                    if self._request_suspends(cluster, victims, req,
                                              nb_by_key or {}, now):
                        handoffs = True
                    blocked[accel] = req
                    behind[accel] = 0
                    barrier_accels.add(accel)
                    if explaining:
                        pack_notes[req.key] = {
                            "role": "head",
                            "preemption": {
                                "considered": True, "outcome": "accepted",
                                "why": explain_mod.PREEMPT_HANDOFF,
                            },
                        }
                    continue
                for v in victims:
                    self._evict(cluster, v, req, preempted_now)
                    self._model.release(v.key)  # epoch bump un-sticks fits
                    bound.pop(v.key, None)
                    # the victim re-queues with its real request and its
                    # original seniority; this cycle reconsiders it after
                    # everything already ahead of the current head
                    queue.push(v.as_request())
                    order.append(v.as_request())
                    if self.metrics is not None:
                        self.metrics.preemptions.inc()
                slices = fleet.place_gang(req.key, req.topo, req.num_slices)
                if slices is not None:  # guaranteed by the trial
                    self._commit_bind(cluster, req, slices, now)
                    queue.discard(req.key)
                    newly_bound.add(req.key)
                continue
            # blocked and nothing junior frees enough: this gang becomes its
            # accelerator's head; everything behind it (same accel) is
            # backfill-only until capacity changes. The note distinguishes
            # "no strictly-junior victims exist" from "evicting all of them
            # still would not fit" — the audit re-proves whichever is
            # claimed against the real bound set.
            if explaining:
                juniors = any(
                    v.topo.accelerator.name == accel
                    and preempt.eligible_victim(v, req)
                    for v in bound.values()
                )
                pack_notes[req.key] = {
                    "role": "head",
                    "preemption": {
                        "considered": True, "outcome": "rejected",
                        "why": (
                            explain_mod.PREEMPT_INSUFFICIENT_RECLAIM
                            if juniors else explain_mod.PREEMPT_NO_JUNIORS
                        ),
                    },
                }
            blocked[accel] = req
            behind[accel] = 0
        return newly_bound, handoffs, pack_notes

    # ------------------------------------------------------------- commits

    def _commit_bind(
        self,
        cluster: FakeCluster,
        req: GangRequest,
        slices: list[dict],
        now: float,
    ) -> None:
        ns, name = req.key.split("/", 1)
        # the fleet already carries the carve (place_gang committed it);
        # record it in the applied map so next cycle's diff treats it as
        # replayed — or, if the annotation write below is lost, releases it
        self._model.applied[req.key] = slices
        try:
            stored = cluster.patch(
                "Notebook", name, ns,
                {"metadata": {"annotations": {
                    PLACEMENT_ANNOTATION: encode_placement(slices, now),
                    # the bind write IS the explanation clear: one atomic
                    # patch, so no crash window where a bound gang still
                    # claims it cannot be placed (the audit checks exactly
                    # this)
                    EXPLANATION_ANNOTATION: None,
                }}},
            )
            self._nb_cache.store(stored)
        except NotFound:
            return  # deleted under us; the fleet model re-derives next cycle
        if self.metrics is not None:
            self.metrics.observe_bind(max(0.0, now - req.queued_at))
        if self.recorder is not None:
            nb = cluster.try_get("Notebook", name, ns)
            if nb is not None:
                pools = sorted({s.get("pool", "?") for s in slices})
                self.recorder.emit(
                    cluster, nb, "Bound",
                    f"gang bound to pool(s) {', '.join(pools)} after "
                    f"{max(0.0, now - req.queued_at):.0f}s in queue",
                )

    def _evict(
        self,
        cluster: FakeCluster,
        victim: BoundGang,
        head: GangRequest,
        preempted_now: dict[str, str],
    ) -> None:
        ns, name = victim.key.split("/", 1)
        nb = cluster.try_get("Notebook", name, ns)
        if nb is not None:
            self._unbind(cluster, nb)
            self._emit(
                cluster, nb, "Preempted",
                f"evicted for higher-priority gang {head.key}",
                type_="Warning",
            )
        preempted_now[victim.key] = f"preempted by {head.key}"

    def _request_suspends(
        self,
        cluster: FakeCluster,
        victims: list[BoundGang],
        head: GangRequest,
        nb_by_key: dict[str, dict],
        now: float,
    ) -> bool:
        """Write the suspend request on every selected victim that does not
        already carry one. Returns True while any victim's handoff is still
        outstanding (request written or pending)."""
        outstanding = False
        for v in victims:
            vnb = nb_by_key.get(v.key)
            if vnb is None:
                continue
            outstanding = True
            if sess.suspend_request(vnb) is not None:
                continue  # already in the barrier; idempotent
            try:
                self._patch_annotations(cluster, vnb, {
                    sess.SUSPEND_ANNOTATION: sess.encode_suspend_request(
                        sess.REASON_PREEMPTION, now, self.suspend_deadline_s
                    ),
                })
            except (NotFound, Conflict):
                continue  # raced a delete/write; next cycle retries
            self._emit(
                cluster, vnb, "Preempted",
                f"suspending for higher-priority gang {head.key}; chips "
                f"hand over once the session snapshot commits",
                type_="Warning",
            )
            if self.metrics is not None:
                self.metrics.preemptions.inc()
        return outstanding

    def _release_suspended(self, cluster: FakeCluster, nb: dict) -> None:
        """The handoff's release: drop the placement AND the spent suspend
        request in one write (half a release could re-run the suspend
        forever, or strand an unbound gang inside the barrier). queued-at
        survives — the victim re-enters the queue with its original submit
        time, so aging makes resume fast."""
        try:
            self._patch_annotations(cluster, nb, {
                PLACEMENT_ANNOTATION: None,
                sess.SUSPEND_ANNOTATION: None,
            })
        except NotFound:
            pass

    @staticmethod
    def _gang_scaled_down(
        cluster: FakeCluster, nb: dict, num_slices: int
    ) -> bool:
        """Has the notebook controller finished tearing the gang's pods
        down (every slice's StatefulSet at spec.replicas 0)? While it has
        not, the hosts still run the gang's containers and the chips must
        not be handed to anyone else."""
        name, ns = ko.name(nb), ko.namespace(nb)
        for j in range(max(1, num_slices)):
            sts_name = name if num_slices <= 1 else f"{name}-s{j}"
            sts = cluster.try_get("StatefulSet", sts_name, ns)
            if sts is not None and (
                (sts.get("spec") or {}).get("replicas", 0) > 0
            ):
                return False
        return True

    def _unbind(
        self,
        cluster: FakeCluster,
        nb_obj: dict,
        *,
        drop_queued_at: bool = False,
    ) -> None:
        """Remove a gang's placement claim. Only NotFound is swallowed (the
        object is gone, its annotation with it). Every other failure MUST
        abort the cycle: the store still carries the claim, and binding
        other gangs into space the failed unbind was supposed to free is
        exactly how two gangs end up holding the same chips (the sched soak
        caught this as a real double-booking under injected Conflicts)."""
        anns: dict = {PLACEMENT_ANNOTATION: None}
        if drop_queued_at:
            anns[QUEUED_AT_ANNOTATION] = None
        if EXPLANATION_ANNOTATION in ko.annotations(nb_obj):
            # a stale verdict must not outlive the claim it judged (a
            # stopped gang, or a spec edit re-queueing from scratch)
            anns[EXPLANATION_ANNOTATION] = None
        try:
            self._patch_annotations(cluster, nb_obj, anns)
        except NotFound:
            pass

    def _emit(
        self,
        cluster: FakeCluster,
        nb: dict,
        reason: str,
        message: str,
        type_: str = "Normal",
    ) -> None:
        if self.recorder is not None:
            self.recorder.emit(cluster, nb, reason, message, type_)

    def _patch_annotations(
        self,
        cluster: FakeCluster,
        nb: dict,
        anns: dict,
        labels: dict | None = None,
    ) -> None:
        patch: dict = {"metadata": {}}
        if anns:
            patch["metadata"]["annotations"] = anns
        if labels:
            patch["metadata"]["labels"] = labels
        stored = cluster.patch(
            "Notebook", ko.name(nb), ko.namespace(nb), patch
        )
        # keep the in-memory copy coherent for the rest of the cycle (the
        # caller may hold a reference to this exact dict) and fold the
        # stored result into the view cache so the next cycle needs no
        # re-fetch for our own write
        for k, v in anns.items():
            if v is None:
                ko.remove_annotation(nb, k)
            else:
                ko.set_annotation(nb, k, v)
        if labels:
            nb.setdefault("metadata", {}).setdefault("labels", {}).update(
                labels
            )
        self._nb_cache.store(stored)

    def _write_conditions(
        self,
        cluster: FakeCluster,
        view: "_NbView",
        conds: list[dict],
        sig: tuple,
    ) -> None:
        """Own exactly the scheduler condition types: strip ours, append the
        given ones in the shared canonical layout (``merge_conditions`` —
        the notebook controller writes the same layout, or the two would
        rewrite each other's status forever), write only on change
        (idempotent cycles must produce zero writes, or the manager would
        never settle).

        ``sig`` is the cheap identity of the desired condition set: when it
        matches what this controller last wrote/verified for the view AND
        the object hasn't moved since (rv check — any other writer resets
        it), the whole merge-and-compare is skipped. At 10k steady queued
        gangs that fast path is the difference between a write phase that
        scales with the queue and one that scales with the delta."""
        nb = view.nb
        if sig == view.conds_sig and view.rv == view.conds_rv:
            return
        current = (nb.get("status") or {}).get("conditions", []) or []
        new = merge_conditions(current, conds)
        if new == current:
            view.conds_sig, view.conds_rv = sig, view.rv
            return
        fresh = cluster.try_get("Notebook", ko.name(nb), ko.namespace(nb))
        if fresh is None:
            return
        status = fresh.setdefault("status", {})
        live = status.get("conditions", []) or []
        new = merge_conditions(live, conds)
        if new != live:
            status["conditions"] = new
            stored = cluster.update_status(fresh)
            self._nb_cache.store(stored)
        # mirror into the local copy so the same cycle sees its own writes
        nb.setdefault("status", {})["conditions"] = new
        view.nb.setdefault("status", {})["conditions"] = new
        view.conds_sig, view.conds_rv = sig, view.rv


class _NbView:
    """One Notebook as the scheduler sees it: the cached body plus every
    derived field a cycle needs (parsed topology, placement, priority, the
    queue request, the last-written condition signature) — re-parsed only
    when the object's resourceVersion moves."""

    __slots__ = (
        "key", "rv", "nb", "topo", "num_slices", "placement", "priority",
        "request", "conds_sig", "conds_rv",
        "admission", "adm_rv", "adm_sig",
    )


class _NotebookCache:
    """Informer-style Notebook cache for the scheduling cycle.

    Level-triggered, like everything else in the scheduler: every cycle
    polls the store's cheap resourceVersion index and re-fetches only the
    bodies that moved, so a cold cycle costs one full read of the world and
    a steady cycle costs O(objects that changed). No watch is involved —
    a dropped watch cannot desynchronize it — and a fresh incarnation
    starts empty, so crash-restart keeps the from-scratch safety story.
    """

    def __init__(self) -> None:
        self.views: dict[str, _NbView] = {}
        self._keystr: dict[tuple[str, str], str] = {}  # (ns, name) -> key
        self._sorted: list[_NbView] | None = None  # None = membership moved
        # keys held OUTSIDE the filtered index (sharded refresh only):
        # gangs the family-label selector cannot see — created unlabeled,
        # or label drifting after a spec edit. Tracked so their rv moves
        # and deletions are polled directly until the label heals.
        self._offindex: set[str] = set()

    def refresh(self, cluster: FakeCluster) -> list[_NbView]:
        rv_index = getattr(cluster, "resource_versions", None)
        if rv_index is None:
            # client surface without the index: degrade to a full re-list
            self.views.clear()
            self._sorted = None
            for nb in cluster.list("Notebook"):
                self.store(nb)
            return self._ordered()
        views, keystr = self.views, self._keystr
        rvs = rv_index("Notebook")
        missed = False
        for nk, rv in rvs.items():
            key = keystr.get(nk)
            if key is None:
                key = keystr[nk] = f"{nk[0]}/{nk[1]}"
            view = views.get(key)
            if view is not None and view.rv == rv:
                continue
            if view is None:
                missed = True
            nb = cluster.try_get("Notebook", nk[1], nk[0])
            if nb is None:
                # deleted between the index poll and the get
                if views.pop(key, None) is not None:
                    self._sorted = None
                continue
            self.store(nb)
        if missed or len(views) != len(rvs):
            live = {keystr[nk] for nk in rvs}
            for key in [k for k in views if k not in live]:
                del views[key]
            if len(keystr) > len(rvs):
                # drop dead name→key entries too, or churn (create/delete
                # at launch-burst scale) grows the map without bound
                for nk in [n for n, k in keystr.items() if k not in live]:
                    del keystr[nk]
            self._sorted = None
        return self._ordered()

    def refresh_filtered(
        self,
        cluster: FakeCluster,
        selectors: list[dict],
        hints: set[tuple[str, str]],
        families: frozenset[str] | None = None,
    ) -> list[_NbView]:
        """The sharded refresh: poll the FAMILY_LABEL-selected rv index —
        O(owned slice) server-side, the whole point of sharding the ingest
        — and cover what the selector cannot see through two side channels:
        ``hints`` (owned-family watch events recorded by the reconciler's
        mapper; the initial watch replay re-seeds them on restart) and the
        ``_offindex`` set (hinted keys that stay invisible to the selector
        — unlabeled or label-drifted gangs — polled directly each cycle
        until the owning shard heals their label). Same crash posture as
        :meth:`refresh`: no watch feeds the cache, a fresh incarnation
        starts cold, faults propagate and the cycle retries."""
        views, keystr = self.views, self._keystr
        rv_index = getattr(cluster, "resource_versions", None)
        if rv_index is None:
            # client surface without the index: degrade to filtered lists
            self.views.clear()
            self._offindex.clear()
            self._sorted = None
            for sel in selectors:
                for nb in cluster.list("Notebook", None, sel):
                    self.store(nb)
            for ns, name in sorted(hints):
                nb = cluster.try_get("Notebook", name, ns)
                if nb is not None:
                    self.store(nb)
            return self._ordered()
        rvs: dict[tuple[str, str], str] = {}
        for sel in selectors:
            rvs.update(rv_index("Notebook", None, sel))
        for nk, rv in rvs.items():
            key = keystr.get(nk)
            if key is None:
                key = keystr[nk] = f"{nk[0]}/{nk[1]}"
            view = views.get(key)
            if view is not None and view.rv == rv:
                continue
            nb = cluster.try_get("Notebook", nk[1], nk[0])
            if nb is None:
                if views.pop(key, None) is not None:
                    self._sorted = None
                continue
            self.store(nb)
        index_keys = {keystr[nk] for nk in rvs}
        # hinted keys the filtered index cannot see: fetch directly
        for nk in sorted(hints):
            key = keystr.get(nk)
            if key is None:
                key = keystr[nk] = f"{nk[0]}/{nk[1]}"
            if key in index_keys:
                self._offindex.discard(key)
                continue
            nb = cluster.try_get("Notebook", nk[1], nk[0])
            if nb is None:
                if views.pop(key, None) is not None:
                    self._sorted = None
                self._offindex.discard(key)
            else:
                self.store(nb)
                self._offindex.add(key)
        # surviving off-index keys not hinted this cycle: their rv moves
        # and deletions are invisible to the selector — poll them directly.
        # A body whose spec family left the owned set is dropped outright:
        # its NEW owner adopts it (hint + label heal on that side), and
        # keeping it here would poll a foreign gang forever.
        for key in sorted(self._offindex):
            if key in index_keys or key not in views:
                self._offindex.discard(key)
                continue
            ns, name = key.split("/", 1)
            nb = cluster.try_get("Notebook", name, ns)
            if nb is None or (
                families is not None
                and sharding.notebook_family(nb) not in families
            ):
                del views[key]
                self._sorted = None
                self._offindex.discard(key)
            elif views[key].rv != (nb.get("metadata") or {}).get(
                "resourceVersion", ""
            ):
                self.store(nb)
        # purge: anything neither indexed nor off-index is gone (deleted,
        # or drifted to a family another shard owns and now labels).
        # Unconditional set difference — a size compare is not a set
        # compare: a phantom index key (body deleted between the rv poll
        # and its get) can mask exactly one truly-stale view and serve a
        # deleted gang for a cycle.
        live = index_keys | self._offindex
        stale = [k for k in views if k not in live]
        if stale:
            for key in stale:
                del views[key]
            self._sorted = None
        if len(keystr) > 2 * max(len(live), 1):
            for nk in [n for n, k in keystr.items() if k not in live]:
                del keystr[nk]
        return self._ordered()

    def _ordered(self) -> list[_NbView]:
        if self._sorted is None:
            self._sorted = sorted(
                self.views.values(), key=lambda v: v.key
            )
        return self._sorted

    def store(self, nb: dict) -> _NbView:
        """Fold one fresh body in (from the index diff or from a write's
        returned object), re-deriving every parsed field. The view object
        is identity-stable per key so in-flight cycle state stays attached."""
        key = _nb_key(nb)
        view = self.views.get(key)
        if view is None:
            view = _NbView()
            view.key = key
            view.conds_sig = None
            view.conds_rv = None
            self.views[key] = view
            self._sorted = None
        view.admission = None
        view.adm_rv = None
        view.adm_sig = None
        view.nb = nb
        view.rv = (nb.get("metadata") or {}).get("resourceVersion", "")
        try:
            view.topo = api.notebook_topology(nb)
            view.num_slices = api.notebook_num_slices(nb)
        except ValueError:
            view.topo = None  # malformed spec.tpu: not a gang
            view.num_slices = 1
        view.placement = placement_of(nb)
        view.priority = gang_priority(nb)
        view.request = None
        return view


def _nb_key(nb: dict) -> str:
    return f"{ko.namespace(nb)}/{ko.name(nb)}"


def _wants_capacity(nb: dict) -> bool:
    return api.STOP_ANNOTATION not in ko.annotations(nb)


def _backfill_note(head: GangRequest) -> dict:
    """Pack note for a gang that tried (or was proven unable) to backfill
    behind a blocked head: preemption is not considered for non-heads."""
    return {
        "role": "backfill", "head": head.key,
        "preemption": {
            "considered": False, "outcome": "",
            "why": explain_mod.PREEMPT_NOT_HEAD,
        },
    }




def _queued_at(nb: dict, default: float | None) -> float | None:
    raw = ko.annotations(nb).get(QUEUED_AT_ANNOTATION)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _map_to_fleet(obj: dict) -> Iterable[tuple[str, str]]:
    yield ("", FLEET_KEY)
