"""Fleet scheduler reconciler.

Runs as one more reconciler under ``runtime/manager.py``, between the
notebook controller and the cluster: a Notebook CR with ``spec.tpu`` is not
a gang until this controller binds it. The bind is a single annotation
write (``scheduling.kubeflow.org/placement``) carrying every slice's pool,
cuboid, and node set — the atomic commit point. The notebook controller
keeps its StatefulSets at 0 replicas until the annotation appears, then
pins the gang to its pool (gang gating,
``notebook_controller.generate_statefulset``).

Level-triggered and stateless across restarts: every scheduling cycle
rebuilds the fleet from Nodes and the occupancy + queue from Notebook
annotations, replays committed placements, then runs admission in priority
order with aging, preemption for blocked heads, and hole-backfill. A crash
between any two writes (armed by the chaos layer) loses nothing: the next
incarnation replays the committed annotations before computing new
placements, so two gangs can never hold overlapping cuboids.

Every Notebook or Node event maps to ONE workqueue key (``@fleet``) — the
cycle is global (placement decisions are fleet-wide), so per-object keys
would run N full cycles for N events; the deduplicating workqueue collapses
them into exactly one (SNIPPETS.md batch-scheduler idiom).

Status surface: ``Queued`` (with queue position), ``Unschedulable`` (no
pool could ever hold the topology), ``Preempted`` (victim of a higher
priority gang or a node drain) — preserved by the notebook controller's
status rewrites and translated by ``webapps/jupyter.py`` for the spawner.

Suspend barrier (``sessions/``, enabled via ``suspend_deadline_s``): the
preemption path stops killing victims outright. A selected victim gets a
suspend-request annotation instead of an eviction; its chips stay held (and
its pods stay up) until the sessions controller acks a committed snapshot —
or the force deadline passes — and only then does one atomic write release
the placement *and* retire the spent request, letting the preemptor bind.
The head stays blocked behind the handoff and backfill is suppressed for
its accelerator (a backfill into the space the victims are about to free
would invalidate the eviction trial and strand everyone). Stopped gangs get
the same courtesy: their chips are not released while the teardown barrier
still holds their pods. Everything is re-derived from annotations each
cycle, so a crash between the snapshot commit and the chip release replays
instead of double-booking (the sessions soak arms exactly that crash).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Iterable

from kubeflow_tpu import sessions as sess
from kubeflow_tpu.api import types as api
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.runtime.fake import Conflict, FakeCluster, NotFound
from kubeflow_tpu.runtime.manager import Reconciler, Result
from kubeflow_tpu.scheduler import (
    COND_PREEMPTED,
    COND_QUEUED,
    COND_UNSCHEDULABLE,
    PLACEMENT_ANNOTATION,
    QUEUED_AT_ANNOTATION,
    condition,
    encode_placement,
    gang_priority,
    merge_conditions,
    placement_matches,
    placement_of,
)
from kubeflow_tpu.scheduler import preemption as preempt
from kubeflow_tpu.scheduler.fleet import Fleet
from kubeflow_tpu.scheduler.preemption import BoundGang
from kubeflow_tpu.scheduler.queue import (
    DEFAULT_AGING_INTERVAL_S,
    GangQueue,
    GangRequest,
)

log = logging.getLogger(__name__)

FLEET_KEY = "@fleet"  # the single coalesced reconcile key

# Beyond this queue depth, Queued messages stop carrying exact positions:
# every bind shifts every position behind it, and rewriting 10k conditions
# per cycle is write-amplification with no reader (the spawner shows tens).
POSITION_MESSAGE_DEPTH = 1000


class SchedulerReconciler(Reconciler):
    """Capacity-aware gang scheduler for TPU notebooks."""

    # Pseudo-kind: no object of this kind ever exists (and no API server
    # could resolve it), so the primary watch is disabled outright; all real
    # events arrive via watches() mapped to FLEET_KEY.
    kind = "SchedulerCycle"
    watch_primary = False

    def __init__(
        self,
        *,
        metrics=None,
        recorder=None,
        clock: Callable[[], float] = time.time,
        aging_interval_s: float = DEFAULT_AGING_INTERVAL_S,
        backfill_window: int = preempt.DEFAULT_BACKFILL_WINDOW,
        resync_s: float = 30.0,
        suspend_deadline_s: float | None = None,
    ) -> None:
        self.metrics = metrics
        # EventRecorder (obs/events.py): Queued/Bound/Preempted/Unschedulable
        # become real Event objects users see in the spawner. Emitted only on
        # TRANSITIONS (first admission, a bind commit, an eviction) — an
        # every-cycle emit would bump counts once per cycle forever on a
        # full fleet, which is exactly the write amplification the recorder's
        # dedup exists to prevent.
        self.recorder = recorder
        self.clock = clock
        self.aging_interval_s = aging_interval_s
        self.backfill_window = backfill_window
        self.resync_s = resync_s
        # Suspend barrier (sessions/): None keeps the legacy immediate-evict
        # preemption; a deadline turns every eviction into a suspend-request
        # handoff bounded by it (chips release on snapshot ack or deadline,
        # whichever first).
        self.suspend_deadline_s = suspend_deadline_s
        # The workqueue already serializes the single key; the lock is a
        # belt-and-braces guard for direct _cycle() callers (bench, tests).
        self._cycle_lock = threading.Lock()

    def watches(self):
        return [("Notebook", _map_to_fleet), ("Node", _map_to_fleet)]

    def reconcile(self, cluster: FakeCluster, namespace: str, name: str) -> Result | None:
        with self._cycle_lock:
            queue_depth, barrier_pending = self._cycle(cluster)
        if barrier_pending:
            # a force deadline crossing has no watch event to announce it;
            # poll the handoff tightly so a wedged snapshot can't stall the
            # preemptor past the deadline
            return Result(requeue_after=min(self.resync_s, 5.0))
        if queue_depth:
            # aging changes effective priorities over time with no event to
            # announce it; periodic resync keeps a waiting queue honest
            return Result(requeue_after=min(self.resync_s, self.aging_interval_s))
        return None

    # ----------------------------------------------------------- the cycle

    def _cycle(self, cluster: FakeCluster) -> tuple[int, bool]:
        """One full scheduling pass. Returns (queue depth, barrier pending)."""
        cycle_started = time.perf_counter()
        barrier_pending = False
        now = self.clock()
        fleet = Fleet.from_nodes(cluster.list("Node"))
        notebooks: list[tuple[dict, object, int]] = []
        for nb in cluster.list("Notebook"):
            try:
                topo = api.notebook_topology(nb)
                num_slices = api.notebook_num_slices(nb)
            except ValueError:
                continue  # malformed spec.tpu: admission's problem, not ours
            if topo is None:
                continue  # CPU notebook: no chips wanted
            notebooks.append((nb, topo, num_slices))

        queue = GangQueue(aging_interval_s=self.aging_interval_s)
        bound: dict[str, BoundGang] = {}
        nb_by_key = {_nb_key(nb): nb for nb, _, _ in notebooks}
        preempted_now: dict[str, str] = {}  # key -> human reason
        released: set[str] = set()  # suspend handoffs completed this cycle
        handoff_accels: set[str] = set()  # accels with a handoff in flight

        # -- replay committed placements (deterministic order: bind time
        #    then key, so an overlap after a drain always evicts the same
        #    gang regardless of list order) --------------------------------
        with_placement = [
            (nb, topo, num_slices, placement_of(nb))
            for nb, topo, num_slices in notebooks
        ]
        with_placement.sort(
            key=lambda t: ((t[3] or {}).get("boundAt", 0.0), _nb_key(t[0]))
        )
        for nb, topo, num_slices, placement in with_placement:
            if placement is None:
                continue
            key = _nb_key(nb)
            if not _wants_capacity(nb):
                if (
                    self.suspend_deadline_s is not None
                    and not sess.suspend_complete(nb, now)
                    and not self._gang_scaled_down(cluster, nb, num_slices)
                ):
                    # teardown barrier: the gang's pods are still up waiting
                    # for their snapshot to commit — the chips stay held (a
                    # release now would bind a second gang onto hosts whose
                    # pods have not exited). Occupancy failing means the
                    # capacity itself is gone (drain/flap): nothing to hold.
                    if fleet.occupy_gang(key, placement["slices"]):
                        barrier_pending = True
                        continue
                # stopped/culled while bound: release the chips and clear
                # every scheduler mark — a restart re-queues from scratch
                self._unbind(cluster, nb, drop_queued_at=True)
                continue
            if not placement_matches(placement, topo, num_slices):
                # spec.tpu edited while bound: the committed placement no
                # longer describes what the gang wants — release it and let
                # the new shape queue from scratch (keeping it would run
                # the gang at the stale shape forever)
                self._unbind(cluster, nb)
                continue
            request = (
                sess.suspend_request(nb)
                if self.suspend_deadline_s is not None
                else None
            )
            if (
                request is not None
                and request.get("reason") == sess.REASON_PREEMPTION
            ):
                if sess.suspend_complete(nb, now):
                    # the handoff's commit point: ONE write releases the
                    # placement and retires the spent request, so a crash on
                    # either side replays cleanly (chips still held, or
                    # victim fully queued — never half). The victim keeps
                    # its queued-at: seniority survives suspension.
                    self._release_suspended(cluster, nb)
                    preempted_now[key] = (
                        "suspended for a higher-priority gang"
                    )
                    released.add(key)
                    continue
                # barrier holds: the victim keeps its chips until the
                # snapshot commits or the force deadline passes
                barrier_pending = True
                handoff_accels.add(topo.accelerator.name)
            if fleet.occupy_gang(key, placement["slices"]):
                bound[key] = BoundGang(
                    key=key,
                    priority=gang_priority(nb),
                    queued_at=_queued_at(nb, now),
                    chips=topo.num_chips * num_slices,
                    topo=topo,
                    num_slices=num_slices,
                )
            else:
                # node drain / capacity flap invalidated the placement
                self._unbind(cluster, nb)
                preempted_now[key] = "placement lost to node drain"

        # -- queue admission ----------------------------------------------
        unschedulable: dict[str, str] = {}
        feasible_cache: dict[tuple, bool] = {}
        for nb, topo, num_slices in notebooks:
            key = _nb_key(nb)
            if key in bound:
                continue
            if not _wants_capacity(nb):
                # stopped while still queued: the queue entry must go with
                # it — a ghost queued-at would hold a phantom capacity claim
                # and resurrect stale seniority on restart. A raced delete
                # or conflicting write must not abort the whole fleet cycle
                # for a gang that holds no geometry claim; the clear is
                # retried next cycle.
                if QUEUED_AT_ANNOTATION in ko.annotations(nb):
                    try:
                        self._patch_annotations(
                            cluster, nb, {QUEUED_AT_ANNOTATION: None}
                        )
                    except (NotFound, Conflict):
                        pass
                continue
            shape_key = (topo.accelerator.name, topo.shape, num_slices)
            feasible = feasible_cache.get(shape_key)
            if feasible is None:
                feasible = fleet.feasible_on_empty(topo, num_slices)
                feasible_cache[shape_key] = feasible
            if not feasible:
                unschedulable[key] = (
                    f"no node pool can hold {topo.slice_name}"
                    + (f" x{num_slices}" if num_slices > 1 else "")
                )
                continue
            queued_at = _queued_at(nb, None)
            if queued_at is None:
                queued_at = now
                try:
                    self._patch_annotations(
                        cluster, nb, {QUEUED_AT_ANNOTATION: repr(queued_at)}
                    )
                except (NotFound, Conflict):
                    continue  # deleted/raced: next cycle re-admits
                # first admission is the transition worth an Event; the
                # queued-at annotation makes it exactly-once per wait
                self._emit(
                    cluster, nb, "Queued",
                    f"gang admitted to the TPU capacity queue "
                    f"({topo.slice_name}"
                    + (f" x{num_slices}" if num_slices > 1 else "") + ")",
                )
            queue.push(GangRequest(
                key=key,
                priority=gang_priority(nb),
                queued_at=queued_at,
                topo=topo,
                num_slices=num_slices,
            ))

        # -- scheduling pass ----------------------------------------------
        # Victims already released while a same-accel handoff is still in
        # flight (multi-victim preemption resolving ack by ack) carry the
        # same re-bind hazard as this cycle's releases: their preserved
        # seniority would grab the partially-freed space back before the
        # head ever gets all of it. Their Preempted=True condition (kept
        # until re-bind) identifies them durably across cycles.
        deferred = set(released)
        if handoff_accels:
            for nb, topo, num_slices in notebooks:
                key = _nb_key(nb)
                if (
                    key not in bound
                    and topo.accelerator.name in handoff_accels
                    and (condition(nb, COND_PREEMPTED) or {}).get("status")
                    == "True"
                ):
                    deferred.add(key)

        # -- scheduling pass ----------------------------------------------
        newly_bound, handoffs = self._schedule(
            cluster, fleet, queue, bound, preempted_now, now, nb_by_key,
            deferred,
        )
        barrier_pending = barrier_pending or handoffs

        # -- status conditions + metrics ----------------------------------
        order = queue.ordered(now)
        positions = {r.key: i + 1 for i, r in enumerate(order)}
        for nb, topo, num_slices in notebooks:
            key = _nb_key(nb)
            if not _wants_capacity(nb):
                self._write_conditions(cluster, nb, [])
            elif key in bound or key in newly_bound:
                self._write_conditions(cluster, nb, [{
                    "type": COND_QUEUED, "status": "False",
                    "reason": "Bound", "message": "",
                }])
            elif key in unschedulable:
                if not (
                    (condition(nb, COND_UNSCHEDULABLE) or {}).get("status")
                    == "True"
                ):
                    # transition into Unschedulable (not the steady state)
                    self._emit(
                        cluster, nb, "Unschedulable", unschedulable[key],
                        type_="Warning",
                    )
                self._write_conditions(cluster, nb, [{
                    "type": COND_UNSCHEDULABLE, "status": "True",
                    "reason": "NoFittingPool",
                    "message": unschedulable[key],
                }])
            elif key in positions:
                if len(order) <= POSITION_MESSAGE_DEPTH:
                    msg = f"position {positions[key]} of {len(order)}"
                else:
                    # depth changes every cycle; putting it in the message
                    # would rewrite every queued notebook's status per cycle
                    msg = "waiting for TPU capacity"
                conds = [{
                    "type": COND_QUEUED, "status": "True",
                    "reason": "WaitingForCapacity", "message": msg,
                }]
                reason = preempted_now.get(key)
                if reason is not None:
                    conds.append({
                        "type": COND_PREEMPTED, "status": "True",
                        "reason": "Preempted", "message": reason,
                    })
                else:
                    # a victim stays marked Preempted until it binds again
                    existing = condition(nb, COND_PREEMPTED)
                    if existing is not None and existing.get("status") == "True":
                        conds.append(existing)
                self._write_conditions(cluster, nb, conds)

        if self.metrics is not None:
            self.metrics.observe_cycle(
                fleet,
                queue_depth=len(order),
                unschedulable=len(unschedulable),
                duration_s=time.perf_counter() - cycle_started,
            )
        return len(order), barrier_pending

    def _schedule(
        self,
        cluster: FakeCluster,
        fleet: Fleet,
        queue: GangQueue,
        bound: dict[str, BoundGang],
        preempted_now: dict[str, str],
        now: float,
        nb_by_key: dict[str, dict] | None = None,
        deferred: set[str] | None = None,
    ) -> tuple[set[str], bool]:
        """Admission in effective-priority order; preemption for a blocked
        head, then hole-backfill of strictly smaller gangs behind it. Heads
        are PER ACCELERATOR: a blocked v4 head says nothing about v5e
        capacity, so gangs of other generations keep scheduling as their own
        heads (a global head would starve them on idle pools forever). One
        sort per cycle — the order is fixed at cycle start (an evicted victim
        re-enters *behind* the position it was evicted for, never ahead of
        the head that evicted it). Every bind commits through the cluster
        before the next decision, so the fleet model and the annotation set
        move in lockstep."""
        newly_bound: set[str] = set()
        handoffs = False
        order = queue.ordered(now)
        if deferred:
            # A suspend-released victim must be considered AFTER the head
            # that suspended it — its preserved submit time usually
            # out-ages the preemptor, and in plain aged order it would
            # re-bind straight into its own freed chips, get re-preempted,
            # and ping-pong forever (the sessions soak caught this as a
            # real livelock: thousands of suspend/resume cycles per seed).
            # The legacy evict path had the same rule implicitly: it bound
            # the head before appending victims to the order.
            order = (
                [r for r in order if r.key not in deferred]
                + [r for r in order if r.key in deferred]
            )
        blocked: dict[str, GangRequest] = {}  # accel -> its blocked head
        behind: dict[str, int] = {}  # same-accel entries seen past the head
        # accelerators whose head is waiting on a suspend handoff: backfill
        # is suppressed there — the eviction trial proved the head fits in
        # free+victim space, and a backfill binding into today's free space
        # would invalidate that proof (victims suspended for nothing, head
        # still blocked: a livelock the barrier must not introduce)
        barrier_accels: set[str] = set()
        i = 0
        while i < len(order):
            req = order[i]
            i += 1
            accel = req.topo.accelerator.name
            head = blocked.get(accel)
            if head is not None:
                # behind this accelerator's blocked head: backfill only —
                # strictly smaller than the head, within the window (same
                # predicate as preempt.backfill_candidates, which the soak's
                # fixed-point audit re-derives)
                behind[accel] += 1
                if accel in barrier_accels:
                    continue
                if behind[accel] > self.backfill_window:
                    continue
                if req.chips >= head.chips:
                    continue
                slices = fleet.place_gang(req.key, req.topo, req.num_slices)
                if slices is not None:
                    self._commit_bind(cluster, req, slices, now)
                    queue.discard(req.key)
                    newly_bound.add(req.key)
                continue
            slices = fleet.place_gang(req.key, req.topo, req.num_slices)
            if slices is not None:
                self._commit_bind(cluster, req, slices, now)
                queue.discard(req.key)
                newly_bound.add(req.key)
                continue
            # victims: only gangs bound by a PREVIOUS cycle — same-cycle
            # binds were just scheduled by current policy; evicting them
            # now would churn annotations for a decision the next cycle
            # reaches anyway
            victims = preempt.select_victims(fleet, list(bound.values()), req)
            if victims is not None:
                if self.suspend_deadline_s is not None:
                    # suspend barrier: request a suspend on each victim
                    # instead of evicting. Chips move only after the
                    # sessions controller acks a committed snapshot (or the
                    # deadline forces) — the replay phase of a LATER cycle
                    # performs the release. Until then the head stays
                    # blocked and its accelerator is backfill-frozen.
                    if self._request_suspends(cluster, victims, req,
                                              nb_by_key or {}, now):
                        handoffs = True
                    blocked[accel] = req
                    behind[accel] = 0
                    barrier_accels.add(accel)
                    continue
                for v in victims:
                    self._evict(cluster, v, req, preempted_now)
                    fleet.free_gang(v.key)
                    bound.pop(v.key, None)
                    # the victim re-queues with its real request and its
                    # original seniority; this cycle reconsiders it after
                    # everything already ahead of the current head
                    queue.push(v.as_request())
                    order.append(v.as_request())
                    if self.metrics is not None:
                        self.metrics.preemptions.inc()
                slices = fleet.place_gang(req.key, req.topo, req.num_slices)
                if slices is not None:  # guaranteed by the trial
                    self._commit_bind(cluster, req, slices, now)
                    queue.discard(req.key)
                    newly_bound.add(req.key)
                continue
            # blocked and nothing junior frees enough: this gang becomes its
            # accelerator's head; everything behind it (same accel) is
            # backfill-only until capacity changes
            blocked[accel] = req
            behind[accel] = 0
        return newly_bound, handoffs

    # ------------------------------------------------------------- commits

    def _commit_bind(
        self,
        cluster: FakeCluster,
        req: GangRequest,
        slices: list[dict],
        now: float,
    ) -> None:
        ns, name = req.key.split("/", 1)
        try:
            cluster.patch(
                "Notebook", name, ns,
                {"metadata": {"annotations": {
                    PLACEMENT_ANNOTATION: encode_placement(slices, now),
                }}},
            )
        except NotFound:
            return  # deleted under us; the fleet model re-derives next cycle
        if self.metrics is not None:
            self.metrics.observe_bind(max(0.0, now - req.queued_at))
        if self.recorder is not None:
            nb = cluster.try_get("Notebook", name, ns)
            if nb is not None:
                pools = sorted({s.get("pool", "?") for s in slices})
                self.recorder.emit(
                    cluster, nb, "Bound",
                    f"gang bound to pool(s) {', '.join(pools)} after "
                    f"{max(0.0, now - req.queued_at):.0f}s in queue",
                )

    def _evict(
        self,
        cluster: FakeCluster,
        victim: BoundGang,
        head: GangRequest,
        preempted_now: dict[str, str],
    ) -> None:
        ns, name = victim.key.split("/", 1)
        nb = cluster.try_get("Notebook", name, ns)
        if nb is not None:
            self._unbind(cluster, nb)
            self._emit(
                cluster, nb, "Preempted",
                f"evicted for higher-priority gang {head.key}",
                type_="Warning",
            )
        preempted_now[victim.key] = f"preempted by {head.key}"

    def _request_suspends(
        self,
        cluster: FakeCluster,
        victims: list[BoundGang],
        head: GangRequest,
        nb_by_key: dict[str, dict],
        now: float,
    ) -> bool:
        """Write the suspend request on every selected victim that does not
        already carry one. Returns True while any victim's handoff is still
        outstanding (request written or pending)."""
        outstanding = False
        for v in victims:
            vnb = nb_by_key.get(v.key)
            if vnb is None:
                continue
            outstanding = True
            if sess.suspend_request(vnb) is not None:
                continue  # already in the barrier; idempotent
            try:
                self._patch_annotations(cluster, vnb, {
                    sess.SUSPEND_ANNOTATION: sess.encode_suspend_request(
                        sess.REASON_PREEMPTION, now, self.suspend_deadline_s
                    ),
                })
            except (NotFound, Conflict):
                continue  # raced a delete/write; next cycle retries
            self._emit(
                cluster, vnb, "Preempted",
                f"suspending for higher-priority gang {head.key}; chips "
                f"hand over once the session snapshot commits",
                type_="Warning",
            )
            if self.metrics is not None:
                self.metrics.preemptions.inc()
        return outstanding

    def _release_suspended(self, cluster: FakeCluster, nb: dict) -> None:
        """The handoff's release: drop the placement AND the spent suspend
        request in one write (half a release could re-run the suspend
        forever, or strand an unbound gang inside the barrier). queued-at
        survives — the victim re-enters the queue with its original submit
        time, so aging makes resume fast."""
        try:
            self._patch_annotations(cluster, nb, {
                PLACEMENT_ANNOTATION: None,
                sess.SUSPEND_ANNOTATION: None,
            })
        except NotFound:
            pass

    @staticmethod
    def _gang_scaled_down(
        cluster: FakeCluster, nb: dict, num_slices: int
    ) -> bool:
        """Has the notebook controller finished tearing the gang's pods
        down (every slice's StatefulSet at spec.replicas 0)? While it has
        not, the hosts still run the gang's containers and the chips must
        not be handed to anyone else."""
        name, ns = ko.name(nb), ko.namespace(nb)
        for j in range(max(1, num_slices)):
            sts_name = name if num_slices <= 1 else f"{name}-s{j}"
            sts = cluster.try_get("StatefulSet", sts_name, ns)
            if sts is not None and (
                (sts.get("spec") or {}).get("replicas", 0) > 0
            ):
                return False
        return True

    def _unbind(
        self,
        cluster: FakeCluster,
        nb_obj: dict,
        *,
        drop_queued_at: bool = False,
    ) -> None:
        """Remove a gang's placement claim. Only NotFound is swallowed (the
        object is gone, its annotation with it). Every other failure MUST
        abort the cycle: the store still carries the claim, and binding
        other gangs into space the failed unbind was supposed to free is
        exactly how two gangs end up holding the same chips (the sched soak
        caught this as a real double-booking under injected Conflicts)."""
        anns: dict = {PLACEMENT_ANNOTATION: None}
        if drop_queued_at:
            anns[QUEUED_AT_ANNOTATION] = None
        try:
            self._patch_annotations(cluster, nb_obj, anns)
        except NotFound:
            pass

    def _emit(
        self,
        cluster: FakeCluster,
        nb: dict,
        reason: str,
        message: str,
        type_: str = "Normal",
    ) -> None:
        if self.recorder is not None:
            self.recorder.emit(cluster, nb, reason, message, type_)

    def _patch_annotations(
        self, cluster: FakeCluster, nb: dict, anns: dict
    ) -> None:
        cluster.patch(
            "Notebook", ko.name(nb), ko.namespace(nb),
            {"metadata": {"annotations": anns}},
        )
        # keep the in-memory copy coherent for the rest of the cycle
        for k, v in anns.items():
            if v is None:
                ko.remove_annotation(nb, k)
            else:
                ko.set_annotation(nb, k, v)

    def _write_conditions(
        self, cluster: FakeCluster, nb: dict, conds: list[dict]
    ) -> None:
        """Own exactly the scheduler condition types: strip ours, append the
        given ones in the shared canonical layout (``merge_conditions`` —
        the notebook controller writes the same layout, or the two would
        rewrite each other's status forever), write only on change
        (idempotent cycles must produce zero writes, or the manager would
        never settle). The no-op check runs against the cycle's own listed
        copy — re-reading every notebook every cycle would be a get per
        object per cycle."""
        current = (nb.get("status") or {}).get("conditions", []) or []
        new = merge_conditions(current, conds)
        if new == current:
            return
        fresh = cluster.try_get("Notebook", ko.name(nb), ko.namespace(nb))
        if fresh is None:
            return
        status = fresh.setdefault("status", {})
        live = status.get("conditions", []) or []
        new = merge_conditions(live, conds)
        if new != live:
            status["conditions"] = new
            cluster.update_status(fresh)
        # mirror into the local copy so the same cycle sees its own writes
        nb.setdefault("status", {})["conditions"] = new


def _nb_key(nb: dict) -> str:
    return f"{ko.namespace(nb)}/{ko.name(nb)}"


def _wants_capacity(nb: dict) -> bool:
    return api.STOP_ANNOTATION not in ko.annotations(nb)




def _queued_at(nb: dict, default: float | None) -> float | None:
    raw = ko.annotations(nb).get(QUEUED_AT_ANNOTATION)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _map_to_fleet(obj: dict) -> Iterable[tuple[str, str]]:
    yield ("", FLEET_KEY)
