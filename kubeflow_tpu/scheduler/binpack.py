"""Topology-aware best-fit placement of torus cuboids.

All geometry runs in *host-block units*: scheduling granularity is a whole
host (one pod per host, ``topology.py``), so a pool of chip shape ``4x4x4``
on v4 (host block ``2x2x1``) is a ``2x2x4`` grid of host cells. Pools are
small (a v4-4096 pool is 8x8x16 = 1024 cells), so exact algorithms beat
clever ones: the free set is recomputed canonically from the used set — a
freed gang's cuboid coalesces back automatically because the decomposition
is a pure function of what remains used (the round-trip property the bin
packing suite asserts), not an incremental merge that can drift.

Placement is best-fit: among every (free cuboid, request orientation) pair
that fits, pick the free cuboid with the least leftover volume — the
smallest hole that accommodates the gang, which is what minimizes
fragmentation for the gangs behind it. Greedy decomposition can split an
L-shaped free region across cuboid boundaries, so a miss falls back to an
exhaustive offset scan: ``fits`` is exact — a placement exists iff the
scheduler finds one — which is what lets the soak assert "every feasible
gang eventually binds" against the scheduler's own feasibility notion.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Iterable, Iterator, Sequence

from kubeflow_tpu.tpu.topology import TpuAccelerator


@dataclasses.dataclass(frozen=True)
class Cuboid:
    """An axis-aligned box inside a pool grid (host-block units)."""

    offset: tuple[int, ...]
    shape: tuple[int, ...]

    @property
    def volume(self) -> int:
        return math.prod(self.shape)

    @property
    def end(self) -> tuple[int, ...]:
        return tuple(o + s for o, s in zip(self.offset, self.shape))

    def overlaps(self, other: "Cuboid") -> bool:
        return all(
            o1 < e2 and o2 < e1
            for o1, e1, o2, e2 in zip(
                self.offset, self.end, other.offset, other.end
            )
        )

    def within(self, grid: Sequence[int]) -> bool:
        return all(o >= 0 for o in self.offset) and all(
            e <= g for e, g in zip(self.end, grid)
        )

    def cells(self) -> Iterator[tuple[int, ...]]:
        return itertools.product(
            *(range(o, o + s) for o, s in zip(self.offset, self.shape))
        )


def ceil_div_shape(
    chip_shape: Sequence[int], host_block: Sequence[int]
) -> tuple[int, ...]:
    """Chip-shape → host-block shape. Sub-host offerings (v5e 1x1/2x2) round
    up to one whole block: the host is theirs alone either way."""
    return tuple(-(-d // b) for d, b in zip(chip_shape, host_block))


def orientations(
    accel: TpuAccelerator, chip_shape: Sequence[int]
) -> list[tuple[tuple[int, ...], tuple[int, ...]]]:
    """Valid axis permutations of a request, as (chip_shape, block_shape).

    A slice request can be rotated onto the pool torus — the sub-cuboid is
    the same mesh up to axis relabeling — but only rotations that still map
    onto whole hosts are usable (same admission rule as ``parse_topology``).
    """
    out: list[tuple[tuple[int, ...], tuple[int, ...]]] = []
    seen: set[tuple[int, ...]] = set()
    for perm in itertools.permutations(tuple(chip_shape)):
        if perm in seen:
            continue
        seen.add(perm)
        tiles = all(d % b == 0 for d, b in zip(perm, accel.host_block))
        if tiles or perm in accel.supports_single_host_sub_blocks:
            out.append((perm, ceil_div_shape(perm, accel.host_block)))
    return out


def decompose_free(
    grid: Sequence[int], used: Iterable[Cuboid]
) -> list[Cuboid]:
    """Canonical decomposition of the free space into disjoint cuboids.

    Deterministic greedy sweep: take the lexicographically smallest free
    cell, grow the box axis-by-axis (last axis first, so runs follow the
    host-ordinal direction) as far as every covered cell stays free, emit,
    repeat. Pure function of the used set — freeing a gang and re-running
    yields exactly the pre-placement free set (the coalescing contract).
    """
    free: set[tuple[int, ...]] = set(
        itertools.product(*(range(g) for g in grid))
    )
    for c in used:
        free.difference_update(c.cells())
    out: list[Cuboid] = []
    while free:
        origin = min(free)
        shape = [1] * len(grid)
        # grow along each axis, last axis first (innermost runs)
        for axis in range(len(grid) - 1, -1, -1):
            while origin[axis] + shape[axis] < grid[axis]:
                grown = list(shape)
                grown[axis] += 1
                candidate = Cuboid(origin, tuple(grown))
                if all(cell in free for cell in candidate.cells()):
                    shape = grown
                else:
                    break
        box = Cuboid(origin, tuple(shape))
        free.difference_update(box.cells())
        out.append(box)
    return out


def _scan_fit(
    grid: Sequence[int],
    free_cells: set[tuple[int, ...]],
    block_shape: tuple[int, ...],
) -> tuple[int, ...] | None:
    """Exhaustive completeness fallback: first offset (lexicographic) where
    the whole request region is free. Greedy decomposition can split a
    placeable region across free-cuboid boundaries; this cannot."""
    for offset in itertools.product(
        *(range(g - s + 1) for g, s in zip(grid, block_shape))
    ):
        if all(c in free_cells for c in Cuboid(offset, block_shape).cells()):
            return offset
    return None


def best_fit(
    grid: Sequence[int],
    used: Iterable[Cuboid],
    accel: TpuAccelerator,
    chip_shape: Sequence[int],
) -> tuple[Cuboid, tuple[int, ...]] | None:
    """Place one slice request into one pool grid.

    Returns ``(block_cuboid, oriented_chip_shape)`` or None. Score order:
    least leftover volume in the hosting free cuboid (best-fit), then
    lexicographic offset, then orientation order — fully deterministic, so
    a restarted scheduler re-derives identical decisions from identical
    state.
    """
    frees = decompose_free(grid, used)
    options = orientations(accel, chip_shape)
    best: tuple[tuple[int, int, tuple[int, ...]], Cuboid, tuple[int, ...]] | None = None
    for i, (chips, blocks) in enumerate(options):
        for f in frees:
            if all(b <= fs for b, fs in zip(blocks, f.shape)):
                score = (f.volume - math.prod(blocks), i, f.offset)
                if best is None or score < best[0]:
                    best = (score, Cuboid(f.offset, blocks), chips)
    if best is not None:
        return best[1], best[2]
    # fall back to the exact scan (free region exists but was split)
    free_cells: set[tuple[int, ...]] = set(
        itertools.product(*(range(g) for g in grid))
    )
    for c in used:
        free_cells.difference_update(c.cells())
    for chips, blocks in options:
        offset = _scan_fit(grid, free_cells, blocks)
        if offset is not None:
            return Cuboid(offset, blocks), chips
    return None
