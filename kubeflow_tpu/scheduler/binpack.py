"""Topology-aware best-fit placement of torus cuboids.

All geometry runs in *host-block units*: scheduling granularity is a whole
host (one pod per host, ``topology.py``), so a pool of chip shape ``4x4x4``
on v4 (host block ``2x2x1``) is a ``2x2x4`` grid of host cells. Pools are
small (a v4-4096 pool is 8x8x16 = 1024 cells), so exact algorithms beat
clever ones: the free set is recomputed canonically from the used set — a
freed gang's cuboid coalesces back automatically because the decomposition
is a pure function of what remains used (the round-trip property the bin
packing suite asserts), not an incremental merge that can drift.

Placement is best-fit: among every (free cuboid, request orientation) pair
that fits, pick the free cuboid with the least leftover volume — the
smallest hole that accommodates the gang, which is what minimizes
fragmentation for the gangs behind it. Greedy decomposition can split an
L-shaped free region across cuboid boundaries, so a miss falls back to an
exhaustive offset scan: ``fits`` is exact — a placement exists iff the
scheduler finds one — which is what lets the soak assert "every feasible
gang eventually binds" against the scheduler's own feasibility notion.
"""
from __future__ import annotations

import dataclasses
import functools
import itertools
import math
from typing import Iterable, Iterator, Sequence

from kubeflow_tpu.tpu.topology import TpuAccelerator


@dataclasses.dataclass(frozen=True)
class Cuboid:
    """An axis-aligned box inside a pool grid (host-block units)."""

    offset: tuple[int, ...]
    shape: tuple[int, ...]

    @property
    def volume(self) -> int:
        return math.prod(self.shape)

    @property
    def end(self) -> tuple[int, ...]:
        return tuple(o + s for o, s in zip(self.offset, self.shape))

    def overlaps(self, other: "Cuboid") -> bool:
        return all(
            o1 < e2 and o2 < e1
            for o1, e1, o2, e2 in zip(
                self.offset, self.end, other.offset, other.end
            )
        )

    def within(self, grid: Sequence[int]) -> bool:
        return all(o >= 0 for o in self.offset) and all(
            e <= g for e, g in zip(self.end, grid)
        )

    def cells(self) -> Iterator[tuple[int, ...]]:
        return itertools.product(
            *(range(o, o + s) for o, s in zip(self.offset, self.shape))
        )


def ceil_div_shape(
    chip_shape: Sequence[int], host_block: Sequence[int]
) -> tuple[int, ...]:
    """Chip-shape → host-block shape. Sub-host offerings (v5e 1x1/2x2) round
    up to one whole block: the host is theirs alone either way."""
    return tuple(-(-d // b) for d, b in zip(chip_shape, host_block))


def _orientations_uncached(
    accel: TpuAccelerator, chip_shape: tuple[int, ...]
) -> tuple[tuple[tuple[int, ...], tuple[int, ...]], ...]:
    out: list[tuple[tuple[int, ...], tuple[int, ...]]] = []
    seen: set[tuple[int, ...]] = set()
    for perm in itertools.permutations(chip_shape):
        if perm in seen:
            continue
        seen.add(perm)
        tiles = all(d % b == 0 for d, b in zip(perm, accel.host_block))
        if tiles or perm in accel.supports_single_host_sub_blocks:
            out.append((perm, ceil_div_shape(perm, accel.host_block)))
    return tuple(out)


_orientations_cached = functools.lru_cache(maxsize=None)(_orientations_uncached)


def orientations(
    accel: TpuAccelerator, chip_shape: Sequence[int]
) -> tuple[tuple[tuple[int, ...], tuple[int, ...]], ...]:
    """Valid axis permutations of a request, as (chip_shape, block_shape).

    A slice request can be rotated onto the pool torus — the sub-cuboid is
    the same mesh up to axis relabeling — but only rotations that still map
    onto whole hosts are usable (same admission rule as ``parse_topology``).

    Memoized: shape tuples are tiny and immutable, the accelerator table is
    fixed, and the scheduler asks for the same handful of shapes once per
    fit attempt across thousands of attempts per cycle.
    """
    return _orientations_cached(accel, tuple(chip_shape))


def _greedy_sweep(
    grid: Sequence[int], free: set[tuple[int, ...]]
) -> list[Cuboid]:
    """The canonical decomposition sweep over a free-cell set (consumed).

    Deterministic: take the lexicographically smallest free cell, grow the
    box axis-by-axis (last axis first, so runs follow the host-ordinal
    direction) as far as every covered cell stays free, emit, repeat. Each
    growth step only probes the newly-added slab — the cells already inside
    the box are free by construction.
    """
    out: list[Cuboid] = []
    n = len(grid)
    while free:
        origin = min(free)
        shape = [1] * n
        for axis in range(n - 1, -1, -1):
            while origin[axis] + shape[axis] < grid[axis]:
                pos = origin[axis] + shape[axis]
                slab = itertools.product(*(
                    (range(o, o + s) if a != axis else (pos,))
                    for a, (o, s) in enumerate(zip(origin, shape))
                ))
                if all(cell in free for cell in slab):
                    shape[axis] += 1
                else:
                    break
        box = Cuboid(origin, tuple(shape))
        free.difference_update(box.cells())
        out.append(box)
    return out


def decompose_free(
    grid: Sequence[int], used: Iterable[Cuboid]
) -> list[Cuboid]:
    """Canonical decomposition of the free space into disjoint cuboids.

    Pure function of the used set — freeing a gang and re-running yields
    exactly the pre-placement free set (the coalescing contract). This is
    the from-scratch reference; :class:`FreeSet` maintains the identical
    decomposition incrementally and is differentially audited against it.
    """
    free: set[tuple[int, ...]] = set(
        itertools.product(*(range(g) for g in grid))
    )
    for c in used:
        free.difference_update(c.cells())
    return _greedy_sweep(grid, free)


def _probe_overlaps(c: Cuboid, box: Cuboid) -> bool:
    """Does ``box`` intersect the region the sweep *probed* while growing
    ``c``? Growth along each axis peeks one slab past the final shape, so
    the probed region is contained in ``c`` inflated by +1 in every positive
    axis direction — a conservative superset is all the prefix rule needs."""
    return all(
        bo < co + cs + 1 and co < bo + bs
        for bo, bs, co, cs in zip(box.offset, box.shape, c.offset, c.shape)
    )


class FreeSet:
    """Incrementally-maintained canonical free decomposition of one grid.

    ``cuboids`` is always cell-for-cell identical to
    ``decompose_free(grid, used)`` (property-tested in test_binpack.py) —
    but a ``carve``/``release`` updates it in time proportional to the
    *suffix* of the sweep the change can influence, not the whole grid.

    The prefix rule: the greedy sweep emits cuboids in lexicographic origin
    order, each one a deterministic function of (a) the smallest remaining
    free cell and (b) the free cells its growth probed. A cuboid of the old
    decomposition therefore survives a change verbatim iff no released cell
    precedes its origin (released cells were used, so they are covered by no
    earlier cuboid and would steal the origin) and the changed box misses
    its probe region entirely; the first cuboid failing either test starts
    the re-swept suffix. Carved cells before a kept origin are inside an
    earlier cuboid by construction, so they fail the probe test there first.
    """

    __slots__ = ("grid", "cells", "cuboids")

    def __init__(
        self, grid: Sequence[int], used: Iterable[Cuboid] = ()
    ) -> None:
        self.grid = tuple(grid)
        self.cells: set[tuple[int, ...]] = set(
            itertools.product(*(range(g) for g in self.grid))
        )
        for c in used:
            self.cells.difference_update(c.cells())
        self.cuboids: list[Cuboid] = _greedy_sweep(self.grid, set(self.cells))

    def carve(self, box: Cuboid) -> None:
        """Remove a fully-free box from the free space (a placement)."""
        self._apply(box, adding=False)

    def release(self, box: Cuboid) -> None:
        """Return a previously-carved box to the free space (coalescing is
        automatic: the suffix re-sweep re-derives the canonical cuboids)."""
        self._apply(box, adding=True)

    def _apply(self, box: Cuboid, *, adding: bool) -> None:
        changed = set(box.cells())
        if adding:
            self.cells |= changed
        else:
            self.cells -= changed
        min_released = min(changed) if adding else None
        prefix: list[Cuboid] = []
        for c in self.cuboids:
            if min_released is not None and not (c.offset < min_released):
                break
            if _probe_overlaps(c, box):
                break
            prefix.append(c)
        remaining = set(self.cells)
        for c in prefix:
            remaining.difference_update(c.cells())
        self.cuboids = prefix + _greedy_sweep(self.grid, remaining)

    def clone(self) -> "FreeSet":
        out = FreeSet.__new__(FreeSet)
        out.grid = self.grid
        out.cells = set(self.cells)
        out.cuboids = list(self.cuboids)  # Cuboids are frozen
        return out


def _scan_fit(
    grid: Sequence[int],
    free_cells: set[tuple[int, ...]],
    block_shape: tuple[int, ...],
) -> tuple[int, ...] | None:
    """Exhaustive completeness fallback: first offset (lexicographic) where
    the whole request region is free. Greedy decomposition can split a
    placeable region across free-cuboid boundaries; this cannot."""
    for offset in itertools.product(
        *(range(g - s + 1) for g, s in zip(grid, block_shape))
    ):
        if all(c in free_cells for c in Cuboid(offset, block_shape).cells()):
            return offset
    return None


def best_fit_free(
    free: FreeSet,
    accel: TpuAccelerator,
    chip_shape: Sequence[int],
) -> tuple[Cuboid, tuple[int, ...]] | None:
    """Place one slice request against a maintained :class:`FreeSet`.

    Returns ``(block_cuboid, oriented_chip_shape)`` or None. Score order:
    least leftover volume in the hosting free cuboid (best-fit), then
    lexicographic offset, then orientation order — fully deterministic, so
    a restarted scheduler re-derives identical decisions from identical
    state. Orientations whose block volume exceeds the free cell count are
    rejected without touching geometry (a necessary-condition fast path).
    """
    options = orientations(accel, chip_shape)
    n_free = len(free.cells)
    best: tuple[tuple[int, int, tuple[int, ...]], Cuboid, tuple[int, ...]] | None = None
    for i, (chips, blocks) in enumerate(options):
        if math.prod(blocks) > n_free:
            continue
        for f in free.cuboids:
            if all(b <= fs for b, fs in zip(blocks, f.shape)):
                score = (f.volume - math.prod(blocks), i, f.offset)
                if best is None or score < best[0]:
                    best = (score, Cuboid(f.offset, blocks), chips)
    if best is not None:
        return best[1], best[2]
    # fall back to the exact scan (free region exists but was split)
    for chips, blocks in options:
        if math.prod(blocks) > n_free:
            continue
        offset = _scan_fit(free.grid, free.cells, blocks)
        if offset is not None:
            return Cuboid(offset, blocks), chips
    return None


def best_fit(
    grid: Sequence[int],
    used: Iterable[Cuboid],
    accel: TpuAccelerator,
    chip_shape: Sequence[int],
) -> tuple[Cuboid, tuple[int, ...]] | None:
    """From-scratch convenience wrapper over :func:`best_fit_free` (the
    scheduler's pools carry a persistent FreeSet and skip the rebuild)."""
    return best_fit_free(FreeSet(grid, used), accel, chip_shape)
