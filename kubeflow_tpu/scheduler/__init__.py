"""Fleet scheduler: topology-aware gang queueing for TPU pod slices.

The notebook controller reconciles a Notebook CR into a multi-host pod-slice
gang but admits every gang unconditionally — ResourceQuota bounds a
*namespace's* chip budget (``profile_controller._quota_spec``), yet nothing
models fleet capacity, so gangs either over-commit node pools or fail
opaquely at the kubelet. This package closes that gap with a scheduler that
sits between the notebook controller and the cluster:

- ``fleet.py``    — node pools as free/used torus cuboids, fed from Nodes;
- ``binpack.py``  — topology-aware best-fit placement of a SliceTopology
  request, minimizing fragmentation;
- ``queue.py``    — priority gang queue with aging (all-or-nothing
  admission, FIFO within priority, no starvation);
- ``preemption.py`` — victim selection (lowest priority, then youngest,
  then fewest chips) and hole-backfill of small gangs;
- ``controller.py`` — a reconciler under ``runtime/manager.py`` that binds
  gangs via annotation + nodeSelector and writes ``Queued`` /
  ``Unschedulable`` / ``Preempted`` status conditions;
- ``soak.py``     — the seeded chaos convergence soak
  (``tools/sched_soak.py``).

This module holds only the wire contract shared with the notebook
controller, culler, and web apps (annotation keys, condition types, and the
placement codec), so importing it never drags in scheduler internals.
"""
from __future__ import annotations

import json
from typing import Mapping

# The single atomic commit point of a bind: one annotation write carries the
# whole gang's placement (every slice), so a gang is either fully placed or
# not placed at all — crash-restart between any two writes cannot leave a
# half-bound gang.
PLACEMENT_ANNOTATION = "scheduling.kubeflow.org/placement"
# Admission timestamp: queue order (FIFO within priority) and aging both key
# off it, and persisting it on the CR is what lets a restarted scheduler
# rebuild the exact queue order.
QUEUED_AT_ANNOTATION = "scheduling.kubeflow.org/queued-at"
# User-set gang priority (integer, default 0); larger schedules first.
PRIORITY_ANNOTATION = "scheduling.kubeflow.org/priority"
# Structured placement explanation (scheduler/explain.py): ONE annotation
# write — crash-safe like the bind — carrying the per-pool verdict trail for
# a gang the pack phase failed to place (why each pool rejected the shape,
# whether preemption was considered and why it was rejected, whether the
# fleet is merely fragmented). Written at the unschedulable transition,
# refreshed when the fleet state it describes moves, cleared by the bind
# write itself; the soaks re-prove every claim against the ground-truth
# fleet per seed (explain.audit_explanations).
EXPLANATION_ANNOTATION = "scheduling.kubeflow.org/explanation"

# Status condition types the scheduler owns on a Notebook. Everything else
# in .status.conditions belongs to the notebook controller, which preserves
# these types when it rewrites status (SCHEDULER_CONDITION_TYPES is the
# ownership boundary between the two reconcilers).
COND_QUEUED = "Queued"
COND_UNSCHEDULABLE = "Unschedulable"
COND_PREEMPTED = "Preempted"
SCHEDULER_CONDITION_TYPES = (COND_QUEUED, COND_UNSCHEDULABLE, COND_PREEMPTED)

# Node labels the fleet model is built from. Pool membership comes from the
# GKE node-pool label; the host index pins a Node to its host-block
# coordinate inside the pool's torus (fake nodes carry it explicitly; real
# GKE nodes fall back to the trailing ordinal in the node name).
POOL_LABEL = "cloud.google.com/gke-nodepool"
HOST_INDEX_LABEL = "tpu.kubeflow.org/host-index"

# Spot-revocation notice (written by the capacity reconciler when the cloud
# provider serves notice on a pool; value = the kill deadline). A revoked
# node is NOT cordoned — its pods must stay up through the suspend barrier —
# but the fleet model refuses NEW binds into any pool carrying the mark, so
# a revocation storm cannot keep re-binding fresh gangs into dying chips.
REVOKED_ANNOTATION = "capacity.kubeflow.org/revoked"
# Capacity tier of a node pool (capacity/): "spot" pools are the cheaper,
# revocable tier the autoscaler prefers when allowed; absent or "on-demand"
# is the durable tier. Stamped on Nodes by the provisioning provider.
TIER_LABEL = "tpu.kubeflow.org/capacity-tier"
TIER_SPOT = "spot"
TIER_ON_DEMAND = "on-demand"
# Nodes the autoscaler itself provisioned (stamped by the provider): the
# only pools scale-down may ever delete — the platform never reclaims
# capacity an operator created by hand.
AUTOSCALED_LABEL = "tpu.kubeflow.org/autoscaled"


def placement_of(nb: Mapping) -> dict | None:
    """Decode the bound placement from a Notebook CR, or None if unbound.

    A malformed annotation (half a write never happens — but a user can
    kubectl-edit garbage in) reads as unbound: the scheduler then re-places
    the gang rather than crash-looping on it.
    """
    raw = (nb.get("metadata", {}).get("annotations") or {}).get(
        PLACEMENT_ANNOTATION
    )
    if not raw:
        return None
    try:
        placement = json.loads(raw)
    except ValueError:
        return None
    slices = placement.get("slices")
    if not isinstance(slices, list) or not slices:
        return None
    for s in slices:
        if not isinstance(s, dict) or not s.get("pool") or not s.get("shape"):
            return None
    return placement


def encode_placement(slices: list[dict], bound_at: float) -> str:
    """Serialize a gang placement for the annotation (sorted keys: the soak
    fingerprints annotations, so the encoding must be canonical)."""
    return json.dumps(
        {"boundAt": bound_at, "slices": slices}, sort_keys=True
    )


def explanation_of(nb: Mapping) -> dict | None:
    """Decode the placement explanation from a Notebook CR, or None.

    Same posture as :func:`placement_of`: a malformed annotation (user-
    edited garbage) reads as absent — consumers fall back to the condition
    message rather than 500 on it, and the scheduler rewrites it on the
    next refresh."""
    raw = (nb.get("metadata", {}).get("annotations") or {}).get(
        EXPLANATION_ANNOTATION
    )
    if not raw:
        return None
    try:
        exp = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(exp, dict) or not exp.get("reason"):
        return None
    return exp


def encode_explanation(payload: Mapping) -> str:
    """Canonical explanation encoding (sorted keys, like the placement
    codec: the soaks fingerprint annotations, and write-skipping compares
    encoded strings, so the encoding must be deterministic)."""
    return json.dumps(payload, sort_keys=True)


def gang_priority(nb: Mapping) -> int:
    raw = (nb.get("metadata", {}).get("annotations") or {}).get(
        PRIORITY_ANNOTATION
    )
    try:
        return int(raw) if raw is not None else 0
    except ValueError:
        return 0


def merge_conditions(others: list, scheduler_conds: list) -> list:
    """The canonical ``.status.conditions`` layout BOTH reconcilers write:
    non-scheduler conditions first (caller order), scheduler-owned types
    appended sorted by type. The notebook controller passes (its own fresh
    conditions, the live list) to carry scheduler types over; the scheduler
    passes (the live list, its own conditions) to own exactly its types.
    One implementation — if the two writers ever disagreed on the layout
    they would rewrite each other's status every cycle and never settle."""
    return [
        c for c in others if c.get("type") not in SCHEDULER_CONDITION_TYPES
    ] + sorted(
        (
            c for c in scheduler_conds
            if c.get("type") in SCHEDULER_CONDITION_TYPES
        ),
        key=lambda c: c.get("type", ""),
    )


def placement_matches(placement: Mapping, topo, num_slices: int) -> bool:
    """Does a committed placement still describe the CR's current request?
    Slice count must match and every slice must be the requested topology
    (up to the axis rotation placement is allowed to apply). Checked by the
    scheduler before replaying occupancy AND by the notebook controller
    before acting on a placement — a spec edit on a bound gang must gate
    the gang, not run the new shape on the old reservation."""
    slices = placement.get("slices") or []
    if len(slices) != num_slices:
        return False
    want = sorted(topo.shape)
    return all(
        s.get("accelerator") == topo.accelerator.name
        and sorted(s.get("shape") or []) == want
        for s in slices
    )


def condition(nb: Mapping, type_: str) -> dict | None:
    for c in (nb.get("status") or {}).get("conditions", []) or []:
        if c.get("type") == type_:
            return c
    return None


def condition_is_true(nb: Mapping, type_: str) -> bool:
    c = condition(nb, type_)
    return bool(c) and c.get("status") == "True"
