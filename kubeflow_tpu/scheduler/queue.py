"""Priority gang queue with aging.

Admission is all-or-nothing at the *gang* level — a queue entry is a whole
notebook (every slice of a multislice gang), never a pod. Ordering is
strict priority, FIFO within a priority class, with time-based aging lifting
long-waiters: effective priority grows by one class per ``aging_interval_s``
waited, so any gang eventually outranks a bounded set of higher-priority
arrivals — the no-starvation argument the soak leans on (a blocked head of
queue ages until preemption clears space for it, provided it is feasible at
all).

The queue is rebuilt from CR annotations every scheduling cycle
(``queued-at`` persists admission time), so it has no state a scheduler
crash can lose; this module is the pure ordering logic.
"""
from __future__ import annotations

import dataclasses

from kubeflow_tpu.tpu.topology import SliceTopology

DEFAULT_AGING_INTERVAL_S = 300.0


@dataclasses.dataclass(frozen=True)
class GangRequest:
    """One queued gang: a notebook wanting capacity for all its slices."""

    key: str            # "<namespace>/<name>"
    priority: int       # user-declared class; larger schedules first
    queued_at: float    # admission time (persisted on the CR)
    topo: SliceTopology
    num_slices: int = 1

    @property
    def chips(self) -> int:
        return self.topo.num_chips * self.num_slices


class GangQueue:
    def __init__(
        self, *, aging_interval_s: float = DEFAULT_AGING_INTERVAL_S
    ) -> None:
        self.aging_interval_s = aging_interval_s
        self._gangs: dict[str, GangRequest] = {}

    def push(self, req: GangRequest) -> None:
        self._gangs[req.key] = req

    def discard(self, key: str) -> None:
        """Remove a gang (bound, stopped, culled, or deleted). Culling a
        queued gang MUST pass through here — a ghost entry would hold a
        phantom claim on capacity accounting."""
        self._gangs.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return key in self._gangs

    def __len__(self) -> int:
        return len(self._gangs)

    def family_depths(self) -> dict[str, int]:
        """Waiting gangs per accelerator family (the per-family queue-depth
        gauge) — one O(depth) pass, no sort."""
        out: dict[str, int] = {}
        for r in self._gangs.values():
            fam = r.topo.accelerator.name
            out[fam] = out.get(fam, 0) + 1
        return out

    def effective_priority(self, req: GangRequest, now: float) -> float:
        """Continuous aging: one priority class per ``aging_interval_s``
        waited. Continuous (not floored) on purpose — the *relative* rank of
        two waiting gangs is then time-invariant (their boost difference is
        a constant), so the queue order is stable between membership
        changes; a floored boost would flip a cross-priority pair back and
        forth forever as the two boost phases cross, and the soak's
        quiescence check would never settle. Aging still does its job
        against new arrivals, which start with zero boost."""
        waited = max(0.0, now - req.queued_at)
        return req.priority + waited / self.aging_interval_s

    def ordered(self, now: float) -> list[GangRequest]:
        """Scheduling order: effective priority desc, then FIFO
        (queued_at asc), then key — a total, deterministic order. The
        1-based positions the spawner UI shows are this list's indices
        (the controller derives them all in one pass per cycle). For gangs
        admitted in the past (the only kind the controller stamps) the
        order is time-invariant — the boost difference between two waiters
        is a constant — so one sort per cycle is the whole ordering cost;
        the ``max(0, ...)`` clamp only matters for future-dated admission
        times, where a fresh arrival must not carry a negative boost."""
        return sorted(
            self._gangs.values(),
            key=lambda r: (
                -self.effective_priority(r, now), r.queued_at, r.key,
            ),
        )
