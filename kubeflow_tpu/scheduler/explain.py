"""Placement explainability: per-pool scheduling verdicts, fragmentation
telemetry, and the per-seed explanation audit (docs/scheduler.md
"explainability").

The platform's contract is that a user who asks for a TPU slice either
gets chips or gets told *why not* — but the pack phase used to collapse
every failure to one generic string. The knowledge was all there (which
pool rejected which orientation and why, whether preemption was even an
option), computed and thrown away every cycle. This module keeps it:

- :func:`pool_verdict` judges ONE pool against ONE slice shape from the
  pool's live free decomposition: ``ShapeNeverFits`` (no orientation fits
  the torus even empty), ``Fragmented`` (free chips suffice but no free
  cuboid admits any orientation — the defrag signal), ``BlockedHosts``
  (the fit exists once drained/missing hosts heal), ``InsufficientFree``
  (capacity genuinely in use), ``SliceFits`` (this pool could take one
  slice; the gang failed elsewhere — multislice spread).
- :class:`ExplainRecorder` is the controller-side state machine: pack-
  phase failures become ONE ``scheduling.kubeflow.org/explanation``
  annotation write per transition, skipped entirely while the per-pool
  occupancy ``version`` tokens are unchanged (a steady blocked queue
  costs a tuple compare per gang, never a re-pack) and bounded per cycle
  (``budget``) so a pathological cycle cannot turn explanation work into
  the new hot path. Reason transitions feed
  ``scheduler_unschedulable_total{reason}`` and the time-in-reason
  histogram; ``since`` is persisted in the annotation so a crash-restart
  resumes the clock instead of resetting it.
- :func:`audit_explanations` is the soak-side prover: every claim in
  every emitted explanation is re-derived from the ground-truth fleet
  (Nodes + committed placements). If an explanation says "no v4 pool has
  a free 2x2x2", the auditor packs the shape against the real free sets
  and must also fail; a planted false verdict fails the seed. That audit
  is what makes the surface trustworthy enough to page on.

Fragmentation telemetry rides the same geometry — a pool's fragmentation
index is largest-free-cuboid ÷ free host cells (1.0 = one contiguous
hole, →0 = shattered), and ``would_fit_after_defrag`` counts waiting
gangs whose only blocker is contiguity: the exact trigger signal the
live-migration and elastic-capacity roadmap items consume ("more chips
would NOT help; defrag would").
"""
from __future__ import annotations

import math
from typing import Iterable, Mapping

from kubeflow_tpu import scheduler as sched
from kubeflow_tpu import sessions as sess
from kubeflow_tpu.api import types as api
from kubeflow_tpu.runtime import objects as ko
from kubeflow_tpu.scheduler import binpack
from kubeflow_tpu.scheduler import preemption as preempt
from kubeflow_tpu.scheduler.fleet import Fleet, Pool, _BLOCKED_PREFIX
from kubeflow_tpu.scheduler.queue import GangRequest
from kubeflow_tpu.tpu.topology import SliceTopology

# Per-cycle cap on explanation (re)computations. Each one is a handful of
# read-only fit probes over the gang's family pools — cheap, but a 10k-gang
# backlog transitioning at once must not turn the pack phase's tail into
# explanation work. Overflow simply keeps last cycle's annotation; blocked
# gangs persist, so the budget catches up within a few cycles (the audit
# runs at the quiesced fixed point, where it has).
DEFAULT_EXPLAIN_BUDGET = 32

# Gang-level reasons (the `reason` field — the top blocking verdict).
REASON_SHAPE_NEVER_FITS = "ShapeNeverFits"
REASON_FRAGMENTED = "Fragmented"
REASON_BLOCKED_HOSTS = "BlockedHosts"
REASON_INSUFFICIENT = "InsufficientCapacity"
REASON_AWAITING_HANDOFF = "AwaitingHandoff"

# Per-pool verdicts (the `pools[].verdict` field).
VERDICT_SHAPE_NEVER_FITS = "ShapeNeverFits"
VERDICT_FRAGMENTED = "Fragmented"
VERDICT_BLOCKED_HOSTS = "BlockedHosts"
VERDICT_INSUFFICIENT_FREE = "InsufficientFree"
VERDICT_SLICE_FITS = "SliceFits"
# Spot revocation in flight (capacity/): the pool's chips are leaving, so
# no free space there counts for anyone — ranked before every geometric
# verdict, exactly as place_gang skips the pool before probing it.
VERDICT_REVOKED = "PoolRevoked"

# Preemption-trail phrasings (the `preemption.why` field).
PREEMPT_NO_JUNIORS = "no strictly-junior victims"
PREEMPT_INSUFFICIENT_RECLAIM = (
    "evicting every junior gang still would not fit this gang"
)
PREEMPT_HANDOFF = (
    "victims are suspending; chips hand over when their snapshots commit"
)
PREEMPT_NOT_HEAD = "not at the head of its queue"
PREEMPT_FROZEN = "backfill frozen while a suspend handoff resolves"


# ------------------------------------------------------------ pure geometry


def fitting_orientations(
    pool: Pool, topo: SliceTopology
) -> list[tuple[tuple[int, ...], tuple[int, ...]]]:
    """The request orientations that fit this pool's torus when EMPTY —
    geometry only, independent of occupancy."""
    return [
        (chips, blocks)
        for chips, blocks in binpack.orientations(pool.accel, topo.shape)
        if all(b <= g for b, g in zip(blocks, pool.grid))
    ]


def min_block_cells(pool: Pool, topo: SliceTopology) -> int | None:
    """Fewest host cells any geometrically-valid orientation needs in this
    pool, or None when no orientation fits even an empty torus."""
    opts = fitting_orientations(pool, topo)
    if not opts:
        return None
    return min(math.prod(blocks) for _, blocks in opts)


def slice_fits_now(pool: Pool, topo: SliceTopology) -> bool:
    """Exact, read-only single-slice fit probe against the live free set
    (the same ``best_fit_free`` the bind path uses, so "the auditor packs
    the shape against the real free sets" is literally this call)."""
    return binpack.best_fit_free(pool.free_space, pool.accel, topo.shape) is not None


def slice_fits_if_healthy(pool: Pool, topo: SliceTopology) -> bool:
    """Would one slice fit if every drained/missing host healed, with the
    bound gangs keeping their carves? Distinguishes "chips are in use"
    from "chips are gone" — the verdict a drain causes."""
    blocked = [
        cub for key, cub in pool.used.items()
        if key.startswith(_BLOCKED_PREFIX)
    ]
    if not blocked:
        return False  # nothing to heal; the live probe already answered
    healthy = pool.free_space.clone()
    for cub in blocked:
        healthy.release(cub)
    return binpack.best_fit_free(healthy, pool.accel, topo.shape) is not None


def largest_free_cuboid_cells(pool: Pool) -> int:
    return max((c.volume for c in pool.free_space.cuboids), default=0)


def fragmentation_index(pool: Pool) -> float:
    """Largest free cuboid ÷ free host cells, in [0, 1]. 1.0 means the free
    space is one contiguous hole (or the pool is full — nothing to
    fragment); values toward 0 mean the same chip count is shattered into
    unusably small holes. Host cells and chips give the identical ratio
    (chips-per-block cancels), so this is also largest-free-cuboid-chips ÷
    free chips, the form the dashboard labels it with."""
    free = pool.free_cells()
    if free == 0:
        return 1.0
    return largest_free_cuboid_cells(pool) / free


def pool_verdict(pool: Pool, topo: SliceTopology) -> dict:
    """One pool's verdict for one slice shape, derived ONLY from the pool's
    live state — the audit re-runs this exact function on the ground-truth
    fleet, so every field is a checkable claim, not prose.

    Verdict ranking (first match wins):
      PoolRevoked      — a spot revocation notice stands on the pool: its
                         free space is leaving and counts for nobody
                         (mirrors place_gang skipping the pool outright);
      ShapeNeverFits   — no orientation fits the empty torus;
      SliceFits        — a slice fits right now (the gang failed elsewhere:
                         multislice spread, or this pool filled mid-trial);
      Fragmented       — enough free cells for some orientation, but no
                         placement exists: contiguity is the only blocker;
      BlockedHosts     — too few free cells, and healing drained/missing
                         hosts would admit the slice;
      InsufficientFree — the capacity is genuinely held by other gangs.
    """
    free_cells = pool.free_cells()
    out = {
        "pool": pool.name,
        "freeChips": pool.free_chips(),
        "largestFreeCuboidChips": largest_free_cuboid_cells(pool)
        * pool.chips_per_block,
        "fragmentationIndex": round(fragmentation_index(pool), 4),
    }
    if pool.revoked:
        out["verdict"] = VERDICT_REVOKED
        return out
    need = min_block_cells(pool, topo)
    if need is None:
        out["verdict"] = VERDICT_SHAPE_NEVER_FITS
        return out
    if slice_fits_now(pool, topo):
        out["verdict"] = VERDICT_SLICE_FITS
        return out
    if free_cells >= need:
        out["verdict"] = VERDICT_FRAGMENTED
        return out
    if slice_fits_if_healthy(pool, topo):
        out["verdict"] = VERDICT_BLOCKED_HOSTS
        return out
    out["verdict"] = VERDICT_INSUFFICIENT_FREE
    return out


def would_fit_after_defrag(
    pools: Iterable[Pool], topo: SliceTopology, num_slices: int
) -> bool:
    """Would the gang fit if free space were compacted (live migration /
    defrag), with nothing evicted and no hosts healed?

    Free cell COUNTS are invariant under migration, so the gang fits after
    some defrag only if its slices can be assigned to pools such that each
    pool has enough free cells for its share and the shape fits the pool's
    torus at all. Slices of one gang are identical, so the assignment
    reduces to capacity counting: sum over geometrically-eligible pools of
    floor(free_cells / min-orientation-cells) ≥ num_slices. This is the
    optimistic bound — True means "defrag may admit it, more chips
    definitely aren't needed"; False means only new capacity (or
    preemption) can help. The roadmap's live-migration and autoscaler
    items branch on exactly this bit."""
    capacity = 0
    for pool in pools:
        if pool.revoked:
            # revoked free space cannot be defragged into: it is leaving
            continue
        need = min_block_cells(pool, topo)
        if need is None:
            continue
        capacity += pool.free_cells() // need
        if capacity >= max(1, num_slices):
            return True
    return False


# ------------------------------------------------------- gang-level verdict


def _gang_reason(
    pool_verdicts: list[dict],
    topo: SliceTopology,
    num_slices: int,
    note: Mapping,
    wfad: bool,
) -> tuple[str, str]:
    """(reason, human message) — the top blocking verdict the spawner
    shows. Pure function of the per-pool verdicts and the pack note, so
    the audit can re-derive it."""
    fam = topo.accelerator.name
    gang = topo.slice_name + (f" x{num_slices}" if num_slices > 1 else "")
    pre = note.get("preemption") or {}
    if note.get("role") == "unschedulable":
        # admission's verdict (feasible_on_empty == False): no combination
        # of this fleet's pools can EVER hold the gang — stronger than any
        # per-pool verdict (a multislice gang can be unschedulable even
        # when each slice alone would fit somewhere)
        return (
            REASON_SHAPE_NEVER_FITS,
            f"no {fam} node pools can hold {gang} in any orientation, "
            f"even on an empty fleet",
        )
    if pre.get("outcome") == "accepted" or pre.get("why") == PREEMPT_FROZEN:
        return (
            REASON_AWAITING_HANDOFF,
            f"{gang} is next in line: a preemption handoff is in flight on "
            f"{fam} and chips hand over when the victims' snapshots commit",
        )
    if not pool_verdicts:
        return (
            REASON_SHAPE_NEVER_FITS,
            f"no {fam} node pools exist in this fleet",
        )
    if all(
        v["verdict"] == VERDICT_SHAPE_NEVER_FITS for v in pool_verdicts
    ):
        return (
            REASON_SHAPE_NEVER_FITS,
            f"no {fam} node pool can hold {gang} in any orientation",
        )
    if all(
        v["verdict"] in (VERDICT_SHAPE_NEVER_FITS, VERDICT_REVOKED)
        for v in pool_verdicts
    ):
        return (
            REASON_INSUFFICIENT,
            f"every {fam} pool that could hold {gang} is under a spot "
            f"revocation notice; waiting for replacement capacity",
        )
    # revoked pools' free chips are leaving the fleet: counting them in the
    # exhausted/unusable arithmetic would contradict the verdicts above
    free = sum(
        v["freeChips"] for v in pool_verdicts
        if v["verdict"] != VERDICT_REVOKED
    )
    if wfad:
        largest = max(
            v["largestFreeCuboidChips"] for v in pool_verdicts
            if v["verdict"] != VERDICT_REVOKED
        )
        return (
            REASON_FRAGMENTED,
            f"{fam} capacity is fragmented: {free} chips are free (largest "
            f"contiguous block {largest}) but no pool offers a contiguous "
            f"{gang}; defragmentation would admit it",
        )
    if any(v["verdict"] == VERDICT_BLOCKED_HOSTS for v in pool_verdicts):
        return (
            REASON_BLOCKED_HOSTS,
            f"{gang} would fit once drained or missing {fam} hosts return",
        )
    needed = topo.num_chips * max(1, num_slices)
    if free >= needed:
        # enough chips in total, but split across pools in holes too small
        # for even one slice (per-pool wfad floored to zero) — saying
        # "exhausted: 24 free, needs 16" would contradict itself
        msg = (
            f"{fam} free capacity is unusable for {gang}: {free} chips "
            f"free but split across pools in holes too small for its slices"
        )
    else:
        msg = (
            f"{fam} capacity is exhausted: {free} chips free, "
            f"{gang} needs {needed}"
        )
    if pre.get("outcome") == "rejected" and pre.get("why"):
        msg += f"; preemption rejected ({pre['why']})"
    return (REASON_INSUFFICIENT, msg)


class ExplainRecorder:
    """Controller-side explanation state, carried across cycles like the
    fit cache: advisory in-memory acceleration over the annotation-is-the-
    store contract (a crash-restart starts cold and re-derives everything,
    `since` included, from the annotations themselves).

    ``explain`` returns the encoded annotation value the gang SHOULD carry
    — or None when the budget is spent (keep whatever is written; later
    cycles catch up). The signature check makes the steady state free:
    while the gang's rv-independent inputs (shape, role, preemption note)
    and every family pool's occupancy ``version`` are unchanged, the cached
    encoding is returned without touching geometry."""

    def __init__(self, *, metrics=None, budget: int = DEFAULT_EXPLAIN_BUDGET) -> None:
        self.metrics = metrics
        self.budget = budget
        self._budget_left = budget
        # key -> {"sig", "encoded", "reason", "since", "wfad"}
        self._state: dict[str, dict] = {}

    def begin_cycle(self) -> None:
        self._budget_left = self.budget

    def adopt(self, view, now: float) -> str | None:
        """Ensure the gang has recorder state and return its current reason.

        On a fresh incarnation the reason + since are adopted from the
        persisted annotation, so the caller's transition check (emit the
        Unschedulable Event only when the reason CHANGES) sees a restart as
        the steady state it is, and the time-in-reason clock keeps running
        across crashes instead of resetting."""
        entry = self._state.get(view.key)
        if entry is None:
            prev = sched.explanation_of(view.nb)
            try:
                since = float(prev.get("since", now)) if prev else now
            except (TypeError, ValueError):
                since = now  # user-edited garbage: restart the clock
            entry = {
                "sig": None,
                "encoded": None,
                "reason": prev.get("reason") if prev else None,
                "since": since,
                "wfad": bool(prev.get("wouldFitAfterDefrag"))
                if prev else False,
            }
            self._state[view.key] = entry
        return entry["reason"]

    def reason_of(self, key: str) -> str | None:
        entry = self._state.get(key)
        return entry["reason"] if entry else None

    # ------------------------------------------------------------- recording

    def explain(
        self,
        view,
        fleet: Fleet,
        note: Mapping,
        now: float,
        *,
        shard: str | None = None,
    ) -> str | None:
        topo, num_slices = view.topo, view.num_slices
        fam = topo.accelerator.name
        pools = sorted(
            (p for p in fleet.pools.values() if p.accel.name == fam),
            key=lambda p: p.name,
        )
        pre = note.get("preemption") or {
            "considered": False, "why": PREEMPT_NOT_HEAD,
        }
        sig = (
            fam,
            tuple(sorted(topo.shape)),
            num_slices,
            note.get("role", ""),
            note.get("head", ""),
            pre.get("outcome", ""),
            pre.get("why", ""),
            shard or "",
            tuple((p.name, p.version) for p in pools),
        )
        self.adopt(view, now)
        entry = self._state[view.key]
        if entry["sig"] == sig and entry["encoded"] is not None:
            return entry["encoded"]
        if self._budget_left <= 0:
            return None
        self._budget_left -= 1

        verdicts = [pool_verdict(p, topo) for p in pools]
        wfad = would_fit_after_defrag(pools, topo, num_slices)
        reason, message = _gang_reason(
            verdicts, topo, num_slices, note, wfad
        )
        if reason != entry["reason"]:
            if self.metrics is not None:
                self.metrics.observe_reason_transition(
                    reason,
                    prev=entry["reason"],
                    seconds_in_prev=max(0.0, now - entry["since"]),
                )
            entry["reason"] = reason
            entry["since"] = now
        payload: dict = {
            "reason": reason,
            "message": message,
            "since": entry["since"],
            "role": note.get("role", "unschedulable"),
            "shape": {
                "accelerator": fam,
                "chips": sorted(topo.shape),
                "numSlices": num_slices,
            },
            "wouldFitAfterDefrag": wfad,
            "preemption": dict(pre),
            "pools": verdicts,
        }
        if note.get("head"):
            payload["headKey"] = note["head"]
        if shard is not None:
            payload["shard"] = shard
        entry["sig"] = sig
        entry["encoded"] = sched.encode_explanation(payload)
        entry["wfad"] = wfad
        return entry["encoded"]

    # ------------------------------------------------------------- lifecycle

    def clear(self, key: str, now: float) -> None:
        """The gang left the blocked set (bound, stopped, explanation
        dropped): close out its time-in-reason observation."""
        entry = self._state.pop(key, None)
        if entry is None or entry["reason"] is None:
            return
        if self.metrics is not None:
            self.metrics.observe_reason_transition(
                None,
                prev=entry["reason"],
                seconds_in_prev=max(0.0, now - entry["since"]),
            )

    def sweep(self, alive: set[str]) -> None:
        """Drop state for gangs that vanished (deleted mid-cycle): nothing
        to observe — the object, its annotation, and its clock are gone."""
        for key in [k for k in self._state if k not in alive]:
            del self._state[key]

    def would_fit_count(self) -> int:
        return sum(1 for e in self._state.values() if e.get("wfad"))


# ----------------------------------------------------------- the probe route


def install_explain_route(app, cluster) -> None:
    """Mount /debug/explain/<ns>/<name> on a web App (the probe port, next
    to /debug/traces and /debug/timeline — cluster-internal, never the
    gateway): the decoded explanation plus the scheduler-owned conditions,
    the "why is my notebook still pending" page for operators."""
    import json as _json

    from werkzeug.wrappers import Response

    @app.route("/debug/explain/<namespace>/<name>")
    def debug_explain(request, namespace, name):
        nb = cluster.try_get("Notebook", name, namespace)
        if nb is None:
            return Response(
                _json.dumps({"error": "no such notebook"}),
                status=404, mimetype="application/json",
            )
        payload = {
            "namespace": namespace,
            "name": name,
            "bound": sched.placement_of(nb) is not None,
            "explanation": sched.explanation_of(nb),
            "conditions": [
                c
                for c in (nb.get("status") or {}).get("conditions", []) or []
                if c.get("type") in sched.SCHEDULER_CONDITION_TYPES
            ],
        }
        return Response(
            _json.dumps(payload, sort_keys=True),
            mimetype="application/json",
        )


# ------------------------------------------------------------------ the audit


def _ground_truth(base) -> tuple[Fleet, list[preempt.BoundGang], list[dict]]:
    """The real fleet as the scheduler must have seen it: pools from live
    Nodes (drained/missing hosts BLOCKED — unlike the double-booking
    audit's healthy fleet, explanations are claims about usable space) with
    every committed placement replayed in."""
    fleet = Fleet.from_nodes(base.list("Node"))
    bound: list[preempt.BoundGang] = []
    notebooks = []
    for nb in base.list("Notebook"):
        try:
            topo = api.notebook_topology(nb)
        except ValueError:
            topo = None
        if topo is None:
            continue
        key = f"{ko.namespace(nb)}/{ko.name(nb)}"
        num_slices = api.notebook_num_slices(nb)
        placement = sched.placement_of(nb)
        if placement is not None:
            fleet.occupy_gang(key, placement["slices"])
            anns = ko.annotations(nb)
            try:
                queued_at = float(anns.get(sched.QUEUED_AT_ANNOTATION, 0.0))
            except (TypeError, ValueError):
                queued_at = 0.0
            bound.append(preempt.BoundGang(
                key=key,
                priority=sched.gang_priority(nb),
                queued_at=queued_at,
                chips=topo.num_chips * num_slices,
                topo=topo,
                num_slices=num_slices,
            ))
        notebooks.append(
            {"nb": nb, "topo": topo, "key": key,
             "num_slices": num_slices, "placement": placement}
        )
    return fleet, bound, notebooks


def audit_explanations(
    base, *, router=None, where: str = "final"
) -> list[str]:
    """The per-seed explanation audit (docs/chaos.md): every emitted
    explanation's claims re-proven against the ground-truth fleet, plus
    the lifecycle invariants. Runs at the quiesced fixed point (healed
    data plane), where the scheduler has had every chance to refresh —
    any surviving mismatch is a real lie, not a transient.

    - a BOUND or STOPPED gang carries no explanation (cleared on bind /
      teardown), and an explanation's recorded shape matches the CURRENT
      spec (wiped on spec.tpu edit);
    - per-pool verdicts equal :func:`pool_verdict` recomputed on the real
      pool — which re-packs the shape against the real free set, so
      "Fragmented"/"InsufficientFree" with a shape that actually fits is
      caught here (the planted-false-verdict test plants exactly that);
    - the gang-level reason, would-fit-after-defrag bit, and preemption
      trail (no-juniors / insufficient-reclaim / handoff) are re-derived
      from the same store;
    - sharded: the explanation carries the OWNING shard's stamp — a gang
      explained by a shard that does not own its family is a routing bug.
    """
    out: list[str] = []
    fleet, bound, notebooks = _ground_truth(base)
    suspending_fams = {
        e["topo"].accelerator.name
        for e in notebooks
        if (req := sess.suspend_request(e["nb"])) is not None
        and req.get("reason") == sess.REASON_PREEMPTION
    }
    for entry in notebooks:
        nb, topo, key = entry["nb"], entry["topo"], entry["key"]
        num_slices, placement = entry["num_slices"], entry["placement"]
        anns = ko.annotations(nb)
        active = api.STOP_ANNOTATION not in anns
        raw = anns.get(sched.EXPLANATION_ANNOTATION)
        if raw is None:
            if active and placement is None and sched.condition_is_true(
                nb, sched.COND_UNSCHEDULABLE
            ):
                out.append(
                    f"{where}: {key}: marked Unschedulable but carries no "
                    f"explanation"
                )
            continue
        exp = sched.explanation_of(nb)
        if exp is None:
            out.append(f"{where}: {key}: unparseable explanation annotation")
            continue
        if placement is not None:
            out.append(
                f"{where}: {key}: explanation survived the bind (must be "
                f"cleared in the bind write)"
            )
            continue
        if not active:
            out.append(f"{where}: {key}: explanation on a stopped gang")
            continue
        shape = exp.get("shape") or {}
        if (
            shape.get("accelerator") != topo.accelerator.name
            or list(shape.get("chips") or []) != sorted(topo.shape)
            or shape.get("numSlices") != num_slices
        ):
            out.append(
                f"{where}: {key}: explanation describes shape {shape}, "
                f"spec wants {topo.accelerator.name} "
                f"{sorted(topo.shape)} x{num_slices} (stale after edit)"
            )
            continue
        fam = topo.accelerator.name
        if router is not None:
            owner = router.stamp(router.shard_for_family(fam))
            if exp.get("shard") != owner:
                out.append(
                    f"{where}: {key}: explanation stamped by shard "
                    f"{exp.get('shard')!r}, owner is {owner!r}"
                )
        family_pools = sorted(
            (p for p in fleet.pools.values() if p.accel.name == fam),
            key=lambda p: p.name,
        )
        recorded = {
            v["pool"]: v
            for v in exp.get("pools") or []
            if isinstance(v, dict) and isinstance(v.get("pool"), str)
        }
        if sorted(recorded) != [p.name for p in family_pools]:
            out.append(
                f"{where}: {key}: explanation covers pools "
                f"{sorted(recorded)}, fleet has "
                f"{[p.name for p in family_pools]}"
            )
        reproved = []
        for pool in family_pools:
            got = recorded.get(pool.name)
            if got is None:
                continue
            want = pool_verdict(pool, topo)
            reproved.append(want)
            for field in (
                "verdict", "freeChips", "largestFreeCuboidChips",
                "fragmentationIndex",
            ):
                if got.get(field) != want[field]:
                    out.append(
                        f"{where}: {key}: pool {pool.name} claims "
                        f"{field}={got.get(field)!r}, ground truth is "
                        f"{want[field]!r}"
                    )
            # the headline claim proven directly against the real free set,
            # not just by recompute-agreement: a blocking verdict with a
            # shape that actually packs is a lie wherever it came from
            if got.get("verdict") in (
                VERDICT_FRAGMENTED, VERDICT_BLOCKED_HOSTS,
                VERDICT_INSUFFICIENT_FREE, VERDICT_SHAPE_NEVER_FITS,
            ) and slice_fits_now(pool, topo):
                out.append(
                    f"{where}: {key}: pool {pool.name} verdict "
                    f"{got.get('verdict')} but {topo.slice_name} packs into "
                    f"its real free set"
                )
        reason = exp.get("reason")
        if reason == REASON_SHAPE_NEVER_FITS:
            if fleet.feasible_on_empty(topo, num_slices):
                out.append(
                    f"{where}: {key}: claims {REASON_SHAPE_NEVER_FITS} but "
                    f"the gang is feasible on an empty fleet"
                )
        elif fleet.clone().place_gang(key, topo, num_slices) is not None:
            out.append(
                f"{where}: {key}: explained as blocked ({reason}) but the "
                f"gang packs into real free space right now"
            )
        wfad = would_fit_after_defrag(family_pools, topo, num_slices)
        if bool(exp.get("wouldFitAfterDefrag")) != wfad:
            out.append(
                f"{where}: {key}: wouldFitAfterDefrag recorded "
                f"{exp.get('wouldFitAfterDefrag')!r}, ground truth {wfad}"
            )
        pre = exp.get("preemption") or {}
        if pre.get("outcome") == "rejected":
            try:
                queued_at = float(anns.get(sched.QUEUED_AT_ANNOTATION, 0.0))
            except (TypeError, ValueError):
                queued_at = 0.0
            req = GangRequest(
                key=key, priority=sched.gang_priority(nb),
                queued_at=queued_at, topo=topo, num_slices=num_slices,
            )
            juniors = [
                v for v in bound
                if v.topo.accelerator.name == fam
                and preempt.eligible_victim(v, req)
            ]
            if pre.get("why") == PREEMPT_NO_JUNIORS and juniors:
                out.append(
                    f"{where}: {key}: claims '{PREEMPT_NO_JUNIORS}' but "
                    f"{[v.key for v in juniors]} are strictly junior"
                )
            if pre.get("why") == PREEMPT_INSUFFICIENT_RECLAIM:
                if not juniors:
                    out.append(
                        f"{where}: {key}: claims insufficient reclaim but "
                        f"no junior victims exist at all"
                    )
                elif preempt.select_victims(fleet, bound, req) is not None:
                    out.append(
                        f"{where}: {key}: claims insufficient reclaim but "
                        f"evicting juniors would admit the gang"
                    )
        if reason == REASON_AWAITING_HANDOFF and fam not in suspending_fams:
            out.append(
                f"{where}: {key}: claims a suspend handoff in flight on "
                f"{fam} but no gang carries a preemption suspend request"
            )
    return out
