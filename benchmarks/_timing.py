"""Shared two-window / min-over-windows estimator for tunnel benchmarks.

THE methodology (bench.py module docstring is the canonical writeup):

- the tunneled runtime charges a large FIXED latency on the first scalar
  readback of a dispatch queue → time a short and a long window, each
  ending in exactly one readback; the fixed cost cancels in the difference;
- tunnel stalls are ADDITIVE (they lengthen a window, never shorten it) →
  the minimum over repeats is each window's uncontaminated time;
- multiplicative phase drift (measured ±30% process-to-process on Pallas
  rows) needs enough repeats for the min to catch a clean phase.

``bench.py`` keeps its own inline copy ON PURPOSE: it is the driver's
entrypoint and must stay runnable as a single file; any change here must be
mirrored there (and vice versa — both cite this note).
"""
from __future__ import annotations

from typing import Callable


def min_window_step_seconds(
    window: Callable[[int], float],
    n_short: int,
    n_long: int,
    repeats: int,
) -> tuple[float, list[float], list[float]]:
    """Estimate seconds per window-unit from interleaved short/long windows.

    ``window(n)`` runs n units ending in ONE readback and returns elapsed
    seconds (callers close over any carried state). Returns
    ``(sec_per_unit, shorts, longs)`` — the raw window times let callers
    report jitter visibility (stall census, per-pair medians).
    """
    shorts: list[float] = []
    longs: list[float] = []
    for _ in range(repeats):
        shorts.append(window(n_short))
        longs.append(window(n_long))
    sec = (min(longs) - min(shorts)) / (n_long - n_short)
    return sec, shorts, longs


def ab_palindrome(
    windows: dict[str, Callable[[int], float]],
    n_short: int,
    n_long: int,
    repeats: int,
) -> dict[str, float]:
    """In-process A/B of two window fns with palindromic ordering (A B B A
    per repeat — cancels linear drift) and min-over-windows per side.

    Process-to-process phase drift on Pallas rows measured ±30%, so only an
    in-process palindrome ranks variants honestly. Returns
    ``{name: sec_per_unit}``. Call sites: moe_bench --ab/--ab-dispatch,
    transformer_bench --ab-head, resnet_ab_probe (its own ABBA predates
    this helper).
    """
    names = list(windows)
    assert len(names) == 2, names
    raw: dict[str, tuple[list, list]] = {n: ([], []) for n in names}
    for _ in range(repeats):
        for n in (names[0], names[1], names[1], names[0]):
            raw[n][0].append(windows[n](n_short))
            raw[n][1].append(windows[n](n_long))
    return {
        n: (min(longs) - min(shorts)) / (n_long - n_short)
        for n, (shorts, longs) in raw.items()
    }
