#!/usr/bin/env python
"""Control-plane sharding benchmark: aggregate placements/s vs shard count
(docs/architecture.md "control-plane sharding").

The world is one mixed-family fleet — four accelerator families (v4, v5p,
v5e, v6e), each with the same host-cell capacity and an equivalent gang-size
mix — partitioned by the REAL :class:`ShardRouter`: at N shards, shard i
runs a :class:`SchedulerReconciler` owning ``router.families_for(i)`` and
drains exactly its slice of the queue, ownership stamps and all. The
1-shard arm is the same reconciler owning every family: the single-loop
control plane over the identical world.

Methodology — per-shard isolated runs, summed: shards share NOTHING (own
leader lease, own process in the production layout, own watch streams; the
store is the apiserver, which is not the component under test), so each
shard is measured alone on an otherwise-idle machine and the aggregate is
``total placements / max(shard walls)`` — what a fleet of one-shard-per-
machine replicas achieves, on hardware with fewer cores than shards. Each
shard's run still carries the full-fleet costs a real shard pays (the
resourceVersion index scan covers all 10k notebooks, not just the owned
quarter), so the scaling number is honest about the non-partitioned work.

    python benchmarks/bench_shards.py                  # 10k gangs, sweep 1,2,4
    python benchmarks/bench_shards.py --gangs 2000     # quick local run
    python benchmarks/bench_shards.py --gangs 100000 --sweep 1,4   # the big one
    python benchmarks/bench_shards.py \
        --check-against benchmarks/shards_baseline.json \
        --sched-baseline benchmarks/sched_baseline.json    # CI perf gate

Emits one SHARD_BENCH JSON line: per-shard-count aggregate placements/s,
per-shard walls, and the headline ``scaling`` (aggregate at max shards /
aggregate at 1 shard). The gate fails when scaling drops below the
baseline's ``min_scaling`` (near-linear: >= 3x at 4 shards), when the
1-shard throughput regresses against the committed SHARD_BENCH baseline,
or when the 1-shard run falls out of tolerance with the PR 8 SCHED_BENCH
baseline (the sharded scheduler at SHARDS=1 must not tax the fast path).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from kubeflow_tpu import scheduler as sched  # noqa: E402
from kubeflow_tpu.api import types as api  # noqa: E402
from kubeflow_tpu.runtime import objects as ko  # noqa: E402
from kubeflow_tpu.runtime.fake import FakeCluster, NotFound  # noqa: E402
from kubeflow_tpu.runtime.sharding import ShardRouter  # noqa: E402
from kubeflow_tpu.scheduler.controller import (  # noqa: E402
    FLEET_KEY,
    SchedulerReconciler,
)
from kubeflow_tpu.scheduler.soak import make_pool  # noqa: E402

NS = "bench"
# Per-family worlds with EQUAL host-cell capacity per pool (16 hosts) and an
# equivalent gang host-count mix [1,1,2,2,4,16] — near-linear scaling needs
# balanced shards, and the router balances families by construction.
FAMILY_WORLDS = {
    "v4": ("4x4x4", ["2x2x1", "2x2x1", "2x2x2", "2x2x2", "2x2x4", "4x4x4"]),
    "v5p": ("4x4x4", ["2x2x1", "2x2x1", "2x2x2", "2x2x2", "2x2x4", "4x4x4"]),
    "v5e": ("8x16", ["2x4", "2x4", "4x4", "4x4", "4x8", "8x16"]),
    "v6e": ("8x16", ["2x4", "2x4", "4x4", "4x4", "4x8", "8x16"]),
}
FAMILIES = sorted(FAMILY_WORLDS)


# one recording-metrics shim and one percentile for both scheduler benches
from benchmarks.bench_scheduler import (  # noqa: E402
    _percentile,
    _RecordingMetrics,
)


def build_world(
    cluster: FakeCluster, gangs: int, pools_per_family: int, seed: int
) -> dict[str, int]:
    """The full mixed-family fleet + queue; returns gangs per family.

    Per-family RNG streams with the same seed: every family gets the
    IDENTICAL sequence of shape-mix indices and priorities, so the four
    shards' workloads are equal by construction — the sweep measures
    scaling, not gang-mix variance (the aggregate is gated on the slowest
    shard, so imbalance would read as lost scaling)."""
    rngs = {f: random.Random(seed) for f in FAMILIES}
    for fam in FAMILIES:
        pool_topo, _ = FAMILY_WORLDS[fam]
        for i in range(pools_per_family):
            make_pool(cluster, fam, pool_topo, f"pool-{fam}-{i}")
    per_family: dict[str, int] = {f: 0 for f in FAMILIES}
    for i in range(gangs):
        fam = FAMILIES[i % len(FAMILIES)]  # exactly balanced
        rng = rngs[fam]
        _, shapes = FAMILY_WORLDS[fam]
        nb = api.notebook(
            f"g{i}", NS,
            tpu_accelerator=fam,
            tpu_topology=shapes[rng.randrange(len(shapes))],
        )
        prio = rng.randrange(3)
        if prio:
            ko.set_annotation(nb, sched.PRIORITY_ANNOTATION, str(prio))
        cluster.create(nb)
        per_family[fam] += 1
    return per_family


def run_shard(
    shard_id: int,
    n_shards: int,
    *,
    gangs: int,
    pools_per_family: int,
    seed: int,
) -> dict:
    """Drain one shard's slice of the full world, isolated (the production
    layout is one shard per machine — see the methodology note above)."""
    cluster = FakeCluster()
    per_family = build_world(cluster, gangs, pools_per_family, seed)
    metrics = _RecordingMetrics()
    if n_shards <= 1:
        # SHARDS=1 is the unsharded reconciler — exactly what
        # build_managers ships at shards<=1 (no router, no stamps, no
        # selector scoping), so the 1-shard arm IS the single-loop
        # control plane the SCHED_BENCH baseline measures
        owned = gangs
        rec = SchedulerReconciler(metrics=metrics, clock=time.monotonic)
    else:
        router = ShardRouter(n_shards)
        families = router.families_for(shard_id)
        owned = sum(per_family[f] for f in families)
        rec = SchedulerReconciler(
            metrics=metrics, clock=time.monotonic,
            families=families, router=router, shard_id=shard_id,
        )

    bound_names: set[str] = set()

    def _on_event(event: str, obj: dict) -> None:
        if event == "DELETED":
            return
        anns = (obj.get("metadata") or {}).get("annotations") or {}
        if sched.PLACEMENT_ANNOTATION in anns:
            bound_names.add(ko.name(obj))

    cluster.watch("Notebook", _on_event)

    t0 = time.monotonic()
    remaining = owned
    while remaining > 0:
        before = len(metrics.bind_latencies)
        rec.reconcile(cluster, "", FLEET_KEY)
        if len(metrics.bind_latencies) == before and not bound_names:
            raise RuntimeError(
                f"shard {shard_id}/{n_shards} stalled with "
                f"{remaining} gangs unbound"
            )
        for name in sorted(bound_names):
            try:
                cluster.delete("Notebook", name, NS)
            except NotFound:
                pass
        remaining -= len(bound_names)
        bound_names.clear()
    wall = time.monotonic() - t0
    return {
        "shard": shard_id,
        "placements": owned,
        "wall_s": round(wall, 3),
        "cycles": metrics.cycles,
        "p99_bind_s": round(_percentile(metrics.bind_latencies, 0.99), 4),
    }


def run_sweep(
    shard_counts: list[int], *, gangs: int, pools_per_family: int, seed: int
) -> dict:
    sweep: dict[str, dict] = {}
    for n in shard_counts:
        shard_runs = [
            run_shard(
                i, n, gangs=gangs, pools_per_family=pools_per_family,
                seed=seed,
            )
            for i in range(n)
        ]
        total = sum(r["placements"] for r in shard_runs)
        if total != gangs:
            raise RuntimeError(
                f"partition incomplete at {n} shards: {total} != {gangs} "
                f"(a gang drained by zero or two shards)"
            )
        slowest = max(r["wall_s"] for r in shard_runs)
        sweep[str(n)] = {
            "aggregate_placements_per_s": round(gangs / slowest, 1),
            "sum_of_shard_pps": round(
                sum(r["placements"] / r["wall_s"] for r in shard_runs), 1
            ),
            "slowest_shard_wall_s": slowest,
            "shards": shard_runs,
        }
    base = sweep[str(shard_counts[0])]["aggregate_placements_per_s"]
    top = sweep[str(shard_counts[-1])]["aggregate_placements_per_s"]
    return {
        "bench": "SHARD_BENCH",
        "gangs": gangs,
        "pools_per_family": pools_per_family,
        "families": FAMILIES,
        "methodology": (
            "per-shard isolated runs over the full world; aggregate = "
            "total placements / slowest shard wall (one shard per machine)"
        ),
        "sweep": sweep,
        "scaling": round(top / base, 2) if base else 0.0,
        "scaling_span": f"{shard_counts[0]}->{shard_counts[-1]}",
    }


def check_against(
    result: dict,
    baseline_path: str,
    sched_baseline_path: str | None,
    tolerance: float,
) -> int:
    """CI perf gate (bench.yaml): near-linear scaling AND an unregressed
    1-shard fast path, against both committed baselines."""
    with open(baseline_path) as f:
        baseline = json.load(f)
    failures = []
    min_scaling = float(baseline.get("min_scaling", 3.0))
    if result["scaling"] < min_scaling:
        failures.append(
            f"scaling {result['scaling']}x < required {min_scaling}x "
            f"({result['scaling_span']} shards)"
        )
    one = result["sweep"].get("1", {}).get("aggregate_placements_per_s", 0.0)
    base_one = float(baseline["one_shard_placements_per_s"])
    if one < base_one * (1.0 - tolerance):
        failures.append(
            f"1-shard {one}/s regressed vs committed {base_one}/s "
            f"(floor {base_one * (1 - tolerance):.1f} at {tolerance:.0%})"
        )
    if sched_baseline_path:
        with open(sched_baseline_path) as f:
            sched_base = json.load(f)
        sched_pps = float(sched_base["placements_per_s"])
        # cross-check vs PR 8's pure-v4 SCHED_BENCH: different gang mix
        # (documented in shards_baseline.json), so the documented tolerance
        # is wider than the same-bench one
        sched_tol = float(baseline.get("sched_baseline_tolerance", 0.30))
        if one < sched_pps * (1.0 - sched_tol):
            failures.append(
                f"1-shard {one}/s out of tolerance with SCHED_BENCH "
                f"baseline {sched_pps}/s (floor "
                f"{sched_pps * (1 - sched_tol):.1f} at {sched_tol:.0%})"
            )
    for line in failures:
        print(f"SHARD_BENCH gate: {line}", file=sys.stderr)
    if failures:
        print(
            "PERF GATE FAILED: control-plane sharding no longer scales — "
            "either fix the regression or re-record "
            "benchmarks/shards_baseline.json with a justified new number",
            file=sys.stderr,
        )
        return 1
    print(
        f"SHARD_BENCH gate: scaling {result['scaling']}x "
        f"(>= {min_scaling}x), 1-shard {one}/s ok",
        file=sys.stderr,
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gangs", type=int, default=10_000,
                    help="queued gangs across all families (default 10000; "
                         "the ROADMAP-scale run uses 100000)")
    ap.add_argument("--pools-per-family", type=int, default=2,
                    help="16-host pools per accelerator family (default 2 "
                         "— 8 pools total, the SCHED_BENCH fleet size)")
    ap.add_argument("--sweep", default="1,2,4",
                    help="comma-separated shard counts (default 1,2,4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--check-against", metavar="BASELINE_JSON",
                    help="compare against the committed SHARD_BENCH "
                         "baseline and exit 1 on regression")
    ap.add_argument("--sched-baseline", metavar="SCHED_BASELINE_JSON",
                    help="also cross-check the 1-shard run against the "
                         "committed SCHED_BENCH baseline")
    ap.add_argument("--tolerance", type=float, default=0.20,
                    help="allowed fractional 1-shard regression for "
                         "--check-against (default 0.20)")
    args = ap.parse_args(argv)
    logging.disable(logging.ERROR)
    shard_counts = sorted({int(s) for s in args.sweep.split(",") if s})
    result = run_sweep(
        shard_counts, gangs=args.gangs,
        pools_per_family=args.pools_per_family, seed=args.seed,
    )
    print("SHARD_BENCH " + json.dumps(result, sort_keys=True))
    if args.check_against:
        return check_against(
            result, args.check_against, args.sched_baseline, args.tolerance
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
