#!/usr/bin/env python
"""Efficiency-ledger benchmark: attribution throughput and control-plane
overhead (docs/observability.md "efficiency ledger").

Two phases:

- **throughput** — the ledger alone over a large synthetic fleet (pools +
  bound gangs in a mix of running/starting/suspending/draining states, a
  fake telemetry source): gang-attributions/s and tick wall p50/p99. This
  is the number that bounds how big a fleet one ledger tick can account at
  a given cadence.
- **overhead A/B** — the same scheduler-driven world driven twice, with and
  without ledger ticks interleaved (the ``--no-ledger`` arm), at the drive
  loop's own pace. The overhead fraction must stay inside SCHED_BENCH's
  committed 20% tolerance: the ledger rides the nightly scheduler gate, so
  this bench failing means the accounting layer started eating the budget
  the bind path is gated on.

Per-run the conservation audit runs over the throughput phase's journal —
a perf run that mis-attributes is a failure, not a fast success.

    python benchmarks/bench_ledger.py                # full (CI) shape
    python benchmarks/bench_ledger.py --gangs 100 --ticks 20   # quick local

Emits one LEDGER_BENCH JSON line (CI artifacts / perf tracking).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from kubeflow_tpu import scheduler as sched  # noqa: E402
from kubeflow_tpu import sessions as sess  # noqa: E402
from kubeflow_tpu.api import types as api  # noqa: E402
from kubeflow_tpu.obs import timeline as tl  # noqa: E402
from kubeflow_tpu.obs.ledger import FleetEfficiencyLedger  # noqa: E402
from kubeflow_tpu.runtime.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.runtime.manager import Manager  # noqa: E402
from kubeflow_tpu.scheduler.controller import (  # noqa: E402
    SchedulerReconciler,
)
from kubeflow_tpu.scheduler.soak import make_pool  # noqa: E402
from kubeflow_tpu.utils.metrics import LedgerMetrics  # noqa: E402

NS = "bench"
OVERHEAD_TOLERANCE = 0.20  # SCHED_BENCH's committed gate tolerance


class _Clock:
    def __init__(self, start: float = 1_000_000.0) -> None:
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


class _FakeTelemetry:
    def __init__(self, duties: dict) -> None:
        self.duties = duties

    def activity(self, namespace: str, name: str):
        duty = self.duties.get(name)
        if duty is None:
            return None

        class _S:
            duty_cycle = duty

        return _S()


def _quantile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def build_world(gangs: int, seed: int = 7):
    """N pools of v4-4x4x4 (16 hosts each), gangs bound four-to-a-pool with
    a seeded state mix — the steady-state fleet a production tick sees."""
    rng = random.Random(seed)
    cluster = FakeCluster()
    pools = max(1, (gangs + 3) // 4)
    for p in range(pools):
        make_pool(cluster, "v4", "4x4x4", f"pool-{p:04d}")
    duties: dict[str, float] = {}
    offsets = [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]  # 2x2x4 carves
    for i in range(gangs):
        name = f"g{i:05d}"
        pool = f"pool-{i // 4:04d}"
        cluster.create(api.notebook(
            name, NS, tpu_accelerator="v4", tpu_topology="2x2x4"))
        slices = [{
            "pool": pool, "accelerator": "v4", "shape": [2, 2, 4],
            "offset": list(offsets[i % 4]), "poolTopology": "4x4x4",
            "nodes": [],
        }]
        anns = {
            sched.PLACEMENT_ANNOTATION: sched.encode_placement(slices, 1.0),
        }
        draw = rng.random()
        if draw < 0.70:  # running, mixed duty
            anns[tl.TIMELINE_ANNOTATION] = tl.encode_marks(
                {"requestedAt": 1.0, "runningAt": 2.0})
            duties[name] = rng.random()
        elif draw < 0.85:
            pass  # bound, not yet running: starting
        elif draw < 0.95:
            anns[sess.SUSPEND_ANNOTATION] = sess.encode_suspend_request(
                sess.REASON_PREEMPTION, 1_000_000.0, 3600.0)
        else:
            anns[api.STOP_ANNOTATION] = "2026-01-01T00:00:00Z"
        cluster.patch("Notebook", name, NS, {
            "metadata": {"annotations": anns}})
    return cluster, duties


def throughput_phase(gangs: int, ticks: int) -> dict:
    cluster, duties = build_world(gangs)
    clock = _Clock()
    ledger = FleetEfficiencyLedger(
        cluster, LedgerMetrics(), clock=clock, interval_s=1.0,
        telemetry=_FakeTelemetry(duties),
    )
    ledger.tick(force=True)  # anchor outside the timed window
    walls: list[float] = []
    t0 = time.perf_counter()
    for _ in range(ticks):
        clock.advance(15.0)
        w0 = time.perf_counter()
        ledger.tick(force=True)
        walls.append(time.perf_counter() - w0)
    wall = time.perf_counter() - t0
    violations = ledger.audit()
    if violations:
        for v in violations[:10]:
            print("AUDIT VIOLATION:", v, file=sys.stderr)
        raise SystemExit(1)
    return {
        "gangs": gangs,
        "ticks": ticks,
        "attributions_per_s": round(gangs * ticks / wall, 1),
        "tick_p50_ms": round(_quantile(walls, 0.50) * 1e3, 3),
        "tick_p99_ms": round(_quantile(walls, 0.99) * 1e3, 3),
        "audit": "clean",
    }


LEDGER_INTERVAL_S = 15.0   # the shipped default cadence
CYCLE_INTERVAL_S = 1.0     # SCHED_BENCH's drain granularity


def _build_unbound_world(gangs: int, seed: int = 11):
    """The SCHED_BENCH shape: pools + a cold queue of UNBOUND gangs the
    real scheduler drains — every cycle does genuine pack work, which is
    the denominator the 20% gate is committed against."""
    rng = random.Random(seed)
    cluster = FakeCluster()
    for p in range(max(1, gangs // 8)):
        make_pool(cluster, "v4", "4x4x4", f"pool-{p:04d}")
    shapes = ["2x2x1", "2x2x2", "2x2x4"]
    for i in range(gangs):
        cluster.create(api.notebook(
            f"g{i:05d}", NS, tpu_accelerator="v4",
            tpu_topology=shapes[rng.randrange(len(shapes))]))
    return cluster


def _drive_arm(gangs: int, *, with_ledger: bool) -> tuple[float, int]:
    """One SCHED_BENCH-shaped arm: the real scheduler drains a cold queue
    (bound gangs are completed-and-deleted each round, bench_scheduler's
    drain idiom, so every cycle does genuine pack work), the ledger (when
    armed) ticking at its TRUE relative cadence — one attribution per
    LEDGER_INTERVAL_S of virtual time against one scheduler pass per
    CYCLE_INTERVAL_S, the shipped loop ratio. Forcing a ledger tick per
    cycle would overstate its cadence ~15x and gate fiction. Returns
    (wall seconds, placements completed)."""
    from kubeflow_tpu.runtime.fake import NotFound

    cluster = _build_unbound_world(gangs)
    clock = _Clock()
    mgr = Manager(cluster, clock=clock)
    mgr.register(SchedulerReconciler(clock=clock, aging_interval_s=300.0))
    ledger = (
        FleetEfficiencyLedger(
            cluster, LedgerMetrics(), clock=clock,
            interval_s=LEDGER_INTERVAL_S,
        )
        if with_ledger
        else None
    )
    placed = 0
    t0 = time.perf_counter()
    for _ in range(gangs * 4):  # bound: a wedged queue must not spin forever
        if ledger is not None:
            ledger.tick()  # interval-gated: fires every 15 virtual seconds
        mgr.tick()
        done = [
            nb for nb in cluster.list("Notebook")
            if sched.placement_of(nb) is not None
        ]
        for nb in done:
            try:
                cluster.delete(
                    "Notebook", nb["metadata"]["name"],
                    nb["metadata"]["namespace"],
                )
            except NotFound:
                pass
        placed += len(done)
        if placed >= gangs:
            break
        clock.advance(CYCLE_INTERVAL_S)
    return time.perf_counter() - t0, placed


def overhead_phase(gangs: int, repeats: int) -> dict:
    # interleave arms to cancel machine drift; ignore a warmup pair
    _drive_arm(max(8, gangs // 4), with_ledger=True)
    _drive_arm(max(8, gangs // 4), with_ledger=False)
    with_l = without = 0.0
    placed_with = placed_without = 0
    for _ in range(repeats):
        w, p = _drive_arm(gangs, with_ledger=True)
        with_l += w
        placed_with += p
        w, p = _drive_arm(gangs, with_ledger=False)
        without += w
        placed_without += p
    pps_with = placed_with / with_l if with_l > 0 else 0.0
    pps_without = placed_without / without if without > 0 else 0.0
    overhead = (
        (pps_without - pps_with) / pps_without if pps_without > 0 else 0.0
    )
    return {
        "gangs": gangs,
        "repeats": repeats,
        "ledger_interval_s": LEDGER_INTERVAL_S,
        "placements_per_s_with_ledger": round(pps_with, 1),
        "placements_per_s_no_ledger": round(pps_without, 1),
        "overhead_fraction": round(overhead, 4),
        "tolerance": OVERHEAD_TOLERANCE,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gangs", type=int, default=400,
                    help="bound gangs in the throughput world (default 400)")
    ap.add_argument("--ticks", type=int, default=40,
                    help="ledger ticks to time (default 40)")
    ap.add_argument("--ab-gangs", type=int, default=200,
                    help="gangs drained in each overhead A/B arm "
                         "(default 200)")
    ap.add_argument("--ab-repeats", type=int, default=3,
                    help="interleaved A/B repetitions (default 3)")
    ap.add_argument("--no-gate", action="store_true",
                    help="report the overhead without failing on it")
    args = ap.parse_args(argv)
    logging.disable(logging.ERROR)

    result = {
        "bench": "LEDGER_BENCH",
        "throughput": throughput_phase(args.gangs, args.ticks),
        "overhead": overhead_phase(args.ab_gangs, args.ab_repeats),
    }
    print("LEDGER_BENCH " + json.dumps(result, sort_keys=True))
    overhead = result["overhead"]["overhead_fraction"]
    if not args.no_gate and overhead > OVERHEAD_TOLERANCE:
        print(
            f"LEDGER_BENCH gate: ledger overhead {overhead:.1%} exceeds the "
            f"{OVERHEAD_TOLERANCE:.0%} tolerance SCHED_BENCH is gated on",
            file=sys.stderr,
        )
        return 1
    print(
        f"LEDGER_BENCH gate: overhead {overhead:.1%} within "
        f"{OVERHEAD_TOLERANCE:.0%} "
        f"({result['throughput']['attributions_per_s']:.0f} "
        f"gang-attributions/s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
