"""MFU_BENCH: ResNet training throughput under the placement-derived mesh.

The SPMD runtime's promise (docs/spmd.md) is that a gang's mesh derives
deterministically from the placement cuboid alone. This bench closes the
loop from derivation to throughput: it derives a
:class:`kubeflow_tpu.spmd.mesh.DerivedMesh` from an (accelerator, topology,
numSlices) triple — the exact inputs a pod reads from its injected env —
builds the jax Mesh over that derivation's data-parallel projection
(``to_data_plan``: the ResNet cell has no model axis to feed, so the
intra-host block ZeRO-shards params instead), feeds it topology-aware
per-host batches (``spmd.mesh.per_host_batch``), and times the same train
step ``bench.py`` ships — then gates img/s/chip against the committed
``benchmarks/mfu_baseline.json``.

Multi-process is SIMULATED: every "host" of the gang lives in this one
process via ``--xla_force_host_platform_device_count`` (set before the
backend initializes), so the mesh spans num_hosts x chips_per_host forced
host devices and the program's collective structure — batch over
dcn x data x fsdp, per-layer param all-gathers over the intra-host block —
is exactly the real gang's. On a real slice each pod runs the same
derivation from its own env (``spmd.bootstrap.read_env``), calls
``jax.distributed.initialize(ctx.coordinator, ctx.num_processes,
ctx.process_id)`` first, and builds the identical mesh over the global
device list; that path is documented in docs/spmd.md "running under the
derived mesh" and exercised end-to-end by tests/test_distributed_e2e.py.

Two arms, one gate:
- ``single``: the same model on ONE device — the committed normalizer;
- ``mesh``:   the derived mesh over all num_devices devices.
The gate metric is the mesh arm's img/s/chip vs the committed baseline
(floor = baseline * (1 - tolerance)). CPU "chips" share the runner's cores,
so mesh-arm per-chip throughput sits well below the single arm — the
baseline records the actuals and scaling_efficiency is reported for
visibility, not gated. MFU itself is reported only when the device peak is
known (TPU generations); on CPU it is null and img/s/chip carries the gate.

Timing reuses the round-4 estimator (benchmarks/_timing.py: short/long
windows ending in one readback, min over repeats, rate from the
difference) without the phase-walk sleeps — this is a local backend, there
is no shared tunnel to dodge.

Prints ONE line: ``MFU_BENCH {json}``.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

# bf16 peak FLOP/s per chip by TPU generation (mirror of bench.py's table —
# bench.py stays single-file on purpose; change both)
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def chip_peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return None  # unknown (CPU sim): report null MFU, gate on img/s/chip


def _force_devices(n: int) -> None:
    """Ask XLA's host platform for n devices; must run before the backend
    initializes (importing jax is fine — clients are created lazily)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _flops_per_step(step, state, batch) -> float | None:
    """Compiler-reported FLOPs for one train step (the honest numerator for
    MFU — no analytic model-shape bookkeeping to drift)."""
    try:
        cost = step.lower(state, batch).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _measure_arm(mesh, *, per_arm_batch, image, k_inner, n_short, n_long,
                 repeats, seed):
    """Build the shipped ResNet train step on ``mesh`` and return
    (imgs_per_sec, flops_per_step). CPU-scale cell: ResNet-18 depths at
    width 16, 32px images — the conv/BN/optimizer structure of the headline
    bench at a size CI can afford."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from benchmarks._timing import min_window_step_seconds
    from kubeflow_tpu.models.resnet import ResNet18
    from kubeflow_tpu.parallel import mesh as meshlib
    from kubeflow_tpu.parallel.train import make_classifier_train_step

    model = ResNet18(num_classes=100, width=16, dtype=jnp.float32)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    bundle = make_classifier_train_step(model, tx, mesh)

    rng = np.random.default_rng(seed)
    batch = {
        "image": jnp.asarray(
            rng.standard_normal((per_arm_batch, image, image, 3)),
            jnp.float32,
        ),
        "label": jnp.asarray(rng.integers(0, 100, per_arm_batch), jnp.int32),
    }
    sh = {k: meshlib.batch_sharding(mesh) for k in batch}
    batch = jax.device_put(batch, sh)
    state = bundle.init(jax.random.PRNGKey(seed), batch)
    flops = _flops_per_step(bundle.step, state, batch)

    # K steps per dispatch over the SAME jitted step (bench.py's amortizer);
    # the scan body is unchanged HLO in a loop
    @functools.partial(jax.jit, donate_argnums=(0,))
    def multi_step(state, batch):
        def body(s, _):
            s2, metrics = bundle.step(s, batch)
            return s2, metrics["loss"]

        s, losses = jax.lax.scan(body, state, None, length=k_inner)
        return s, losses[-1]

    carry = {"state": state}

    def window(n: int) -> float:
        t = time.perf_counter()
        loss = None
        for _ in range(n):
            carry["state"], loss = multi_step(carry["state"], batch)
        float(loss)  # one readback per window; the fixed cost cancels
        return time.perf_counter() - t

    window(n_short)  # compile + warm
    window(n_long)
    sec_per_dispatch, _, _ = min_window_step_seconds(
        window, n_short, n_long, repeats
    )
    step_s = sec_per_dispatch / k_inner
    return per_arm_batch / step_s, flops


def run(args) -> dict:
    from kubeflow_tpu.spmd import mesh as spmd_mesh

    # derivation is pure python — do it before jax so the device count the
    # topology implies can still be forced onto the host platform
    dm = spmd_mesh.derive(args.accelerator, args.topology, args.num_slices)
    if not args.native:
        _force_devices(dm.num_devices)

    import jax

    from kubeflow_tpu.parallel import mesh as meshlib

    devices = jax.devices()
    if len(devices) < dm.num_devices:
        raise SystemExit(
            f"{args.accelerator}:{args.topology} x{args.num_slices} needs "
            f"{dm.num_devices} devices, have {len(devices)} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={dm.num_devices} "
            f"before the backend initializes (or pass a smaller --topology)"
        )
    devices = devices[: dm.num_devices]

    global_batch = args.per_chip_batch * dm.num_devices
    host_batch = spmd_mesh.per_host_batch(dm, global_batch)

    timing = dict(
        image=args.image, k_inner=args.k_inner, n_short=args.n_short,
        n_long=args.n_long, repeats=args.repeats, seed=args.seed,
    )
    single_ips, flops = _measure_arm(
        meshlib.create_mesh(meshlib.MeshPlan(data=1), devices[:1]),
        per_arm_batch=args.per_chip_batch, **timing,
    )
    mesh = spmd_mesh.build_mesh(dm, devices, data_parallel=True)
    mesh_ips, mesh_flops = _measure_arm(
        mesh, per_arm_batch=global_batch, **timing,
    )

    per_chip = mesh_ips / dm.num_devices
    peak = chip_peak_flops(devices[0])
    mfu = None
    if peak and mesh_flops:
        mfu = (mesh_flops / global_batch) * per_chip / peak

    return {
        "bench": "MFU_BENCH",
        "accelerator": dm.accelerator,
        "topology": dm.topology,
        "num_slices": dm.num_slices,
        "axes": dm.axes(),
        "n_devices": dm.num_devices,
        "global_batch": global_batch,
        "per_host_batch": host_batch,
        "per_chip_batch": args.per_chip_batch,
        "image": args.image,
        "imgs_per_sec_per_chip": round(per_chip, 2),
        "imgs_per_sec_per_chip_single": round(single_ips, 2),
        "scaling_efficiency": round(per_chip / single_ips, 4),
        "train_flops_per_image": round(mesh_flops / global_batch)
        if mesh_flops else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "backend": jax.default_backend(),
    }


def check_against(result: dict, baseline_path: str, tolerance: float) -> int:
    """CI perf gate: fail when the derived-mesh img/s/chip regressed beyond
    tolerance against the committed baseline (benchmarks/mfu_baseline.json).
    """
    with open(baseline_path) as f:
        baseline = json.load(f)
    base = float(baseline["imgs_per_sec_per_chip"])
    new = float(result["imgs_per_sec_per_chip"])
    floor = base * (1.0 - tolerance)
    verdict = "ok" if new >= floor else "REGRESSED"
    print(
        f"MFU_BENCH gate: {new:.1f} img/s/chip on the derived mesh vs "
        f"baseline {base:.1f} (floor {floor:.1f} at {tolerance:.0%} "
        f"tolerance) {verdict}",
        file=sys.stderr,
    )
    if verdict == "REGRESSED":
        print(
            "PERF GATE FAILED: ResNet throughput under the placement-derived "
            "mesh regressed — either fix the regression (mesh derivation, "
            "device ordering, train-step sharding) or re-record "
            "benchmarks/mfu_baseline.json with a justified new number",
            file=sys.stderr,
        )
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--accelerator", default="v4",
                    help="accelerator short name (default v4)")
    ap.add_argument("--topology", default="2x2x2",
                    help="slice chip cuboid, e.g. 2x2x2 (default: 8 chips "
                         "= 2 hosts x 4 chips — fits CI's 8 forced devices)")
    ap.add_argument("--num-slices", type=int, default=1)
    ap.add_argument("--per-chip-batch", type=int, default=8)
    ap.add_argument("--image", type=int, default=32,
                    help="image side (default 32: CPU-affordable cell)")
    ap.add_argument("--k-inner", type=int, default=4,
                    help="train steps per dispatch (scan length)")
    ap.add_argument("--n-short", type=int, default=1)
    ap.add_argument("--n-long", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--native", action="store_true",
                    help="don't force CPU host devices — run on whatever "
                         "backend jax picks (real-TPU path)")
    ap.add_argument("--check-against", metavar="BASELINE_JSON",
                    help="compare img/s/chip against a committed baseline "
                         "and exit 1 on regression beyond --tolerance")
    ap.add_argument("--tolerance", type=float, default=0.50,
                    help="allowed fractional regression for --check-against "
                         "(default 0.50 — CPU-sim noise band, see "
                         "benchmarks/mfu_baseline.json note)")
    args = ap.parse_args(argv)
    result = run(args)
    print("MFU_BENCH " + json.dumps(result, sort_keys=True))
    if args.check_against:
        return check_against(result, args.check_against, args.tolerance)
    return 0


if __name__ == "__main__":
    sys.exit(main())
