#!/usr/bin/env python
"""Control-plane latency benchmark: reconcile-duration and queue-wait
percentiles from the REAL histograms (docs/observability.md).

Drives N notebooks (mixed CPU/TPU) through the manager + fake kubelet to
convergence with ControlPlaneMetrics attached, then reads p50/p99 straight
off the ``controller_reconcile_duration_seconds`` histogram — the same
numbers a `histogram_quantile` query returns in production, so CI records a
control-plane latency trajectory PRs can be judged against.

    python benchmarks/bench_controlplane.py              # 200 notebooks
    python benchmarks/bench_controlplane.py --notebooks 50

Emits one CONTROLPLANE_BENCH JSON line (consumed by CI artifacts).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from kubeflow_tpu.api import types as api  # noqa: E402
from kubeflow_tpu.controllers.notebook_controller import (  # noqa: E402
    NotebookReconciler,
)
from kubeflow_tpu.controllers.profile_controller import (  # noqa: E402
    ProfileReconciler,
)
from kubeflow_tpu.runtime.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.runtime.manager import Manager  # noqa: E402
from kubeflow_tpu.utils.config import ControllerConfig  # noqa: E402
from kubeflow_tpu.utils.metrics import ControlPlaneMetrics  # noqa: E402
from kubeflow_tpu.webhooks import tpu_env  # noqa: E402

NS = "bench"


def run(notebooks: int) -> dict:
    cluster = FakeCluster()
    tpu_env.install(cluster)
    cluster.add_tpu_node_pool("v4", "2x2x2")
    metrics = ControlPlaneMetrics()
    # real wall clock (as cmd/controller.py wires it): without it the
    # manager's virtual clock never advances and every queue-wait reads 0
    mgr = Manager(cluster, clock=time.time, metrics=metrics)
    mgr.register(NotebookReconciler(ControllerConfig()))
    mgr.register(ProfileReconciler())
    cluster.create(api.profile(NS, owner_name="bench@example.com"))
    for i in range(notebooks):
        kwargs = (
            dict(tpu_accelerator="v4", tpu_topology="2x2x2")
            if i % 4 == 0
            else {}
        )
        cluster.create(api.notebook(f"nb-{i}", NS, **kwargs))
    cluster.settle(mgr, rounds=6)

    h = metrics.reconcile_duration
    qw = metrics.queue_wait
    return {
        "bench": "CONTROLPLANE_BENCH",
        "notebooks": notebooks,
        "reconciles": int(h.count(kind="Notebook")),
        "reconcile_duration_s": {
            "p50": round(h.quantile(0.50, kind="Notebook"), 5),
            "p99": round(h.quantile(0.99, kind="Notebook"), 5),
            "mean": round(
                h.sum(kind="Notebook") / max(1, h.count(kind="Notebook")), 5
            ),
        },
        "queue_wait_s": {
            "p50": round(qw.quantile(0.50), 5),
            "p99": round(qw.quantile(0.99), 5),
            "samples": int(qw.count()),
        },
        "outcomes": {
            s["labels"]["outcome"]: int(s["value"])
            for s in metrics.reconcile_total.samples()
            if s["labels"]["kind"] == "Notebook"
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--notebooks", type=int, default=200)
    args = ap.parse_args(argv)
    logging.disable(logging.ERROR)
    print("CONTROLPLANE_BENCH " + json.dumps(run(args.notebooks), sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
