#!/usr/bin/env python
"""Startup-latency benchmark: click-to-ready percentiles and the per-phase
breakdown off the REAL SLO histograms (docs/observability.md).

Drives N TPU gangs through spawner-stamped timelines — request → scheduler
queue → bind → pod start → ready — on the virtual clock against a fleet
sized to hold K gangs at once, so the queue phase carries real contention.
Then reads p50/p99 straight off ``session_startup_seconds`` and the
dominant-phase attribution off ``session_startup_phase_seconds`` — the same
numbers a `histogram_quantile` query returns in production, so CI records a
startup-latency trajectory PRs can be judged against.

    python benchmarks/bench_timeline.py                 # 60 gangs
    python benchmarks/bench_timeline.py --notebooks 20

Emits one STARTUP_BENCH JSON line (consumed by CI artifacts).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from kubeflow_tpu.api import types as api  # noqa: E402
from kubeflow_tpu.controllers.notebook_controller import (  # noqa: E402
    NotebookReconciler,
)
from kubeflow_tpu.obs.slo import SLOMetrics  # noqa: E402
from kubeflow_tpu.obs.timeline import (  # noqa: E402
    TIMELINE_ANNOTATION,
    TimelineRecorder,
    audit_timeline,
    encode_marks,
)
from kubeflow_tpu.runtime import objects as ko  # noqa: E402
from kubeflow_tpu.runtime.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.runtime.manager import Manager  # noqa: E402
from kubeflow_tpu.scheduler.controller import SchedulerReconciler  # noqa: E402
from kubeflow_tpu.scheduler.soak import make_pool  # noqa: E402
from kubeflow_tpu.utils.config import ControllerConfig  # noqa: E402
from kubeflow_tpu.webhooks import tpu_env  # noqa: E402

NS = "bench"
PHASES = ("requested", "created", "queued", "bound", "pods-starting",
          "restoring", "running")


class _Clock:
    def __init__(self, t: float = 1_000_000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def run(notebooks: int, pools: int = 4) -> dict:
    cluster = FakeCluster()
    tpu_env.install(cluster)
    for i in range(pools):  # each pool holds exactly one 2x2x2 gang
        make_pool(cluster, "v4", "2x2x2", f"pool-{i}")
    clock = _Clock()
    slo = SLOMetrics(clock=clock, target_s=300.0)
    mgr = Manager(cluster, clock=clock)
    cfg = ControllerConfig(scheduler_enabled=True)
    mgr.register(NotebookReconciler(
        cfg, clock=clock, timeline=TimelineRecorder(slo=slo, clock=clock),
    ))
    mgr.register(SchedulerReconciler(clock=clock, aging_interval_s=300.0))

    done: set[str] = set()
    for i in range(notebooks):
        nb = api.notebook(
            f"nb-{i}", NS, tpu_accelerator="v4", tpu_topology="2x2x2"
        )
        # the spawner's origin stamp: the click is the timeline's t0
        ko.set_annotation(
            nb, TIMELINE_ANNOTATION, encode_marks({"requestedAt": clock.t})
        )
        cluster.create(nb)
    ticks = 0
    # run gangs through to ready, stopping each once measured so its pool
    # frees for the next — the queue phase accrues real contention
    while len(done) < notebooks and ticks < notebooks * 30:
        ticks += 1
        cluster.step_kubelet()
        mgr.run_until_idle()
        for i in range(notebooks):
            name = f"nb-{i}"
            if name in done:
                continue
            nb = cluster.try_get("Notebook", name, NS)
            if nb is None:
                continue
            from kubeflow_tpu.obs.timeline import marks_of

            if "runningAt" in marks_of(nb):
                done.add(name)
                cluster.patch("Notebook", name, NS, {
                    "metadata": {"annotations": {
                        api.STOP_ANNOTATION: "2026-01-01T00:00:00Z"}}})
        clock.advance(7.0)

    violations = audit_timeline(cluster)
    h = slo.startup_total
    phase_h = slo.startup_phase
    slo.refresh()
    return {
        "bench": "STARTUP_BENCH",
        "notebooks": notebooks,
        "pools": pools,
        "measured": int(h.count()),
        "click_to_ready_s": {
            "p50": round(h.quantile(0.50), 3),
            "p99": round(h.quantile(0.99), 3),
            "mean": round(h.sum() / max(1, h.count()), 3),
        },
        "phase_mean_s": {
            p: round(
                phase_h.sum(phase=p) / max(1, phase_h.count(phase=p)), 3
            )
            for p in PHASES
            if phase_h.count(phase=p)
        },
        "slo": {
            "target_s": slo.target_s,
            "within_target": int(slo.startups.get(within_target="true")),
            "breaches": int(slo.startups.get(within_target="false")),
            "budget_remaining": round(
                slo.error_budget_remaining.get(), 4
            ),
        },
        "timeline_audit_violations": len(violations),
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--notebooks", type=int, default=60)
    ap.add_argument("--pools", type=int, default=4)
    args = ap.parse_args(argv)
    logging.disable(logging.ERROR)
    result = run(args.notebooks, args.pools)
    print("STARTUP_BENCH " + json.dumps(result, sort_keys=True))
    if result["measured"] < args.notebooks:
        print(
            f"WARNING: only {result['measured']}/{args.notebooks} gangs "
            f"reached ready", file=sys.stderr,
        )
        return 1
    return 0 if result["timeline_audit_violations"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
