"""Standalone probe: Pallas fused (BN+relu)-backward + 1x1-conv dgrad/wgrad.

Computes, in one pass over the activations (tiled over rows):
    db   = dr * relu_mask            (relu mask from bn-out recomputed)
    dy   = (gamma*inv) * (db - mean_db - xhat * mean_db_xhat)
    dX   = dy @ W.T   (+ optional residual-grad add-in)
    dW   = X.T @ dy   (accumulated in VMEM f32)
vs the same math in plain XLA ops. Shapes: the bench's hottest unit
(stage2_block1/conv1: N=256*56*56, Ci=256, Co=128).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 256 * 56 * 56
CI = 256
CO = 128
TN = 2048


def bwd_kernel(dr_ref, y_ref, x_ref, wt_ref, scal_ref, dx_ref, dw_ref, acc_ref):
    # scal_ref rows: 0 gamma*inv, 1 mean, 2 inv, 3 beta_eff(gamma,beta),
    #               4 mean_db, 5 mean_db_xhat, 6 gamma   (all f32 [7, CO])
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    y = y_ref[:].astype(jnp.float32)
    xhat = (y - scal_ref[1, :]) * scal_ref[2, :]
    mask = (xhat * scal_ref[6, :] + scal_ref[3, :]) > 0
    db = jnp.where(mask, dr_ref[:].astype(jnp.float32), 0.0)
    dy = scal_ref[0, :] * (db - scal_ref[4, :] - xhat * scal_ref[5, :])
    dy16 = dy.astype(jnp.bfloat16)
    dx_ref[:] = jnp.dot(
        dy16, wt_ref[:], preferred_element_type=jnp.float32
    ).astype(jnp.bfloat16)
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], dy16,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[:] = acc_ref[:]


@jax.jit
def pallas_bwd(dr, y, x, wt, scal):
    return pl.pallas_call(
        bwd_kernel,
        grid=(N // TN,),
        in_specs=[
            pl.BlockSpec((TN, CO), lambda i: (i, 0)),
            pl.BlockSpec((TN, CO), lambda i: (i, 0)),
            pl.BlockSpec((TN, CI), lambda i: (i, 0)),
            pl.BlockSpec((CO, CI), lambda i: (0, 0)),
            pl.BlockSpec((7, CO), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((TN, CI), lambda i: (i, 0)),
            pl.BlockSpec((CI, CO), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, CI), jnp.bfloat16),
            jax.ShapeDtypeStruct((CI, CO), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((CI, CO), jnp.float32)],
    )(dr, y, x, wt, scal)


@jax.jit
def xla_bwd(dr, y, x, wt, scal):
    yf = y.astype(jnp.float32)
    xhat = (yf - scal[1, :]) * scal[2, :]
    db = jnp.where(xhat * scal[6, :] + scal[3, :] > 0, dr.astype(jnp.float32), 0.0)
    dy = scal[0, :] * (db - scal[4, :] - xhat * scal[5, :])
    dy16 = dy.astype(jnp.bfloat16)
    dx = jnp.dot(dy16, wt)
    dw = jax.lax.dot_general(
        x, dy16, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return dx, dw


def timeit(f, args, label):
    """Chain the op inside a device-side scan so each iteration differs and
    per-call host effects cancel; subtract two scan lengths to drop the fixed
    sync cost. The chain adds one identical slice per iter to both variants."""
    dr, y, x, wt, scal = args

    def make_loop(steps):
        @jax.jit
        def loop(dr, y, x, wt, scal):
            def body(yc, _):
                dx, dw = f(dr, yc, x, wt, scal)
                return dx[:, :CO], dw[0, 0]
            yout, dws = jax.lax.scan(body, y, None, length=steps)
            return dws[-1]

        return loop

    short, long_ = make_loop(3), make_loop(13)
    float(short(dr, y, x, wt, scal))
    float(long_(dr, y, x, wt, scal))
    best = float("inf")
    for _ in range(3):
        t = time.perf_counter()
        float(short(dr, y, x, wt, scal))
        t3 = time.perf_counter() - t
        t = time.perf_counter()
        float(long_(dr, y, x, wt, scal))
        t13 = time.perf_counter() - t
        best = min(best, (t13 - t3) / 10)
    gb = (N * (CO + CO + CI) * 2 + N * CI * 2) / 1e9
    print(f"{label}: {best*1000:.2f} ms  {gb/best:.0f} GB/s effective (incl chain slice)")
    return best


def main():
    rng = np.random.default_rng(0)
    dr = jnp.asarray(rng.standard_normal((N, CO)), jnp.bfloat16)
    y = jnp.asarray(rng.standard_normal((N, CO)), jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((N, CI)), jnp.bfloat16)
    wt = jnp.asarray(rng.standard_normal((CO, CI)), jnp.bfloat16)
    scal = jnp.asarray(rng.standard_normal((7, CO)), jnp.float32)

    # correctness
    dx_p, dw_p = pallas_bwd(dr, y, x, wt, scal)
    dx_x, dw_x = xla_bwd(dr, y, x, wt, scal)
    err_dx = float(jnp.max(jnp.abs(dx_p.astype(jnp.float32) - dx_x.astype(jnp.float32))))
    err_dw = float(jnp.max(jnp.abs(dw_p - dw_x))) / float(jnp.max(jnp.abs(dw_x)))
    print(f"max|dX err|={err_dx:.4f}  rel|dW err|={err_dw:.6f}")

    timeit(pallas_bwd, (dr, y, x, wt, scal), "pallas fused bwd")
    timeit(xla_bwd, (dr, y, x, wt, scal), "xla same math   ")


if __name__ == "__main__":
    main()
