"""Capture an XLA trace of the bench train step and dump the op breakdown."""
import glob
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.models.resnet import ResNet50
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel.train import make_classifier_train_step

LOGDIR = "/tmp/bench_trace"
BATCH = 32


def main():
    mesh = meshlib.create_mesh(meshlib.MeshPlan(data=1))
    model = ResNet50(num_classes=1000)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    bundle = make_classifier_train_step(model, tx, mesh)
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.standard_normal((BATCH, 224, 224, 3)), jnp.bfloat16),
        "label": jnp.asarray(rng.integers(0, 1000, BATCH), jnp.int32),
    }
    sh = {k: meshlib.batch_sharding(mesh) for k in batch}
    batch = jax.device_put(batch, sh)
    state = bundle.init(jax.random.PRNGKey(0), batch)
    for _ in range(3):
        state, metrics = bundle.step(state, batch)
    float(metrics["loss"])

    jax.profiler.start_trace(LOGDIR)
    for _ in range(3):
        state, metrics = bundle.step(state, batch)
    float(metrics["loss"])
    jax.profiler.stop_trace()

    files = glob.glob(f"{LOGDIR}/**/*.xplane.pb", recursive=True)
    print("TRACE FILES:", files)


if __name__ == "__main__":
    main()
