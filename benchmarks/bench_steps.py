#!/usr/bin/env python
"""Gang step-telemetry benchmark: aggregation throughput + pass latency over
a large multi-host fleet (docs/observability.md "gang step telemetry").

Builds N multi-host gangs (v4 4x4x2 = 8 hosts each by default, so ~200
gangs is ~1600 per-host step streams), each host backed by a fake in-pod
agent with a seeded step schedule, then drives the gang aggregator through
M full parallel passes on a virtual clock. Reports hosts/second of
aggregation throughput and the pass p50/p99 read straight off the REAL
``tpu_gang_pass_seconds`` histogram — the same numbers a
``histogram_quantile`` query returns in production.

A slice of the fleet carries planted culprits (slow / lagging / stalled
hosts, one per planted gang); the run FAILS — regardless of speed — unless
the aggregator names exactly the planted hosts and every claim re-proves
from its own evidence, so a fast-but-wrong aggregation can never pass.

    python benchmarks/bench_steps.py                  # 200 gangs x 8 hosts
    python benchmarks/bench_steps.py --gangs 50 --passes 5
    python benchmarks/bench_steps.py \\
        --check-against benchmarks/steps_baseline.json   # CI gate

Emits one STEP_BENCH JSON line (consumed by CI artifacts).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from kubeflow_tpu.api import types as api  # noqa: E402
from kubeflow_tpu.culler.probe import ProbeResult  # noqa: E402
from kubeflow_tpu.runtime import objects as ko  # noqa: E402
from kubeflow_tpu.runtime.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.telemetry.agent import (  # noqa: E402
    FakeDeviceBackend,
    FakeStepSchedule,
    TelemetryAgent,
)
from kubeflow_tpu.telemetry.gang import (  # noqa: E402
    GangTelemetryAggregator,
    audit_gang_attribution,
    host_key,
)
from kubeflow_tpu.utils.metrics import GangMetrics  # noqa: E402
from kubeflow_tpu.webhooks import tpu_env  # noqa: E402

NS = "bench"
# one planted culprit per PLANT_EVERY gangs, shapes rotating
PLANT_EVERY = 20
SHAPES = (
    ("straggler", dict(slow_factor=2.0)),
    ("desync", dict(behind_steps=15)),
    ("stall", dict(stall_after=5)),
)


class _Clock:
    """Virtual time drives the step schedules (deterministic streams);
    wall time is only measured around the pass itself."""

    def __init__(self, start: float = 1_000_000.0) -> None:
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


def run(gangs: int, passes: int, topology: str) -> dict:
    clock = _Clock()
    cluster = FakeCluster()
    tpu_env.install(cluster)
    agents: dict[str, TelemetryAgent] = {}
    planted: dict[tuple[str, str], dict] = {}
    num_hosts = 0
    for i in range(gangs):
        name = f"g-{i}"
        nb = api.notebook(
            name, NS, tpu_accelerator="v4", tpu_topology=topology
        )
        cluster.create(nb)
        topo = api.notebook_topology(nb)
        num_hosts = topo.num_hosts
        plant_host = None
        shape: dict = {}
        if i % PLANT_EVERY == 0:
            kind, shape = SHAPES[(i // PLANT_EVERY) % len(SHAPES)]
            plant_host = (i // PLANT_EVERY) % topo.num_hosts
            planted[(NS, name)] = {
                "kind": kind,
                "host": host_key(name, 0, plant_host, 1),
            }
        for o in range(topo.num_hosts):
            agents[host_key(name, 0, o, 1)] = TelemetryAgent(
                FakeDeviceBackend(
                    duty_cycle=0.9,
                    hbm_used_bytes=8e9,
                    jitter=0.01,
                    seed=i * 100 + o,
                ),
                clock=clock,
                step_schedule=FakeStepSchedule(
                    period_s=6.0,
                    duration_s=2.5,
                    start_at=clock() - 200.0,
                    jitter_s=0.15,
                    seed=i * 100 + o,
                    **(shape if o == plant_host else {}),
                ),
            )

    def probe(targets, timeout=5.0, max_concurrency=64):
        # agents answer in-process: the number under test is the
        # aggregator's own pass cost (parse + align + judge + aggregate),
        # the same work it does behind the native prober in production
        return [
            ProbeResult(200, agents[host].exposition())
            for host, _port, _path in targets
        ]

    agg = GangTelemetryAggregator(
        cluster,
        GangMetrics(),
        min_steps=3,
        desync_steps=10,
        stall_after_s=45.0,
        clock=clock,
        probe_fn=probe,
        target_for=lambda nb, j, o: (host_key(ko.name(nb), j, o, 1), 0, "/"),
    )
    t0 = time.perf_counter()
    for _ in range(passes):
        agg.collect(force=True)
        # enough virtual time that every pass sees fresh completed steps
        # (and the planted stalls accrue quiet time past the threshold)
        clock.advance(15.0)
    wall = time.perf_counter() - t0

    # correctness arm: the attribution + evidence audits must come back
    # clean — a fast-but-wrong aggregation fails here before any gate
    audit = agg.audit(where="bench") + audit_gang_attribution(
        agg, planted, where="bench"
    )
    named = {
        (f["namespace"], f["notebook"]) for f in agg.findings()
    } & set(planted)
    h = agg.metrics.pass_duration
    return {
        "bench": "STEP_BENCH",
        "gangs": gangs,
        "hosts_per_gang": num_hosts,
        "passes": passes,
        "hosts_scraped": agg.hosts_scraped,
        "host_throughput_per_s": round(
            agg.hosts_scraped / max(wall, 1e-9), 1
        ),
        "pass_seconds": {
            "p50": round(h.quantile(0.50), 5),
            "p99": round(h.quantile(0.99), 5),
            "mean": round(h.sum() / max(1, h.count()), 5),
        },
        "tracked_gangs": int(agg.metrics.gangs.get()),
        "fleet_step_p99_s": round(agg.fleet_step_p99(), 3),
        "planted": len(planted),
        "planted_named": len(named),
        "audit_violations": audit,
    }


def check_against(result: dict, baseline_path: str, tolerance: float) -> int:
    """CI gate: aggregation throughput must not fall below the committed
    floor and the pass p99 must not blow past its ceiling (tolerance
    absorbs shared-runner wall noise; losing the single-pass aggregation
    is an order-of-magnitude cliff that no tolerance covers). Correctness
    — every planted culprit named, zero audit violations — is a hard
    gate with no tolerance at all."""
    with open(baseline_path) as f:
        base = json.load(f)
    failures = []
    if result["audit_violations"]:
        failures += [f"audit: {v}" for v in result["audit_violations"]]
    if result["planted_named"] != result["planted"]:
        failures.append(
            f"planted culprits named: {result['planted_named']} of "
            f"{result['planted']} — the judge lost real stragglers"
        )
    floor = base["host_throughput_per_s"] * (1.0 - tolerance)
    if result["host_throughput_per_s"] < floor:
        failures.append(
            f"host_throughput_per_s: {result['host_throughput_per_s']} < "
            f"floor {floor:.1f} (baseline "
            f"{base['host_throughput_per_s']} - {tolerance:.0%})"
        )
    ceiling = base["pass_seconds"]["p99"] * (1.0 + tolerance)
    if result["pass_seconds"]["p99"] > ceiling:
        failures.append(
            f"pass p99: {result['pass_seconds']['p99']}s > ceiling "
            f"{ceiling:.5f}s (baseline {base['pass_seconds']['p99']}s "
            f"+ {tolerance:.0%})"
        )
    if failures:
        print("STEP_BENCH gate: FAIL")
        for f_ in failures:
            print(f"  {f_}")
        return 1
    print(
        f"STEP_BENCH gate: OK ({result['host_throughput_per_s']} hosts/s "
        f"vs baseline {base['host_throughput_per_s']}; pass p99 "
        f"{result['pass_seconds']['p99']}s <= {ceiling:.5f}s; "
        f"{result['planted_named']}/{result['planted']} culprits named)"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gangs", type=int, default=200)
    ap.add_argument("--passes", type=int, default=10)
    ap.add_argument("--topology", default="4x4x2",
                    help="per-gang v4 topology (default 4x4x2 = 8 hosts)")
    ap.add_argument("--check-against", metavar="BASELINE_JSON",
                    help="compare against a committed baseline and exit 1 "
                         "on regression beyond --tolerance (correctness "
                         "failures gate unconditionally)")
    ap.add_argument("--tolerance", type=float, default=0.50,
                    help="relative band for the throughput floor and pass "
                         "p99 ceiling (default 0.50)")
    args = ap.parse_args(argv)
    logging.disable(logging.ERROR)
    result = run(args.gangs, args.passes, args.topology)
    print("STEP_BENCH " + json.dumps(result, sort_keys=True))
    if args.check_against:
        return check_against(result, args.check_against, args.tolerance)
    if result["audit_violations"] or result["planted_named"] != result["planted"]:
        print("STEP_BENCH correctness: FAIL")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
