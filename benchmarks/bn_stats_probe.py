"""Probe: XLA BN-stats reduction vs a Pallas channel-moments kernel.

The round-3 device trace (BASELINE.md "ResNet step anatomy") showed the
BatchNorm-statistics pass (`convert_reduce_fusion`) at 1.33 ms/step = 26% of
the ResNet step, with the stem tensor's reduce running at ~82 GB/s — far off
the ~750 GB/s streaming bandwidth. This probe measures, per ResNet activation
shape, XLA's (sum, sumsq) channel reduction against a Pallas kernel that
streams the tensor once and accumulates per-channel f32 moments in VMEM.

Timing: K reduction passes inside ONE dispatch via lax.scan (per-dispatch
overhead would swamp a ~30 us kernel), with a scalar carry multiplied into the
input INSIDE the single pass (fuses into the read for XLA; an SMEM scalar for
Pallas) so loop-invariant code motion can't hoist the work. Short/long window
differencing cancels the tunnel's fixed readback cost.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SHAPES = [  # the ResNet-50 batch-16 activation zoo (NHWC)
    (16, 112, 112, 64),
    (16, 56, 56, 64),
    (16, 56, 56, 256),
    (16, 28, 28, 512),
    (16, 14, 14, 1024),
    (16, 7, 7, 2048),
]


def xla_moments(x, c):
    xf = x.astype(jnp.float32) * c
    return jnp.sum(xf, axis=(0, 1, 2)), jnp.sum(xf * xf, axis=(0, 1, 2))


def _moments_kernel(c_ref, x_ref, sum_ref, sq_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        sq_ref[:] = jnp.zeros_like(sq_ref)

    xf = x_ref[:].astype(jnp.float32) * c_ref[0]
    sum_ref[:] += jnp.sum(xf, axis=0, keepdims=True)
    sq_ref[:] += jnp.sum(xf * xf, axis=0, keepdims=True)


def pick_block_rows(m: int, ch: int) -> int:
    """The library's divisor search (ops/bn_pallas.py) with the probe's
    larger VMEM budget — one implementation of the Mosaic sublane
    constraint, not two drifting copies."""
    from kubeflow_tpu.ops.bn_pallas import _pick_block_rows

    return _pick_block_rows(m, ch, budget_bytes=4 << 20)


def pallas_moments(x, c, block_rows=None):
    n, h, w, ch = x.shape
    m = n * h * w
    x2 = x.reshape(m, ch)
    if block_rows is None:
        block_rows = pick_block_rows(m, ch)
    assert m % block_rows == 0, (m, block_rows)
    grid = (m // block_rows,)
    s, q = pl.pallas_call(
        _moments_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block_rows, ch), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, ch), lambda i: (0, 0)),
            pl.BlockSpec((1, ch), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, ch), jnp.float32),
            jax.ShapeDtypeStruct((1, ch), jnp.float32),
        ),
    )(jnp.reshape(c, (1,)), x2)
    return s[0], q[0]


def make_looped(fn, x, k):
    @jax.jit
    def run(c0):
        def body(c, _):
            s, q = fn(x, c)
            # fold the result back into the carry: a true data dependency
            return 1.0 + 0.0 * s[0], None

        c, _ = jax.lax.scan(body, c0, None, length=k)
        return c

    return run


def timeit(fn, x, k=512, repeats=6):
    short = make_looped(fn, x, k)
    long_ = make_looped(fn, x, 3 * k)
    float(short(jnp.float32(1.0)))  # compile
    float(long_(jnp.float32(1.0)))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(short(jnp.float32(1.0)))
        t1 = time.perf_counter()
        float(long_(jnp.float32(1.0)))
        t2 = time.perf_counter()
        per = ((t2 - t1) - (t1 - t0)) / (2 * k)
        best = min(best, per)  # stalls are additive; min is the honest time
    return best


def main():
    rng = np.random.default_rng(0)
    print(f"{'shape':>22} {'MB':>6} {'xla':>9} {'pallas':>9} {'x GB/s':>7} {'p GB/s':>7}")
    for shape in SHAPES:
        x = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        nbytes = x.size * 2
        t_x = timeit(xla_moments, x)
        t_p = timeit(pallas_moments, x)
        one = jnp.float32(1.0)
        s1, q1 = jax.jit(xla_moments)(x, one)
        s2, q2 = jax.jit(pallas_moments)(x, one)
        rel = float(jnp.max(jnp.abs(s1 - s2) / (jnp.abs(s1) + 1.0)))
        print(
            f"{str(shape):>22} {nbytes/1e6:5.1f}M {t_x*1e6:8.1f}u {t_p*1e6:8.1f}u "
            f"{nbytes/t_x/1e9:7.0f} {nbytes/t_p/1e9:7.0f}  rel={rel:.1e}"
        )


if __name__ == "__main__":
    main()
